"""Train-MFU ablations: donation-amortized scan timing, optimizer variants."""
import dataclasses, functools
import numpy as np, jax, jax.numpy as jnp, optax
from jax import lax
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_125M, Transformer, fused_next_token_loss)
from learning_jax_sharding_tpu.ops.flash_attention import make_flash_attn_fn
from learning_jax_sharding_tpu.parallel import build_mesh, mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP, activate
from learning_jax_sharding_tpu.training.pipeline import make_train_step, sharded_train_state
from learning_jax_sharding_tpu.utils.bench import time_fn

mesh = build_mesh((1, 1), ("data", "model"))
b, s = 8, 1024
cfg = dataclasses.replace(CONFIG_125M, attn_fn=make_flash_attn_fn())
model = Transformer(cfg)
rng = np.random.default_rng(0)
tokens = rng.integers(0, cfg.vocab_size, size=(b, s + 1)).astype(np.int32)
sh = mesh_sharding(mesh, "data", None)
batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
FLOPS = cfg.train_step_flops(b, s)

def report(tag, secs):
    print(f"{tag}: {secs*1e3:.2f} ms/step, {FLOPS/secs/1e12:.1f} TFLOP/s, MFU={FLOPS/secs/197e12:.1%}", flush=True)

def loss_of(params, bt):
    hidden = model.apply({"params": params}, bt["inputs"], return_hidden=True)
    return fused_next_token_loss(hidden, bt, params)

def scan_step_time(opt, tag, k=4, compiler_options=None):
    state, _ = sharded_train_state(
        model, opt, batch["inputs"], {"params": jax.random.key(0)}, mesh, RULES_DP_TP)
    def body(st, _):
        grads = jax.grad(lambda p: loss_of(p, batch))(st.params)
        return st.apply_gradients(grads=grads), None
    def many(st):
        st, _ = lax.scan(body, st, None, length=k)
        return st
    with activate(mesh, RULES_DP_TP):
        j = jax.jit(many, compiler_options=compiler_options)
        secs = time_fn(j, state, min_time=2.0) / k
    report(tag, secs)
    del state
    return secs

# 1. current bench config: single step, no donation
state, state_sh = sharded_train_state(
    model, optax.adamw(3e-4), batch["inputs"], {"params": jax.random.key(0)}, mesh, RULES_DP_TP)
step = make_train_step(
    state_sh, {k: v.sharding for k, v in batch.items()}, mesh, RULES_DP_TP,
    loss_fn=fused_next_token_loss, loss_needs_params=True,
    apply_kwargs={"return_hidden": True}, donate_state=False)
report("single-step no-donate (r1 bench)", time_fn(step, state, batch, min_time=2.0))
del state

# 2. scanned steps (in-place state, the real training regime)
scan_step_time(optax.adamw(3e-4), "scan x4 adamw fp32")
# 3. bf16 first moment
scan_step_time(optax.adamw(3e-4, mu_dtype=jnp.bfloat16), "scan x4 adamw mu=bf16")
# 4. bigger scoped vmem for fusions
scan_step_time(optax.adamw(3e-4), "scan x4 adamw + vmem64M",
               compiler_options={"xla_tpu_scoped_vmem_limit_kib": 65536})
