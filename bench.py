#!/usr/bin/env python
"""Round benchmark: case-6 attention throughput on real TPU hardware.

Prints ONE JSON line:
    {"metric": "case6_attention_tflops_per_chip", "value": N,
     "unit": "TFLOP/s/chip", "vs_baseline": R}

* The workload is the reference's case-6 configuration — multi-head attention
  at B=8, S=256, M=640, 8 heads × 64 (`/root/reference/case6_attention.py:44-45,
  149-151`) — measured with a correct harness (warmup excluded, devices
  synced; the reference's own loop at `case6_attention.py:234-238` has neither).
* ``value`` is this framework's TPU-native path: bf16 compute, fp32-upcast
  softmax, K forward applications chained inside one jitted program so device
  time, not dispatch latency, is measured.
* ``vs_baseline`` compares against a reference-faithful baseline implementation
  (fp32 compute, same math) timed with the same correct harness in the same
  run — the reference publishes no numbers of its own (BASELINE.md).

Extra context (125M composed-transformer train-step MFU, the BASELINE.json
north star) goes to stderr so stdout stays one machine-readable line.
"""

import json
import sys
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_jax_sharding_tpu.models.attention import MultiHeadAttention
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_125M,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import build_mesh, mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)
from learning_jax_sharding_tpu.utils.bench import (
    device_peak_flops,
    measure,
)

# Reference case-6 dims (`/root/reference/case6_attention.py:44-45,149-151`).
B, S, M = 8, 256, 640
NUM_HEADS, HEAD_DIM = 8, 64
CHAIN = 32  # forward applications chained per jitted call


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _chained_apply(model, params, x0, n):
    """n chained forwards in one program: x_{i+1} = normalize(f(x_i)).

    Chaining defeats loop-invariant hoisting (each iteration depends on the
    last); the rms normalization (negligible FLOPs next to the matmuls) keeps
    magnitudes stable across repeated un-normalized attention blocks.
    """

    def body(_, x):
        y = model.apply({"params": params}, x)
        return (y * jax.lax.rsqrt(jnp.mean(jnp.square(y)) + 1e-6)).astype(x0.dtype)

    x0 = x0.astype(model.dtype)
    return jax.lax.fori_loop(0, n, body, x0)


def bench_attention(dtype, label):
    from learning_jax_sharding_tpu.telemetry import executable_report

    mesh = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
    model = MultiHeadAttention(
        features=M, num_heads=NUM_HEADS, head_dim=HEAD_DIM, dtype=dtype
    )
    x = put(
        np.random.default_rng(0).standard_normal((B, S, M)).astype(np.float32),
        mesh_sharding(mesh, "data", None, None),
    )
    params = model.init({"params": jax.random.key(0)}, x)["params"]
    import flax.linen as nn

    params = nn.meta.unbox(params)

    single = jax.jit(lambda p, x: model.apply({"params": p}, x))
    # ONE AOT compile serves both FLOPs and the collective inventory for
    # the JSON telemetry block (all-zero collectives on the 1-chip
    # degenerate mesh — multi-chip counts are pinned in tests/ on the
    # emulated mesh). This diagnostic compile is the one extra
    # backend-compile the headline phase delta includes.
    rep = executable_report(single, params, x)
    flops_single = rep["flops"]
    collectives = rep["collectives"]
    from learning_jax_sharding_tpu.telemetry import axis_collective_volume

    axis_volume = axis_collective_volume(
        rep["collective_instructions"], mesh
    )
    chained = jax.jit(partial(_chained_apply, model, n=CHAIN))
    result = measure(
        chained, params, x,
        flops=(flops_single * CHAIN) if flops_single else None,
        n_devices=1,
    )
    per_iter = result.seconds_per_iter / CHAIN
    tflops = (flops_single / per_iter / 1e12) if flops_single else None
    msg = f"[bench] {label}: {per_iter * 1e6:.1f} us/forward"
    if tflops:
        msg += f", {tflops:.2f} TFLOP/s/chip"
    _log(msg)
    return {
        "tflops": tflops,
        "seconds_per_forward": per_iter,
        "collectives": collectives,
        "axis_volume": axis_volume,
    }


def _timed_train_step(cfg, *, b=8, s=1024, K=8, opt=None):
    """Shared sustained train-step harness for the dense and MoE context
    lines: K full optimizer steps per jitted call (lax.scan, state carried
    in place — the regime ``fit()`` runs; single-call timing cannot donate,
    which charges every step a ~2.7 ms fp32 state copy real training never
    pays), measured drift-robustly — the tunneled chip drifts ±30% across
    seconds-scale windows (PERF.md methodology), which in round 2 cost the
    bench artifact 4 ms/step vs the same path measured in-session. Longer
    chains (≥4 s per run) average the drift; 5 pairs give the median teeth.
    """
    from learning_jax_sharding_tpu.models.transformer import fused_next_token_loss

    mesh = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(b, s + 1)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
    state, state_sh = sharded_train_state(
        Transformer(cfg), opt if opt is not None else optax.adamw(3e-4),
        batch["inputs"], {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
    )
    stacked = {
        k: put(
            np.stack([np.asarray(v)] * K),
            mesh_sharding(mesh, None, "data", None),
        )
        for k, v in batch.items()
    }
    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()}, mesh, RULES_DP_TP,
        loss_fn=fused_next_token_loss, loss_needs_params=True,
        apply_kwargs={"return_hidden": True}, donate_state=False,
        steps_per_call=K,
    )
    result = measure(
        step, state, stacked, flops=cfg.train_step_flops(b, s) * K,
        n_devices=1, min_time=4.0, repeats=5,
    )
    return result, result.seconds_per_iter / K, K


def bench_transformer_125m():
    """North-star context: composed 125M transformer train step, MFU.

    Tuned TPU configuration (each measured on the v5e, b=8 s=1024):
    * Pallas flash attention, auto block sizes — the dense path's fp32
      (B, N, S, S) score traffic is the single largest time sink (~26 ms of a
      102 ms step);
    * chunked fused cross-entropy head — the full (B, S, V) logits never
      materialize (~3 ms, and the memory headroom for bigger batches);
    * MFU from analytic model FLOPs (``TransformerConfig.train_step_flops``):
      XLA cost analysis cannot see Pallas/scan FLOPs.
    """
    import dataclasses

    from learning_jax_sharding_tpu.ops.flash_attention import make_flash_attn_fn

    cfg = dataclasses.replace(CONFIG_125M, attn_fn=make_flash_attn_fn())
    # fp32 AdamW, unmodified training numerics. Round 3 re-measured every
    # recorded optimizer variant in ONE process (PERF.md "Round-3
    # resolution"): bf16 moments are a no-op (66.4 vs 66.6 ms), flattened
    # params are worse (82.3), sgd is the only thing faster (63.2) — the
    # honest sustained AdamW figure on this chip is ~66.5 ms.
    result, per_step, K = _timed_train_step(cfg)
    msg = f"[bench] 125M transformer train step: {per_step * 1e3:.1f} ms/step"
    if result.tflops_per_chip is not None:
        msg += f", {result.tflops_per_chip:.1f} TFLOP/s/chip"
    if result.mfu is not None:
        msg += f", MFU={result.mfu:.1%} (sustained, {K}-step scan)"
    _log(msg)
    return result


def _decode_ladder(cfg, label, *, b, prompt_len, new, rounds=3):
    """bf16 / int8 / int4-fused greedy decode, measured INTERLEAVED.

    Round 3's sequential ladder let the tunnel's ±30% drift reorder the
    125M variants between runs (VERDICT r3 item 1): each variant sampled a
    different drift window. Here every round times all three variants
    back-to-back and the per-variant MEDIAN across rounds is reported, so
    the ordering is a within-window comparison whichever way the tunnel
    drifts.
    """
    import flax.linen as nn

    from learning_jax_sharding_tpu.models.generate import make_generate_fn
    from learning_jax_sharding_tpu.models.quantize import (
        map_unquantized,
        quantize_tree,
        quantized_bytes,
    )
    from learning_jax_sharding_tpu.utils.bench import mbu, time_fn

    mesh = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    prompt = put(
        rng.integers(0, cfg.vocab_size, size=(b, prompt_len)).astype(np.int32),
        mesh_sharding(mesh, "data", None),
    )
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(0), prompt
        )["params"]
    )

    def to_bf16(x):
        return (
            x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x
        )

    def decode_mbu(weight_bytes: float, secs_per_tok: float) -> str:
        # Per-token-step HBM roofline: served weights + the VALID KV cache
        # (mean over the run: prompt + new/2 slots — the blocked decode
        # kernel reads only valid blocks). MBU because decode is
        # bandwidth-bound; its matmuls are too thin for MFU to mean much.
        n_kv = cfg.num_kv_heads or cfg.num_heads
        cache_bytes = (
            cfg.num_layers * b * n_kv * (prompt_len + new / 2)
            * cfg.head_dim * 2 * 2
        )  # K+V, bf16
        frac = mbu(weight_bytes + cache_bytes, secs_per_tok)
        return "" if frac is None else f", MBU={frac:.1%}"

    def make(deq):
        return make_generate_fn(
            cfg, mesh, RULES_DP_TP, max_new_tokens=new,
            inference_dtype=jnp.bfloat16, dequantize=deq,
        )

    variants = [
        ("bf16", jax.tree.map(to_bf16, params), make(False)),
        ("int8", quantize_tree(params), make(True)),
        ("int4-fused", quantize_tree(params, bits=4), make("fused")),
    ]
    del params
    times = {name: [] for name, _, _ in variants}
    # time_fn's own warmup (1 untimed call) covers compile on the first
    # round; keeping it minimal holds the variants' timed samples as close
    # together as the tunnel allows, which is the point of interleaving.
    for _ in range(rounds):
        for name, tree, gen in variants:
            times[name].append(
                time_fn(gen, tree, prompt, jax.random.key(1),
                        min_time=1.0, repeats=1, warmup=1)
            )
    order = sorted(times, key=lambda n: float(np.median(times[n])))
    for name, tree, gen in variants:
        served = quantized_bytes(map_unquantized(to_bf16, tree))
        secs = float(np.median(times[name]))
        _log(
            f"[bench] {label} decode, {name} (b={b}, prompt {prompt_len}, "
            f"+{new} new): {b * new / secs:,.0f} tok/s, "
            f"{secs / new * 1e3:.2f} ms/token-step, served "
            f"{served / 1e6:,.0f} MB" + decode_mbu(served, secs / new)
        )
    _log(
        f"[bench] {label} decode ladder ordering (interleaved medians, "
        f"fastest first): {' > '.join(order)}"
    )


def bench_decode_125m():
    """Serving context: KV-cached greedy decode ladder on the 125M model."""
    _decode_ladder(CONFIG_125M, "125M", b=8, prompt_len=128, new=128)


def bench_decode_1p4b():
    """The weight-BANDWIDTH-bound ladder shape (24×2048, 16 heads×128):
    decode streams 0.9-2.8 GB of weights per token, so the quantization
    ladder separates on served bytes instead of launch overhead — the
    shape where PERF.md claims int4-fused ≥ int8 ("whole-FF kernel"
    section), now in the driver artifact (VERDICT r3 item 2)."""
    from learning_jax_sharding_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(
        num_layers=24, features=2048, num_heads=16, head_dim=128,
        hidden=8192, max_seq_len=256,
    )
    # rounds=5 (vs the ladder default 3): the ABSOLUTE int4 number is a
    # claim here, not just the ordering — round 4's artifact/shakedown
    # spread (3,036-4,056 tok/s) needs the deeper median (VERDICT item 6).
    _decode_ladder(cfg, "1.4B", b=8, prompt_len=64, new=64, rounds=5)


def bench_longcontext():
    """Long-context train line (SURVEY §5): S=8192, head_dim 128 — the
    configuration of record from PERF.md's round-3 VPU:MXU verification
    (hd=64 is VPU-floored at ~24% of peak on the v5e; doubling the
    contraction dim doubles kernel throughput)."""
    import dataclasses

    from learning_jax_sharding_tpu.ops.flash_attention import make_flash_attn_fn

    cfg = dataclasses.replace(
        CONFIG_125M, num_heads=6, head_dim=128, max_seq_len=8192,
        attn_fn=make_flash_attn_fn(), remat=False,
    )
    result, per_step, K = _timed_train_step(cfg, b=2, s=8192, K=2)
    msg = (
        f"[bench] long-context train step (S=8192, b=2, hd=128, flash "
        f"causal): {per_step * 1e3:.1f} ms/step"
    )
    if result.mfu is not None:
        msg += f", MFU={result.mfu:.1%} (sustained, {K}-step scan)"
    _log(msg)


def bench_reference_configs():
    """BASELINE.md's remaining config list, one line each on the real chip.

    The reference shapes are lesson-sized (A(4,16)·B(16,4) — microseconds of
    work), so each pattern is measured at a ×512-scaled shape that keeps the
    MXU busy; the multi-device sharding/collective semantics of these cases
    are pinned on the emulated 8-device mesh in tests/test_matmul_shardings.py
    (HLO collective asserts) — one chip runs each pattern's compute
    degenerate.

    * case1a replicated matmul (`/root/reference/case1a.py:49`)
    * case3 fully-sharded matmul pattern (`/root/reference/case3_fully_sharded.py:23-29`)
    * case4 DP×MP feed-forward einsum (`/root/reference/case4_gspmd_ff.py:30,52`)
    """
    from learning_jax_sharding_tpu.utils.bench import time_fn

    peak = device_peak_flops(jax.devices()[0])
    rng = np.random.default_rng(0)

    def line(label, fn, *args, flops):
        # Warm-weight microbench: the same operands repeat every call, so
        # the chip overlaps weight fetches perfectly — sustained rates can
        # EXCEED the cold-read bf16 peak ratio (PERF.md methodology notes);
        # the ratio is context, not an MFU claim.
        secs = time_fn(jax.jit(fn), *args, min_time=1.0)
        tf = flops / secs / 1e12
        pct = f" ({tf * 1e12 / peak:.0%} of bf16 peak, warm-weight)" if peak else ""
        _log(f"[bench] {label}: {secs * 1e6:.0f} us, {tf:.1f} TFLOP/s/chip{pct}")

    m, k_, n = 2048, 8192, 2048
    a = jnp.asarray(rng.standard_normal((m, k_)), jnp.bfloat16)
    bmat = jnp.asarray(rng.standard_normal((k_, n)), jnp.bfloat16)
    line(
        "case1a replicated matmul (2048x8192x2048 bf16, 1-chip degenerate)",
        jax.lax.dot, a, bmat, flops=2 * m * k_ * n,
    )
    line(
        "case3 fully-sharded matmul pattern (same shapes, fp32-accum)",
        lambda x, y: jax.lax.dot_general(
            x, y, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ),
        a, bmat, flops=2 * m * k_ * n,
    )
    bb, s, d, h = 8, 512, 2048, 8192
    x = jnp.asarray(rng.standard_normal((bb, s, d)), jnp.bfloat16)
    w1 = jnp.asarray(rng.standard_normal((d, h)), jnp.bfloat16)
    w2 = jnp.asarray(rng.standard_normal((h, d)), jnp.bfloat16)

    def ff(x, w1, w2):
        return jnp.einsum("bsh,hd->bsd", jax.nn.relu(jnp.einsum("bsd,dh->bsh", x, w1)), w2)

    line(
        "case4 DP*MP feed-forward (8x512x2048, hidden 8192, bf16)",
        ff, x, w1, w2, flops=2 * bb * s * d * h * 2,
    )


def bench_shardflow():
    """Analyzer self-check (round 13): price the tracked program shapes
    with ``analysis.shardflow`` + ``analysis.costmodel`` BEFORE running
    them, then measure the same jitted programs and report the model
    error — the number ``scripts/bench_compare.py`` gates direction-aware
    (``predicted_vs_measured_pct``; a growing error means the propagation
    rules or the platform profile drifted from the real machine).

    On the TPU host the lines price the 125M tracked shapes; on the
    emulated-CPU host a scaled-down same-architecture configuration keeps
    the measured side inside the tier-1 window (PERF.md round 13 records
    the error for both). One-chip degenerate mesh, like every other
    tracked line: the roofline terms (compute/HBM) carry the prediction;
    the multi-chip collective term is reconciled against goldens by
    ``scripts/shardcheck.py`` on the emulated mesh instead, where
    emulated "wire time" would be fiction.
    """
    import dataclasses

    from learning_jax_sharding_tpu.analysis import costmodel
    from learning_jax_sharding_tpu.analysis.shardflow import trace_shardflow
    from learning_jax_sharding_tpu.models.generate import make_generate_fn
    from learning_jax_sharding_tpu.models.transformer import next_token_loss
    from learning_jax_sharding_tpu.parallel.logical import activate
    from learning_jax_sharding_tpu.utils.bench import time_fn

    import flax.linen as nn

    profile = costmodel.current_profile()
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = CONFIG_125M
        b, s = 8, 1024
        db, dprompt, dnew = 8, 128, 128
    else:
        cfg = dataclasses.replace(
            CONFIG_125M, vocab_size=8192, num_layers=2, features=256,
            num_heads=4, head_dim=64, hidden=1024, max_seq_len=512,
        )
        b, s = 4, 256
        db, dprompt, dnew = 4, 64, 32
    mesh = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
    block: dict = {"profile": profile.to_dict()}

    def line(label, rep, measured_s, unit_scale, unit):
        cost = costmodel.price(rep, profile)
        cmp = costmodel.compare(cost.predicted_s, measured_s)
        _log(
            f"[bench] shardflow {label}: predicted "
            f"{cost.predicted_s * unit_scale:.2f} vs measured "
            f"{measured_s * unit_scale:.2f} {unit} "
            f"({cost.bound}-bound), model err {cmp['err_pct']:.1f}%"
        )
        return {**cmp, "bound": cost.bound, "flops": cost.flops,
                "hbm_bytes": cost.hbm_bytes}

    # Train step: same builders as the tracked 125M line (single-call
    # timing here — the prediction is also single-step).
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(b, s + 1)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
    state, state_sh = sharded_train_state(
        Transformer(cfg), optax.adamw(3e-4), batch["inputs"],
        {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
    )
    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()}, mesh,
        RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
    )
    with activate(mesh, RULES_DP_TP):
        rep = trace_shardflow("bench_train_step", step.jitted, state, batch,
                              mesh=mesh)
    measured = time_fn(step, state, batch, min_time=1.0, repeats=2)
    block["train_step"] = line(
        f"train step (b={b}, s={s})", rep, measured, 1e3, "ms/step"
    )

    # Decode: whole greedy generation in one jitted program — the token
    # loop is a scan, so the analyzer's trip multiplier prices the
    # weight re-streaming that makes decode bandwidth-bound.
    model = Transformer(cfg)
    prompt = put(
        np.random.default_rng(1).integers(
            0, cfg.vocab_size, size=(db, dprompt)
        ).astype(np.int32),
        mesh_sharding(mesh, "data", None),
    )
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(0), prompt
        )["params"]
    )
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating) else x,
        params,
    )
    gen = make_generate_fn(
        cfg, mesh, RULES_DP_TP, max_new_tokens=dnew,
        inference_dtype=jnp.bfloat16,
    )
    with activate(mesh, RULES_DP_TP):
        rep = trace_shardflow("bench_decode", gen, params, prompt,
                              jax.random.key(1), mesh=mesh)
    measured = time_fn(gen, params, prompt, jax.random.key(1),
                       min_time=1.0, repeats=2)
    block["decode"] = line(
        f"decode (b={db}, prompt {dprompt}, +{dnew} new)",
        rep, measured, 1e3 / dnew, "ms/token-step",
    )
    return block


def bench_layout_search():
    """Layout-search closed loop (round 17): run the abstract search
    over the train step's param layout, then compile ONLY the hand
    layout and the argmin layout and measure both — the predicted win
    is confirmed against real execution, and the two tracked numbers
    ride ``scripts/bench_compare.py`` direction-aware: ``layout gap``
    (searched-vs-hand priced gap — growing means the committed layouts
    drifted from the searchable optimum) and ``layout err`` (the
    search's predicted-vs-measured error on the two layouts it
    compiles, the analyzer-loop analogue of the shardflow model err).

    Like ``bench_fleet``, the layout legs need device MULTIPLICITY the
    one-chip bench host lacks, so the search + both measurements run on
    the emulated 8-device mesh in a subprocess
    (``scripts/layout_search.py --bench-lines``) whose ``[bench]``
    lines are relayed verbatim; the subprocess prices the measured legs
    with the live profile scaled to the emulated-device share of the
    socket (its docstring records the convention)."""
    import os
    import pathlib
    import subprocess

    script = (
        pathlib.Path(__file__).resolve().parent / "scripts"
        / "layout_search.py"
    )
    proc = subprocess.run(
        [sys.executable, str(script), "--entry", "train_step",
         "--bench-lines", "--budget", "48"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if proc.returncode != 0:
        tail = "\n".join(proc.stderr.splitlines()[-5:])
        raise RuntimeError(f"layout_search exited {proc.returncode}: {tail}")
    block = None
    for line in proc.stdout.splitlines():
        if line.startswith("[bench]"):
            _log(line)
        elif line.startswith("[bench-json] "):
            block = json.loads(line[len("[bench-json] "):])
    return block


def bench_memflow():
    """Memflow reconciliation (round 18): the static per-device
    peak-HBM analyzer (``analysis/memflow.py``) against what XLA's
    ``compiled.memory_analysis()`` reports for the searchable entry
    points — the accuracy number behind the layout search's HBM budget
    gate and ``shardcheck --memory``'s OOM findings.

    Like ``bench_fleet``, the entry points need the emulated 8-device
    mesh, so the pass runs in a subprocess (``scripts/shardcheck.py
    --pass memory --json``); this relay prints one ``[bench] memflow
    <entry>`` line per searchable entry plus a summary line, and
    ``scripts/bench_compare.py`` gates ``memflow err`` per line
    direction-aware (phrased distinctly from shardflow's ``model err``
    and the search's ``layout err``). The per-entry peak table lands in
    the JSON line's ``memflow`` block. The signed error is structurally
    POSITIVE (memflow over-predicts: it cannot see XLA's rematerialized
    fusions freeing buffers early), which is what makes the budget gate
    safe — drift toward 0 is fine, drift NEGATIVE would mean the gate
    can pass layouts that OOM."""
    import os
    import pathlib
    import subprocess

    script = (
        pathlib.Path(__file__).resolve().parent / "scripts"
        / "shardcheck.py"
    )
    proc = subprocess.run(
        [sys.executable, str(script), "--pass", "memory", "--json"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if proc.returncode != 0:
        tail = "\n".join((proc.stderr or proc.stdout).splitlines()[-5:])
        raise RuntimeError(
            f"shardcheck --pass memory exited {proc.returncode}: {tail}"
        )
    doc = json.loads(proc.stdout)
    entries: dict = {}
    worst = 0.0
    for rec in doc.get("memory", []):
        rep, rc = rec["report"], rec["reconciled"]
        err = abs(float(rc["err_pct"]))
        worst = max(worst, err)
        _log(
            f"[bench] memflow {rec['name']}: predicted peak "
            f"{rep['peak_mib']:.1f} MiB/device at {rep['peak_where']}, "
            f"XLA measures {rc['measured_bytes'] / 2**20:.1f} MiB, "
            f"memflow err {err:.1f}%"
        )
        entries[rec["name"]] = {
            "peak_bytes": rep["peak_bytes"],
            "peak_where": rep["peak_where"],
            "measured_bytes": rc["measured_bytes"],
            "signed_err_pct": rc["signed_err_pct"],
            "unexplained": rc["unexplained"],
            "donated": rec["donated"],
        }
    if entries:
        _log(
            f"[bench] memflow summary: worst of {len(entries)} entries, "
            f"memflow err {worst:.1f}%"
        )
    return {"entries": entries, "worst_err_pct": worst} if entries else None


def bench_commscope():
    """Comm observatory (round 19): the measured per-axis α–β link
    profiles from the commscope calibration ladder plus the realized
    comm/compute overlap attribution of one saturated serving window
    (``telemetry/commscope.py`` + the goodput ledger's per-family
    device split).

    Like ``bench_fleet``, the ladder needs device multiplicity, so it
    runs on the emulated 8-device mesh in a subprocess
    (``scripts/perf_commscope.py --bench-lines``) whose ``[bench]``
    lines are relayed verbatim. ``scripts/bench_compare.py`` gates
    them direction-aware: ``axis bandwidth`` (higher), ``comm fit
    err`` / ``exposed comm`` / ``comm prediction err`` (lower). The
    ``overlap ratio`` is printed but NOT gated — overlapping more or
    less comm is a scheduling outcome, not monotonic goodness."""
    import os
    import pathlib
    import subprocess

    script = (
        pathlib.Path(__file__).resolve().parent / "scripts"
        / "perf_commscope.py"
    )
    proc = subprocess.run(
        [sys.executable, str(script), "--json"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if proc.returncode != 0:
        tail = "\n".join((proc.stderr or proc.stdout).splitlines()[-5:])
        raise RuntimeError(
            f"perf_commscope exited {proc.returncode}: {tail}"
        )
    res = json.loads(proc.stdout)
    for axis, ap in sorted(res["profile"].items()):
        _log(
            f"[bench] commscope axis {axis} (8-dev emulated): "
            f"axis bandwidth {ap['beta_gb_s']:.3f} GB/s, "
            f"alpha {ap['alpha_us']:.1f} us, "
            f"comm fit err {ap['fit_err_pct']:.1f}%"
        )
    ratio = res.get("overlap_ratio")
    _log(
        f"[bench] commscope overlap (8-dev emulated): "
        f"exposed comm {res['exposed_share_pct']:.2f}% of device, "
        f"overlap ratio "
        f"{(ratio or 0.0) * 100.0:.1f}%, "
        f"comm prediction err {res['model_err_pct']:.1f}%"
    )
    return {
        "profile": res["profile"],
        "exposed_share_pct": res["exposed_share_pct"],
        "overlap_ratio": ratio,
        "model_err_pct": res["model_err_pct"],
    }


def bench_topology():
    """Topology observatory (round 21): the two-tier interconnect model
    (``analysis/topology.py``) closed against real execution three ways,
    each a tracked bench_compare gate.

    * ``topo err`` per searchable entry — the overlap-aware prediction
      (``max(compute, memory) + exposed comm``) vs the measured step,
      from ``scripts/shardcheck.py --pass topo --json`` on the emulated
      8-device mesh (the same gate CI runs; the serial-sum error on the
      same line is context, not a gate — serial is the honest upper
      bound, not the claim).
    * ``dcn B/token`` + ``overlap gap`` — what the static model says
      the train step pushes across the slow tier per token, and how far
      the profile's pinned overlap ratio sits from the ledger's realized
      one (``decompose_overlap``); both lower-is-better drift signals.
    * ``topo argmin gap`` — the seeded two-tier acceptance scenario
      (``scripts/layout_search.py --topo-gap``, abstract pricing only):
      flat pricing parks the hot all-reduce on the DCN tier, topology
      pricing routes it onto ICI. Deterministic, so the gap collapsing
      toward 0 can only mean hierarchy pricing lost its discrimination
      power — gated HIGHER-is-better, the inverse of every error gate.
    """
    import os
    import pathlib
    import subprocess

    root = pathlib.Path(__file__).resolve().parent
    env = {**os.environ, "JAX_PLATFORMS": ""}
    proc = subprocess.run(
        [sys.executable, str(root / "scripts" / "shardcheck.py"),
         "--pass", "topo", "--json"],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    if proc.returncode != 0:
        tail = "\n".join((proc.stderr or proc.stdout).splitlines()[-5:])
        raise RuntimeError(
            f"shardcheck --pass topo exited {proc.returncode}: {tail}"
        )
    doc = json.loads(proc.stdout)
    topo = doc.get("topo") or {}
    programs = topo.get("programs", [])
    entries: dict = {}
    worst = 0.0
    for pr in programs:
        err = float(pr["err_topo_pct"])
        worst = max(worst, err)
        _log(
            f"[bench] topo {pr['name']}: measured "
            f"{pr['measured_s'] * 1e3:.2f} ms vs overlap-aware "
            f"{pr['topo_predicted_s'] * 1e3:.2f} ms, topo err "
            f"{err:.1f}% (serial-sum {pr['err_serial_pct']:.1f}%), "
            f"dcn {pr['dcn_bytes'] / 1e3:.1f} kB predicted / "
            f"{pr['observed_dcn_bytes'] / 1e3:.1f} kB contract"
        )
        entries[pr["name"]] = {
            k: pr[k] for k in (
                "measured_s", "topo_predicted_s", "serial_predicted_s",
                "err_topo_pct", "err_serial_pct", "ici_bytes",
                "dcn_bytes", "observed_dcn_bytes",
            )
        }
    train = next(
        (p for p in programs if p["name"] == "train_step"), None
    )
    dcn_per_token = None
    if train and train.get("tokens_per_step"):
        dcn_per_token = (
            float(train["dcn_bytes"]) / float(train["tokens_per_step"])
        )
        _log(
            f"[bench] topo dcn: train_step moves {dcn_per_token:,.1f} "
            f"dcn B/token ({train['dcn_bytes']:.0f} B over "
            f"{train['tokens_per_step']} tokens)"
        )
    overlap_gap_pp = None
    if train:
        used = train.get("overlap_ratio_used")
        realized = (train.get("realized") or {}).get(
            "realized_overlap_ratio"
        )
        if used is not None and realized is not None:
            overlap_gap_pp = abs(float(used) - float(realized)) * 100.0
            _log(
                f"[bench] topo overlap: train_step profile predicts "
                f"{float(used):.2f}, ledger realized "
                f"{float(realized):.2f}, overlap gap "
                f"{overlap_gap_pp:.1f} pp"
            )
    # The seeded two-tier canary: abstract pricing, nothing compiles.
    proc2 = subprocess.run(
        [sys.executable, str(root / "scripts" / "layout_search.py"),
         "--topo-gap"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    if proc2.returncode != 0:
        tail = "\n".join((proc2.stderr or proc2.stdout).splitlines()[-5:])
        raise RuntimeError(
            f"layout_search --topo-gap exited {proc2.returncode}: {tail}"
        )
    argmin_block = None
    for line in proc2.stdout.splitlines():
        if line.startswith("[bench]"):
            _log(line)
        elif line.startswith("[bench-json] "):
            argmin_block = json.loads(line[len("[bench-json] "):])
    if entries:
        _log(
            f"[bench] topo summary: worst of {len(entries)} entries, "
            f"topo err {worst:.1f}%"
        )
    if not (entries or argmin_block):
        return None
    return {
        "profile": (topo.get("topology") or {}).get("name"),
        "entries": entries,
        "worst_err_pct": worst,
        "dcn_bytes_per_token": dcn_per_token,
        "overlap_predicted_vs_realized_pp": overlap_gap_pp,
        "argmin": argmin_block,
        "findings": [
            f for f in doc.get("findings", [])
            if f.get("check") == "topo"
        ],
    }


def bench_moe_125m():
    """MoE context line: 125M-class with E=8 top-2 routed FFs (GShard
    capacity routing, fp32 router — models/moe.py), same harness as the
    dense 125M step. MFU uses activated-FLOPs (top_k expert FFs + router),
    the honest denominator for routed models."""
    import dataclasses

    from learning_jax_sharding_tpu.ops.flash_attention import make_flash_attn_fn

    cfg = dataclasses.replace(
        CONFIG_125M, attn_fn=make_flash_attn_fn(), num_experts=8, moe_top_k=2,
        moe_dispatch="scatter",
    )
    # sgd + b=4: non-donating timing holds INPUT and OUTPUT states at once,
    # and 2× the E=8 fp32 AdamW state (~6.8 GB each) exhausts the 16 GB
    # chip; sgd state is params-only. Round 4: scatter dispatch (routing
    # bit-identical to the einsum path, no (T,E,C) one-hot contractions)
    # replaced remat+einsum — without the stacked dispatch tensors the
    # activations fit un-rematerialized, and the measured ladder
    # (PERF.md round 4) has scatter+noremat at 67.8 ms vs the round-3
    # einsum+remat anchor's 97.8 in the same process.
    result, per_step, _ = _timed_train_step(cfg, b=4, K=2, opt=optax.sgd(3e-4))
    msg = (
        f"[bench] 125M-class MoE (E=8, top-2, scatter dispatch) train step "
        f"(b=4, sgd): {per_step * 1e3:.1f} ms/step"
    )
    if result.mfu is not None:
        msg += f", activated-MFU={result.mfu:.1%}"
    _log(msg)


def bench_moe_headline():
    """The MoE configuration the README headlines (VERDICT r4 item 5):
    E=4 WIDER experts (2x hidden), top-2, capacity 1.0, scatter dispatch,
    remat OFF — scatter has no (T,E,C) dispatch tensors to fit, so the
    activations fit un-rematerialized and routing cost vs the dense
    control collapses (PERF.md round-4 ladder: 46.3% vs 45.9% dense).
    ``bench_moe_125m`` keeps the E=8 cap1.25 workload for cross-round
    comparability; this line is the tuned configuration of record."""
    import dataclasses

    from learning_jax_sharding_tpu.ops.flash_attention import make_flash_attn_fn

    cfg = dataclasses.replace(
        CONFIG_125M, attn_fn=make_flash_attn_fn(), num_experts=4,
        hidden=2 * CONFIG_125M.hidden, moe_top_k=2,
        moe_capacity_factor=1.0, moe_dispatch="scatter", remat=False,
    )
    result, per_step, _ = _timed_train_step(cfg, b=4, K=2, opt=optax.sgd(3e-4))
    msg = (
        f"[bench] 125M-class MoE HEADLINE (E=4 wide, top-2, cap 1.0, "
        f"scatter, noremat) train step (b=4, sgd): {per_step * 1e3:.1f} ms/step"
    )
    if result.mfu is not None:
        msg += f", activated-MFU={result.mfu:.1%}"
    _log(msg)


def bench_serving_125m():
    """The serving-engine story, in the driver artifact (VERDICT r4 item
    2): the shared-system-prompt workload from
    ``scripts/perf_prefix_cache.py`` (512-token system prefix + 32
    request tokens, 24 requests through 8 slots, +32 generated) served by

    * the plain bf16 continuous engine,
    * the COMPOSED stack — int4-fused weights + paged KV (+ prefix), and
    * the prefix cache COLD (registry flushed per call — within-call
      sharing only, the round-4 comparison) and WARM (registry persisted
      from the previous call — the round-5 persistent-engine payoff: the
      system prompt is never re-prefilled).

    Interleaved rounds with per-variant medians, like the decode ladders
    (the tunnel drifts ±30%; only within-window comparisons order
    reliably). Also reports the refill-pause share of engine time
    (VERDICT r4 item 9) and the warm prefix hit rate.
    """
    import dataclasses
    import time as _time

    import flax.linen as nn

    from learning_jax_sharding_tpu.models.quantize import quantize_tree
    from learning_jax_sharding_tpu.models.serving import make_continuous_engine

    cfg = dataclasses.replace(
        CONFIG_125M, max_seq_len=1024, decode_attention="blocked"
    )
    mesh = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
    rng = np.random.default_rng(0)
    model = Transformer(cfg)
    probe = np.zeros((8, 64), np.int32)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(0), probe
        )["params"]
    )
    q4 = quantize_tree(params, bits=4)
    system = rng.integers(1, cfg.vocab_size, size=(512,)).astype(np.int32)
    NREQ, NEW = 24, 32
    prompts = [
        np.concatenate(
            [system,
             rng.integers(1, cfg.vocab_size, size=(32,)).astype(np.int32)]
        )
        for _ in range(NREQ)
    ]
    common = dict(
        batch_size=8, max_new_tokens=NEW, refill_chunk=64,
        inference_dtype=jnp.bfloat16,
        # Dispatch-granularity tuning (round 5, perf_block_ladder.py):
        # a jitted call through the tunneled chip costs ~120 ms in the
        # dispatch itself, so tokens-per-dispatch sets engine
        # throughput. K = max_new (one decode dispatch per generation
        # wave, rows retire exactly at the block boundary) and chained
        # refills (each 544-token prompt's ceil(544/64) = 9 chunks ride
        # one host sync).
        decode_block_steps=NEW, decode_chain=9,
    )
    PAGES = 8 * 10 + 1 + 12   # 8 slots x ceil(608/64) + scratch + slack
    plain = make_continuous_engine(cfg, mesh, RULES_DP_TP, **common)
    # The FUSED scheduler (round 9): every dispatch advances decode AND
    # pushes budgeted refill — the ITL/queue-wait engine. Budget 128 (two
    # chunks + the decode wave) from the perf_mixed.py ladder.
    mixed = make_continuous_engine(
        cfg, mesh, RULES_DP_TP, **common, mixed=True,
        token_budget=128 + 8,
    )
    paged4 = make_continuous_engine(
        cfg, mesh, RULES_DP_TP, **common, dequantize="fused",
        paged_pages=PAGES, page_size=64,
    )
    pfx4 = make_continuous_engine(
        cfg, mesh, RULES_DP_TP, **common, dequantize="fused",
        paged_pages=PAGES, page_size=64, prefix_cache=True,
    )

    def timed(serve, tree):
        t0 = _time.perf_counter()
        outs = serve(tree, prompts)
        dt = _time.perf_counter() - t0
        return dt, sum(len(o) - 544 for o in outs)

    variants = [
        ("bf16 engine", plain, params, None),
        ("bf16 mixed engine", mixed, params, None),
        ("int4-fused + paged", paged4, q4, None),
        ("int4 + paged + prefix (cold)", pfx4, q4, "cold"),
        ("int4 + paged + prefix (warm)", pfx4, q4, "warm"),
    ]
    # Warm every executable once (compiles excluded from the ladder).
    for _, serve, tree, mode in variants[:4]:
        serve(tree, prompts[:8])
    times = {name: [] for name, *_ in variants}
    toks = {}
    stats = {}
    for _ in range(3):
        for name, serve, tree, mode in variants:
            if mode == "cold":
                serve.engine.flush_prefix_cache()
            dt, n = timed(serve, tree)
            times[name].append(dt)
            toks[name] = n
            stats[name] = (serve.last_stats, serve.last_latency)
    base = None
    for name, *_ in variants:
        secs = float(np.median(times[name]))
        rate = toks[name] / secs
        if base is None:
            base = rate
        st, lat = stats[name]
        extra = ""
        if st and "prefix_hits" in st:
            extra += (
                f", hits {st['prefix_hits']}/{NREQ}"
                f" ({st['prefix_pages_reused']} pages reused)"
            )
        if lat and lat.get("refill_frac") is not None:
            extra += f", refill {lat['refill_frac']:.0%} of engine time"
        _log(
            f"[bench] 125M serving, {name}: {rate:,.0f} tok/s "
            f"({secs:.2f} s, {toks[name]} generated, "
            f"{rate / base:.2f}x bf16){extra}"
        )

    # bf16 speculation agreement guard (VERDICT r4 item 10): the verify
    # chunk evaluates num_draft+1 positions in one bf16 forward whose
    # logits differ in the last ulps from the plain path's S=1 forwards,
    # occasionally flipping a greedy argmax (fp32 oracle exact,
    # test-pinned). A SELF-draft isolates exactly that drift; recording
    # the agreement rate every round makes verify-chunk numerics
    # regressions visible. Round-4 observation: 97-99%.
    spec = make_continuous_engine(
        cfg, mesh, RULES_DP_TP, **common, draft_config=cfg, num_draft=4,
    )
    plain_outs = plain(params, prompts)
    spec_outs = spec(params, prompts, draft_params=params)
    agree = float(
        np.mean([
            np.mean(a[544:][: min(len(a), len(b)) - 544]
                    == b[544:][: min(len(a), len(b)) - 544])
            for a, b in zip(plain_outs, spec_outs)
        ])
    )
    _log(
        f"[bench] 125M serving, bf16 self-draft speculative token "
        f"agreement vs plain: {agree:.1%} (per-round drift guard; this "
        f"544-prompt/+32 queue first recorded ~90% — one early argmax "
        f"flip cascades through a short stream; the 64/+128 queue "
        f"recorded 97-99% in round 4)"
    )

    # Staggered-arrival latency (VERDICT r4 item 1): requests arrive over
    # time through the persistent engine's streaming API; TTFT and
    # per-token latency percentiles come from the engine's own telemetry.
    # Round 9: the TRACKED line runs the MIXED engine (decode advances in
    # every dispatch, refill rides the token budget, admission at chunk
    # granularity); the split engine's numbers stay as the stall
    # baseline so bench_compare sees both trajectories.
    def staggered(eng, label):
        eng.decode_chain = 1    # latency-sensitive: no chain coarsening
        eng.reset_stats()
        arrivals = list(prompts[:16])
        gap = 0.05                   # 20 req/s offered load
        t0 = _time.perf_counter()
        nxt = 0
        while eng.has_work() or nxt < len(arrivals):
            while (
                nxt < len(arrivals)
                and _time.perf_counter() - t0 >= nxt * gap
            ):
                eng.add_request(arrivals[nxt])
                nxt += 1
            eng.step(params)
        dt = _time.perf_counter() - t0
        outs = eng.pop_finished()
        toks = sum(len(o) - 544 for o in outs.values())
        generated = toks
        lat = eng.latency_stats()
        extras = f", {toks / dt:,.0f} tok/s"
        if lat.get("refill_frac") is not None:
            extras += f", refill {lat['refill_frac']:.0%} of engine time"
        if lat.get("decode_stall_share") is not None:
            extras += f", decode stalled {lat['decode_stall_share']:.0%}"
        # Recovery-policy telemetry (round 10): with no faults these must
        # hold at 0 — bench_compare gates them direction-aware, so the
        # deadline/admission hooks can't silently start shedding clean
        # traffic.
        extras += (
            f", shed {lat.get('shed_rate', 0.0):.0%}"
            f", deadline miss {lat.get('deadline_miss_rate', 0.0):.0%}"
        )
        _log(
            f"[bench] 125M serving latency{label} (16 staggered arrivals, "
            f"{1 / gap:.0f} req/s): TTFT p50 {lat['ttft_p50'] * 1e3:.0f} ms"
            f" / p99 {lat['ttft_p99'] * 1e3:.0f} ms, TPOT p50 "
            f"{lat['tpot_p50'] * 1e3:.1f} ms, ITL p99 "
            f"{lat['itl_p99'] * 1e3:.0f} ms, queue wait p50 "
            f"{lat['queue_wait_p50'] * 1e3:.0f} ms{extras}"
        )
        return generated

    # The latency engine re-tunes the two mixed knobs (perf_mixed.py
    # ladder): budget 128+B bounds each fused dispatch (the ITL gap a
    # decoding row sees while prompts stream), and decode_block_steps=8
    # bounds the PURE-DECODE fallback's token-visibility gap (in mixed
    # mode the block program only runs when there is no refill to fuse,
    # so a small K costs a few extra tail dispatches, not refill
    # overlap).
    # Recovery hooks ON but never tripping (round 10): a 300 s TTL and a
    # 256-deep queue bound are far beyond this workload, so the tracked
    # line now PRICES the deadline sweep + admission check — the <2%
    # overhead budget PERF.md round 10 measures (scripts/perf_recovery.py).
    mixed_lat = make_continuous_engine(
        cfg, mesh, RULES_DP_TP,
        **{**common, "decode_block_steps": 8},
        mixed=True, token_budget=128 + 8,
        deadline_s=300.0, max_queue=256,
    )
    # Warm before the tracked run: this engine's executables (its
    # decode_block_steps differs from the ladder's warmed engines) must
    # compile outside the measured window — staggered() resets stats, so
    # the warm pass leaves no trace in the gated percentiles.
    mixed_lat(params, prompts[:8])
    # Goodput accounting rides the tracked staggered run (round 14): the
    # engine's ledger windows with reset_stats, a TraceStore collects
    # every request's critical path, and the decode roofline (each
    # generation wave streams the bf16 weights once per batch) prices
    # what an ideally-scheduled device would have needed — host_share /
    # goodput_ratio / telemetry overhead become gated bench facts.
    from learning_jax_sharding_tpu.analysis.costmodel import current_profile
    from learning_jax_sharding_tpu.telemetry import TraceStore

    eng = mixed_lat.engine
    eng.trace_sink = TraceStore(registry=eng.registry)
    generated = staggered(eng, "")
    prof = current_profile()
    wbytes = sum(x.size for x in jax.tree.leaves(params)) * 2  # bf16
    roofline = (
        (generated / common["batch_size"]) * wbytes
        / max(prof.hbm_bw * prof.mbu_eff, 1.0)
    )
    rep = eng.ledger.window_report(roofline_device_s=roofline)
    rec = eng.ledger.reconcile()
    cps = eng.trace_sink.completed()
    ttfts = [cp["ttft_s"] for cp in cps if cp["ttft_s"] is not None]
    cp50 = float(np.percentile(ttfts, 50)) * 1e3 if ttfts else None
    cp99 = float(np.percentile(ttfts, 99)) * 1e3 if ttfts else None
    _log(
        f"[bench] goodput: host_share {(rep['host_share'] or 0) * 100:.1f}%, "
        f"goodput_ratio {rep['goodput_ratio'] * 100:.2f}%, "
        f"top contributor {rep['top_contributor']} "
        f"({rep['top_contributor_s']:.2f} s of {rep['wall_s']:.2f} s), "
        f"telemetry overhead {rep['telemetry_share'] * 100:.2f}%, "
        f"TTFT critical path p50 {cp50:.0f} ms / p99 {cp99:.0f} ms, "
        f"reconcile {'ok' if rec['ok'] else 'FAILED'} "
        f"(residual {rec['residual_s'] * 1e3:.2f} ms)"
    )
    goodput_block = {
        "host_share": rep["host_share"],
        "goodput_ratio": rep["goodput_ratio"],
        "roofline_device_s": roofline,
        "top_contributor": rep["top_contributor"],
        "top_contributor_s": rep["top_contributor_s"],
        "telemetry_share": rep["telemetry_share"],
        "buckets": rep["buckets"],
        "wall_s": rep["wall_s"],
        "reconcile_ok": rec["ok"],
        "reconcile_residual_s": rec["residual_s"],
        "ttft_critical_path_p50_ms": cp50,
        "ttft_critical_path_p99_ms": cp99,
        "traced_requests": len(cps),
    }
    staggered(plain.engine, " split-engine baseline")
    return goodput_block


def bench_fleet():
    """Fleet serving trajectory (round 11): aggregate tok/s and
    router-side e2e tail vs replica count, plus the disaggregated
    2-prefill + 2-decode split with its streamed-KV volume.

    The fleet needs device MULTIPLICITY (replica sub-meshes) that the
    one-chip bench host lacks, so the ladder runs on the emulated
    8-device mesh in a SUBPROCESS (``scripts/perf_fleet.py
    --bench-lines``) and its ``[bench]`` lines are relayed verbatim into
    this run's stderr tail — ``scripts/bench_compare.py`` then gates
    aggregate tok/s and e2e p99 direction-aware per replica count, like
    every other tracked line. Router/handoff overhead is what the
    emulated ladder prices; chip-level scaling claims wait for a
    multi-chip host."""
    import os
    import pathlib
    import subprocess

    script = pathlib.Path(__file__).resolve().parent / "scripts" / "perf_fleet.py"
    proc = subprocess.run(
        [sys.executable, str(script), "--bench-lines"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if proc.returncode != 0:
        tail = "\n".join(proc.stderr.splitlines()[-5:])
        raise RuntimeError(f"perf_fleet exited {proc.returncode}: {tail}")
    for line in proc.stdout.splitlines():
        if line.startswith("[bench]"):
            _log(line)


def bench_economics():
    """Workload observatory (round 20): the canonical 24h-compressed
    day replayed through a K=4 unified fleet
    (``fleet/loadgen.py``), JOINed into the per-tenant bill
    (``telemetry/economics.py``) — fleet goodput ratio under the paced
    trace, fleet-wide cost per generated token, and the worst tenant's
    SLO burn rate.

    Like ``bench_fleet``, the replay needs device multiplicity, so it
    runs on the emulated 8-device mesh in a subprocess
    (``scripts/replay.py --json``) and its ``[bench]`` line is relayed.
    ``scripts/bench_compare.py`` gates ``goodput_ratio`` (higher),
    ``cost/token`` (lower), and ``worst tenant burn`` (lower — the
    zero-old floor means a clean 0.00 baseline still fails a round
    that starts burning). The returned block also carries the
    conservation verdict: Σ per-tenant device-seconds must equal the
    fleet ledger's device bucket — attribution that invents or drops
    seconds is a bug, not a pricing choice."""
    import os
    import pathlib
    import subprocess

    script = (
        pathlib.Path(__file__).resolve().parent / "scripts" / "replay.py"
    )
    proc = subprocess.run(
        [sys.executable, str(script), "--json"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if proc.returncode != 0:
        tail = "\n".join((proc.stderr or proc.stdout).splitlines()[-5:])
        raise RuntimeError(f"replay exited {proc.returncode}: {tail}")
    res = json.loads(proc.stdout)
    _log(res["bench_line"])
    return {
        k: res[k] for k in (
            "k", "speed", "offered", "admitted", "shed",
            "generated_tokens", "goodput_ratio", "cost_per_token_usd",
            "worst_tenant", "worst_tenant_burn_rate", "conservation_ok",
        )
    }


def bench_autoscale():
    """Elastic fleet (round 23): the canonical day replayed twice under
    identical pacing — a static oracle at the planner's best K (the
    SLO-burn threshold) and the elastic fleet (plan floor fed forward,
    SLO-burn loop above it). Like ``bench_economics``, the replay needs
    device multiplicity, so it runs on the emulated mesh in a
    subprocess and its ``[bench]`` line is relayed.
    ``scripts/bench_compare.py`` gates ``elastic uusd/tok`` (lower),
    ``drain p99`` (lower) and ``planner gap`` (lower); peak/final burn
    vs the oracle print for context only (the settled comparison is
    stable, the 50 ms-sample peak jitters with wall-clock pacing on a
    loaded host — a trajectory gate on it would flake)."""
    import os
    import pathlib
    import subprocess

    script = (
        pathlib.Path(__file__).resolve().parent / "scripts" / "replay.py"
    )
    proc = subprocess.run(
        [sys.executable, str(script), "--autoscale", "--json"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if proc.returncode != 0:
        tail = "\n".join((proc.stderr or proc.stdout).splitlines()[-5:])
        raise RuntimeError(f"autoscale replay exited {proc.returncode}: {tail}")
    res = json.loads(proc.stdout)
    _log(res["bench_line"])
    return {
        k: res[k] for k in (
            "k0", "k_max", "speed", "generated_tokens", "shed",
            "elastic_cost_per_token_usd", "static_cost_per_token_usd",
            "best_static_k", "peak_burn", "static_oracle_peak_burn",
            "worst_tenant_burn_rate", "static_oracle_final_burn",
            "drain_ms_p99", "planner_gap_pct", "decisions",
            "conservation_ok",
        )
    }


def bench_multistep():
    """Multi-step scheduling horizon ladder (round 16): the fused
    ``multi_step`` program (one dispatch per N engine iterations, host
    demoted to an async next-horizon planner) vs today's
    per-iteration loop, N ∈ {1, 2, 4, 8, 16}.

    The ladder is host-loop physics over the emulated 8-device mesh —
    nothing chip-specific — so it runs in a subprocess
    (``scripts/perf_hostloop.py --bench-lines``) whose lines are
    relayed, exactly like ``bench_fleet``. Two regimes per rung: "raw"
    (emulated mesh as-is; owns the structural metrics — host_share,
    steps/dispatch, boundary stall) and "multistep" (a modeled fixed
    per-dispatch cost through the ``engine.dispatch`` seam, the
    BENCH r05 tunneled-chip regime; owns the headline tok/s).
    ``scripts/bench_compare.py`` gates host_share_pct (down) and
    steps_per_dispatch (up) per rung, direction-aware."""
    import os
    import pathlib
    import subprocess

    script = (
        pathlib.Path(__file__).resolve().parent
        / "scripts" / "perf_hostloop.py"
    )
    proc = subprocess.run(
        [sys.executable, str(script), "--bench-lines"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if proc.returncode != 0:
        tail = "\n".join(proc.stderr.splitlines()[-5:])
        raise RuntimeError(f"perf_hostloop exited {proc.returncode}: {tail}")
    for line in proc.stdout.splitlines():
        if line.startswith("[bench]"):
            _log(line)


def bench_kv_economy():
    """KV economy A/B (round 15): the SAME 80%-prefix-overlap traffic
    mix through K=4 paged replicas, prefix-aware (``KvEconomy`` wired:
    placement scores predicted prefix-hit tokens, cold chains demote
    HBM → host RAM, placed requests promote back on admission) vs
    prefix-blind (round-11 load + burn score only).

    Placement quality and the tier ladder are host/router machinery
    over replica MULTIPLICITY, nothing chip-specific, so the A/B runs
    on the emulated 8-device mesh in a subprocess
    (``scripts/perf_kv_economy.py --bench-lines``) whose lines are
    relayed, exactly like ``bench_fleet``. Tracked per config:
    aggregate tok/s and fleet TTFT p99, plus the aware side's realized
    prefix-hit rate, tier-miss rate, and kv bytes moved per request —
    all gated direction-aware by ``scripts/bench_compare.py``."""
    import os
    import pathlib
    import subprocess

    script = (
        pathlib.Path(__file__).resolve().parent
        / "scripts" / "perf_kv_economy.py"
    )
    proc = subprocess.run(
        [sys.executable, str(script), "--bench-lines"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if proc.returncode != 0:
        tail = "\n".join(proc.stderr.splitlines()[-5:])
        raise RuntimeError(f"perf_kv_economy exited {proc.returncode}: {tail}")
    for line in proc.stdout.splitlines():
        if line.startswith("[bench]"):
            _log(line)


def bench_compression():
    """Comm compression A/B (round 22): the quantized TP all-reduce
    (plain vs int8 block-scaled mixed engine, with the greedy-agreement
    check the drift oracle holds at 100%) and the compressed KV tier
    ladder (K=2 fleet, ``int8_delta`` page codec — wire vs raw kB per
    request and their ratio).

    Codec passes and wire accounting are host machinery, nothing
    chip-specific, so the A/B runs on the emulated 8-device mesh in a
    subprocess (``scripts/perf_compression.py --bench-lines``) whose
    lines are relayed, exactly like ``bench_fleet``. All four numbers
    (compressed tok/s, q8 agreement, kv wire kB/req, compression
    ratio) are gated direction-aware by ``scripts/bench_compare.py``."""
    import os
    import pathlib
    import subprocess

    script = (
        pathlib.Path(__file__).resolve().parent
        / "scripts" / "perf_compression.py"
    )
    proc = subprocess.run(
        [sys.executable, str(script), "--bench-lines"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if proc.returncode != 0:
        tail = "\n".join(proc.stderr.splitlines()[-5:])
        raise RuntimeError(f"perf_compression exited {proc.returncode}: {tail}")
    for line in proc.stdout.splitlines():
        if line.startswith("[bench]"):
            _log(line)


def bench_tenancy():
    """Tenancy (round 12): zero-downtime weight hot-swap under load at
    125M, plus the multi-LoRA mixed-batch ladder.

    The device part serves a saturated queue through the 125M MIXED
    engine while drain-mode ``swap_weights`` rollouts land every few
    dispatches — tracked numbers are the swap stall (the stage → commit
    serve gap, from the engine's ``engine.swap_commit`` events) p50/p99
    and throughput during the rollout vs undisturbed. The warm pass
    commits one swap and serves through the swapped-in weights first:
    the staged tree's layout differs from the born-init layout, and the
    one-time post-commit recompile must not land in the timed rollout.

    The multi-LoRA ladder (mixed-adapter vs solo tok/s at 1/4/16
    adapters) prices host-side pool machinery, nothing chip-specific, so
    it runs on the emulated 8-device mesh in a subprocess
    (``scripts/perf_tenancy.py --bench-lines``) whose lines are relayed,
    exactly like ``bench_fleet``.
    """
    import dataclasses
    import os
    import pathlib
    import subprocess
    import time as _time

    import flax.linen as nn

    from learning_jax_sharding_tpu.models.serving import make_continuous_engine

    cfg = dataclasses.replace(CONFIG_125M, max_seq_len=1024)
    mesh = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
    rng = np.random.default_rng(5)
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(0), np.zeros((8, 64), np.int32)
        )["params"]
    )
    new_params = jax.jit(
        lambda t: jax.tree.map(lambda x: x * (1.0 + 1e-3), t)
    )(params)
    NREQ, NEW = 16, 32
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(64,)).astype(np.int32)
        for _ in range(NREQ)
    ]
    serve = make_continuous_engine(
        cfg, mesh, RULES_DP_TP, batch_size=8, max_new_tokens=NEW,
        refill_chunk=64, inference_dtype=jnp.bfloat16, mixed=True,
        token_budget=128 + 8, decode_block_steps=NEW,
    )
    eng = serve.engine

    def drive(reqs, swap_every=None, versions=()):
        plen = {}
        for p in reqs:
            plen[eng.add_request(p)] = len(p)
        vq = list(versions)
        steps = 0
        t0 = _time.perf_counter()
        while eng.has_work():
            if (
                vq and swap_every and steps % swap_every == swap_every - 1
                and not eng.swap_pending
            ):
                v = vq.pop(0)
                eng.swap_weights(
                    new_params if v % 2 else params, version=v,
                )
            eng.step(params)
            steps += 1
        dt = _time.perf_counter() - t0
        gen = sum(
            len(t) - plen[rid] for rid, t in eng.pop_finished().items()
            if not hasattr(t, "status")
        )
        return dt, gen

    drive(prompts[:9])                       # warm: first_refill + mixed step
    eng.swap_weights(new_params, version=1)  # warm the stage + commit path
    while eng.has_work():
        eng.step(params)
    drive(prompts[:9])                       # warm the post-commit layout
    dt0, gen0 = drive(prompts)               # undisturbed baseline
    eng.recorder.clear()
    dt, gen = drive(prompts, swap_every=2, versions=[2, 3, 4, 5, 6])
    stalls = np.asarray([
        e["stall_s"] for e in eng.recorder.events("engine.swap_commit")
    ])
    _log(
        f"[bench] 125M hot-swap under load: "
        f"swap stall p50 {np.percentile(stalls, 50) * 1e3:,.0f} ms, "
        f"swap stall p99 {np.percentile(stalls, 99) * 1e3:,.0f} ms "
        f"({len(stalls)} swaps, {gen / dt:,.0f} tok/s during rollout vs "
        f"{gen0 / dt0:,.0f} tok/s undisturbed)"
    )

    script = pathlib.Path(__file__).resolve().parent / "scripts" / "perf_tenancy.py"
    proc = subprocess.run(
        [sys.executable, str(script), "--bench-lines"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if proc.returncode != 0:
        tail = "\n".join(proc.stderr.splitlines()[-5:])
        raise RuntimeError(f"perf_tenancy exited {proc.returncode}: {tail}")
    for line in proc.stdout.splitlines():
        if line.startswith("[bench]"):
            _log(line)


def _device_ready(timeout_s: float = 600.0) -> bool:
    """Probe the device with a tiny op under a watchdog.

    The tunneled TPU in this environment can wedge (every device op hangs)
    after an earlier process died mid-operation; without this guard a wedged
    tunnel would hang the whole benchmark instead of failing loudly.
    """
    import threading

    ok = threading.Event()
    err: list[BaseException] = []

    def probe():
        try:
            np.asarray(jnp.ones((8, 8)).sum())
        except BaseException as e:
            err.append(e)
            raise
        ok.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    while t.is_alive() and not err:
        t.join(1.0)
        timeout_s -= 1.0
        if timeout_s <= 0:
            break
    if err:  # a real error, not a hang — surface it with its cause
        raise err[0]
    return ok.is_set()


def _diagnosis_block(headline_axis_volume):
    """The round-7 diagnosis summary for the JSON line: predicted HBM for
    the 125M bench configuration vs the chip's LIVE watermark (devview —
    guarded: backends without memory stats report plan-only), plus the
    headline executable's per-mesh-axis collective bytes. Machine-readable
    per round, so drifts in either become bench_compare-visible facts."""
    import dataclasses as _dc

    from learning_jax_sharding_tpu.ops.flash_attention import (
        make_flash_attn_fn,
    )
    from learning_jax_sharding_tpu.telemetry import memory_report
    from learning_jax_sharding_tpu.utils.memory import memory_plan

    cfg = _dc.replace(CONFIG_125M, attn_fn=make_flash_attn_fn())
    plan = memory_plan(cfg, 8, 1024, donate_state=False)
    mem = memory_report(plan)
    block = {
        "memory_predicted_bytes": plan.total,
        "memory_actual_available": mem["actual_available"],
        "memory_actual_peak_bytes": mem.get("actual_peak_bytes"),
        "memory_predicted_over_actual": mem.get("predicted_over_actual"),
        "memory_hbm_bytes": mem.get("hbm_bytes"),
        "headline_collective_bytes_per_axis": headline_axis_volume,
    }
    actual = block["memory_actual_peak_bytes"]
    _log(
        f"[bench] diagnosis: 125M step predicted "
        f"{plan.total / 1e9:.2f} GB"
        + (
            f", device peak {actual / 1e9:.2f} GB "
            f"(predicted/actual {block['memory_predicted_over_actual']:.2f})"
            if actual else ", no live memory stats (plan-only)"
        )
    )
    return block


def _phase_telemetry(watch, before, label):
    """Delta of a CompileWatch report across one phase → a log line plus
    the dict that lands in the JSON telemetry block: compile seconds are
    the one-time cost the steady-state numbers exclude, and the split
    makes 'how much of this run was XLA' a recorded fact per round."""
    after = watch.report()
    delta = {
        k: after[k] - before[k]
        for k in after if isinstance(after[k], (int, float))
    }
    _log(
        f"[bench] telemetry {label}: {delta['backend_compiles']} backend "
        f"compiles, {delta['backend_compile_seconds']:.2f} s compile "
        f"({delta['traces']} traces, {delta['trace_seconds']:.2f} s)"
    )
    return delta


def main():
    from learning_jax_sharding_tpu.telemetry import CompileWatch

    if not _device_ready():
        _log("[bench] FATAL: device did not answer a trivial op (tunnel wedged?)")
        sys.exit(1)
    dev = jax.devices()[0]
    _log(f"[bench] device: {dev.device_kind} ({dev.platform}), "
         f"peak bf16 {device_peak_flops(dev)}")

    watch = CompileWatch().start()
    base_report = watch.report()
    ours = bench_attention(jnp.bfloat16, "case6 attention (ours, bf16)")
    headline_compile = _phase_telemetry(
        watch, base_report, "case6 attention headline phase"
    )
    baseline = bench_attention(jnp.float32, "case6 attention (reference-faithful, fp32)")

    try:
        bench_transformer_125m()
    except Exception as e:  # context only — never break the headline line
        _log(f"[bench] 125M transformer bench skipped: {type(e).__name__}: {e}")
    try:
        bench_longcontext()
    except Exception as e:
        _log(f"[bench] long-context bench skipped: {type(e).__name__}: {e}")
    try:
        bench_decode_125m()
    except Exception as e:
        _log(f"[bench] 125M decode bench skipped: {type(e).__name__}: {e}")
    try:
        bench_decode_1p4b()
    except Exception as e:
        _log(f"[bench] 1.4B decode bench skipped: {type(e).__name__}: {e}")
    try:
        goodput_block = bench_serving_125m()
    except Exception as e:
        _log(f"[bench] serving bench skipped: {type(e).__name__}: {e}")
        goodput_block = None
    try:
        bench_fleet()
    except Exception as e:
        _log(f"[bench] fleet bench skipped: {type(e).__name__}: {e}")
    try:
        bench_multistep()
    except Exception as e:
        _log(f"[bench] multistep bench skipped: {type(e).__name__}: {e}")
    try:
        bench_kv_economy()
    except Exception as e:
        _log(f"[bench] kv economy bench skipped: {type(e).__name__}: {e}")
    try:
        bench_compression()
    except Exception as e:
        _log(f"[bench] compression bench skipped: {type(e).__name__}: {e}")
    try:
        bench_tenancy()
    except Exception as e:
        _log(f"[bench] tenancy bench skipped: {type(e).__name__}: {e}")
    try:
        bench_moe_125m()
    except Exception as e:
        _log(f"[bench] MoE bench skipped: {type(e).__name__}: {e}")
    try:
        bench_moe_headline()
    except Exception as e:
        _log(f"[bench] MoE headline bench skipped: {type(e).__name__}: {e}")
    try:
        bench_reference_configs()
    except Exception as e:
        _log(f"[bench] reference-config bench skipped: {type(e).__name__}: {e}")
    try:
        shardflow_block = bench_shardflow()
    except Exception as e:
        _log(f"[bench] shardflow bench skipped: {type(e).__name__}: {e}")
        shardflow_block = None
    try:
        layout_search_block = bench_layout_search()
    except Exception as e:
        _log(f"[bench] layout_search bench skipped: {type(e).__name__}: {e}")
        layout_search_block = None
    try:
        memflow_block = bench_memflow()
    except Exception as e:
        _log(f"[bench] memflow bench skipped: {type(e).__name__}: {e}")
        memflow_block = None
    try:
        commscope_block = bench_commscope()
    except Exception as e:
        _log(f"[bench] commscope bench skipped: {type(e).__name__}: {e}")
        commscope_block = None
    try:
        economics_block = bench_economics()
    except Exception as e:
        _log(f"[bench] economics bench skipped: {type(e).__name__}: {e}")
        economics_block = None
    try:
        topology_block = bench_topology()
    except Exception as e:
        _log(f"[bench] topology bench skipped: {type(e).__name__}: {e}")
        topology_block = None
    try:
        autoscale_block = bench_autoscale()
    except Exception as e:
        _log(f"[bench] autoscale bench skipped: {type(e).__name__}: {e}")
        autoscale_block = None

    watch.stop()
    run_report = watch.report()
    try:
        diagnosis = _diagnosis_block(ours["axis_volume"])
    except Exception as e:  # context only — never break the headline line
        _log(f"[bench] diagnosis block skipped: {type(e).__name__}: {e}")
        diagnosis = None
    ours_tf, base_tf = ours["tflops"], baseline["tflops"]
    vs_baseline = (ours_tf / base_tf) if (ours_tf and base_tf) else None
    print(json.dumps({
        "metric": "case6_attention_tflops_per_chip",
        "value": round(ours_tf, 3) if ours_tf else None,
        "unit": "TFLOP/s/chip",
        "vs_baseline": round(vs_baseline, 3) if vs_baseline else None,
        # Per-phase telemetry (compile_watch): one-time compile cost vs
        # the steady-state per-iteration time the headline measures, and
        # the headline executable's collective inventory.
        "telemetry": {
            "headline_steady_seconds_per_forward": (
                round(ours["seconds_per_forward"], 9)
            ),
            "headline_backend_compiles": (
                headline_compile["backend_compiles"]
            ),
            "headline_backend_compile_seconds": round(
                headline_compile["backend_compile_seconds"], 3
            ),
            "headline_collectives": ours["collectives"],
            "run_backend_compiles": run_report["backend_compiles"],
            "run_backend_compile_seconds": round(
                run_report["backend_compile_seconds"], 3
            ),
            "run_trace_seconds": round(run_report["trace_seconds"], 3),
            "monitoring_available": run_report["monitoring_available"],
        },
        # Round-7 diagnosis: predicted-vs-actual memory + per-axis
        # collective bytes (telemetry.devview).
        "diagnosis": diagnosis,
        # Round-13 analyzer self-check: the cost model's predicted step
        # time vs the measured one for the tracked shapes
        # (analysis.shardflow + costmodel; gated by bench_compare).
        "shardflow": shardflow_block,
        # Round-17 layout-search closed loop: the searched-vs-hand
        # priced gap for the tracked train step and the measured
        # confirmation on the two compiled layouts (analysis/
        # layout_search.py; gated by bench_compare's `layout gap` /
        # `layout err` patterns).
        "layout_search": layout_search_block,
        # Round-18 memflow reconciliation: the static liveness
        # analyzer's per-entry predicted peak vs XLA's memory_analysis
        # on the searchable entries (analysis/memflow.py; gated by
        # bench_compare's `memflow err` pattern) — the accuracy bound
        # on the layout search's HBM budget gate.
        "memflow": memflow_block,
        # Round-19 comm observatory: measured per-axis α–β link
        # profiles (commscope calibration ladder) and the serving
        # window's realized comm/compute overlap decomposition
        # (telemetry/commscope.py; gated by bench_compare's
        # `axis bandwidth` / `comm fit err` / `exposed comm` /
        # `comm prediction err` patterns).
        "commscope": commscope_block,
        # Round-20 workload observatory: the canonical day replayed
        # through a K=4 fleet, priced per tenant (fleet/loadgen.py +
        # telemetry/economics.py; gated by bench_compare's
        # `goodput_ratio` / `cost/token` / `worst tenant burn`
        # patterns), with the tier-1 conservation verdict.
        "economics": economics_block,
        # Round-21 topology observatory: the two-tier interconnect
        # model's reconcile errors per searchable entry, the train
        # step's priced DCN bytes/token and overlap prediction gap, and
        # the seeded flat-vs-topo argmin canary (analysis/topology.py;
        # gated by bench_compare's `topo err` / `dcn B/token` /
        # `overlap gap` / `topo argmin gap` patterns).
        "topology": topology_block,
        # Round-23 elastic fleet: the canonical day on the autoscaled
        # fleet vs the planner's best static K under identical pacing
        # (fleet/autoscaler.py + fleet/capacity.py; gated by
        # bench_compare's `elastic uusd/tok` / `drain p99` /
        # `planner gap` patterns), with burn-vs-oracle context.
        "autoscale": autoscale_block,
        # Round-14 goodput ledger: where the tracked serving window's
        # wall-clock went (exclusive buckets, Σ == wall reconciled),
        # host_share / goodput_ratio vs the decode roofline, and the
        # trace-derived TTFT critical-path tails — the measured
        # anatomy of ROADMAP item 1's host-vs-device gap.
        "goodput": goodput_block,
    }), flush=True)


if __name__ == "__main__":
    main()
