"""Flax models with logical partitioning: attention, feed-forward, transformer."""
