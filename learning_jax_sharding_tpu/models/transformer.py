"""Composed transformer (case 7): the FF + attention blocks as one model.

The reference stops at a standalone attention module
(`/root/reference/case6_attention.py:42-143`) and a standalone GSPMD
feed-forward matmul (`/root/reference/case4_gspmd_ff.py:36-58`); the driver's
north star composes them into "a minimal transformer training step under a 2D
(data × model) mesh … ≥45% MFU" (`/root/repo/BASELINE.json`). This module is
that composition:

* :class:`FeedForward` — the case-4 DP×MP projection as a module: up-kernel
  logically ``(EMBED, MLP)`` (column-parallel), down-kernel ``(MLP, EMBED)``
  (row-parallel) — under ``RULES_DP_TP`` each token crosses the model axis
  once per block, the GSPMD §3.2 pattern;
* :class:`TransformerBlock` — pre-LayerNorm attention + FF with residuals;
* :class:`Transformer` — token embedding, N blocks (optionally rematerialized),
  final norm, logits head: the 125M-parameter flagship configuration of
  `BASELINE.json` ("case4+case6 composed 125M transformer").

Everything is dtype-parameterized: bf16 compute / fp32 params is the TPU MXU
sweet spot and the default for benchmarks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from learning_jax_sharding_tpu.models.attention import MultiHeadAttention
from learning_jax_sharding_tpu.parallel.logical import (
    BATCH,
    EMBED,
    HIDDEN,
    LAYERS,
    MLP,
    SEQ,
    VOCAB,
)


def resolve_remat_policy(name: Optional[str]):
    """Named ``jax.checkpoint`` policies for block rematerialization.

    ``None``/``"nothing"`` — save nothing, recompute everything (the
    ``jax.checkpoint`` default; minimum memory, ~1/3 extra FLOPs);
    ``"dots"`` — save matmul outputs, recompute only elementwise/softmax work
    (most of the memory win at a fraction of the recompute);
    ``"dots_no_batch"`` — save only batch-free matmuls (i.e. none in a
    transformer block: everything carries the batch dim, so this is the
    conservative middle ground XLA offload papers use).
    """
    if name is None or name == "nothing":
        return None
    policies = {
        "dots": jax.checkpoint_policies.checkpoint_dots,
        "dots_no_batch": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    }
    if name not in policies:
        raise ValueError(
            f"unknown remat_policy {name!r}: expected None, 'nothing', "
            f"'dots', or 'dots_no_batch'"
        )
    return policies[name]


class _CompressedDense(nn.Module):
    """Param-compatible stand-in for a projection ``nn.Dense`` whose TP
    reduction ships int8 blocks instead of floats.

    Declares the identical ``kernel`` (and ``bias``) parameters — same
    name, shape, dtype, init, and logical axes — so a checkpoint or a
    born-sharded init transfers verbatim across the ``comm_compress_fn``
    flag, exactly like :class:`~..models.quantize.Int4Dense` mirrors its
    plain twin. The compute is delegated to ``compress_fn`` (built by
    ``parallel.compression.make_compressed_matmul_fn``), which reads the
    live :class:`~..parallel.compression.CommCompression` policy at TRACE
    time: compression on → shard_map with quantized all-gathers;
    off (never configured, axis not wire-bound, or drift-tripped) → the
    very ``dot_general`` ``nn.Dense`` lowers to, bit-identical.
    """

    features: int
    kernel_axes: tuple
    use_bias: bool = False
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    compress_fn: Any = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param(
            "kernel",
            nn.with_logical_partitioning(self.kernel_init, self.kernel_axes),
            (x.shape[-1], self.features),
            self.param_dtype,
        )
        x = x.astype(self.dtype)
        kernel = kernel.astype(self.dtype)
        y = self.compress_fn(x, kernel, kernel_axes=tuple(self.kernel_axes))
        if self.use_bias:
            bias = self.param(
                "bias",
                nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), (self.kernel_axes[-1],)
                ),
                (self.features,),
                self.param_dtype,
            )
            y = y + bias.astype(y.dtype)
        return y


class FeedForward(nn.Module):
    """Position-wise FF: up-project → GELU → down-project.

    The case-4 feed-forward (`/root/reference/case4_gspmd_ff.py:36-58`) grown
    into a real module: with MLP→model rules the up-projection is
    column-parallel and the down-projection row-parallel, so its output
    arrives as partial sums that GSPMD all-reduces (or reduce-scatters under
    sequence sharding) — one collective per block, the minimum for TP.
    """

    features: int
    hidden: int
    use_bias: bool = False
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    quantization: Optional[str] = None       # "int4" → fused-kernel serving
    quantization_group: int = 128
    quantized_matmul_fn: Optional[Callable] = None
    comm_compress_fn: Optional[Callable] = None  # int8-wire TP reduction for
                                  # the down projection (the block's one
                                  # all-reduce site); built by
                                  # parallel.compression.make_compressed_matmul_fn

    def _dense(self, features: int, kernel_axes, name: str):
        from learning_jax_sharding_tpu.models.quantize import projection_dense

        return projection_dense(
            quantization=self.quantization,
            features=features,
            kernel_axes=kernel_axes,
            use_bias=self.use_bias,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=self.kernel_init,
            group_size=self.quantization_group,
            quantized_matmul_fn=self.quantized_matmul_fn,
            name=name,
        )

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.with_logical_constraint(x, (BATCH, SEQ, EMBED))
        k = x.shape[-1]
        if self._use_fused_ff(k):
            # Whole-FF fused kernel: up, GELU, and down in ONE pallas call —
            # the hidden activation never leaves VMEM, and decode's serial
            # launch chain shrinks by one dependent kernel per block
            # (PERF.md "int4 decode: where the time actually goes").
            # Single-device/replicated serving only: under TP the hidden dim
            # is sharded and the per-projection shard_map path applies.
            from learning_jax_sharding_tpu.models.quantize import Int4ProjParams
            from learning_jax_sharding_tpu.ops.int4_ff import int4_ff

            g = self.quantization_group
            q4_up, s_up = Int4ProjParams(
                k // 2, self.hidden, k // min(g, k), name="up"
            )()
            q4_dn, s_dn = Int4ProjParams(
                self.hidden // 2, self.features,
                self.hidden // min(g, self.hidden), name="down",
            )()
            out = int4_ff(
                x.astype(self.dtype), q4_up, s_up, q4_dn, s_dn, group=g
            )
            return nn.with_logical_constraint(out, (BATCH, SEQ, EMBED))
        h = self._dense(self.hidden, (EMBED, MLP), "up")(x)
        h = nn.with_logical_constraint(h, (BATCH, SEQ, HIDDEN))
        h = nn.gelu(h)
        if self.comm_compress_fn is not None and self.quantization is None:
            # The down projection is the block's one all-reduce site (the
            # up projection is column-parallel, collective-free): swap in
            # the param-identical compressed dense so the reduction ships
            # int8 blocks when the engine's CommCompression policy is live.
            out = _CompressedDense(
                features=self.features,
                kernel_axes=(MLP, EMBED),
                use_bias=self.use_bias,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=self.kernel_init,
                compress_fn=self.comm_compress_fn,
                name="down",
            )(h)
        else:
            out = self._dense(self.features, (MLP, EMBED), "down")(h)
        return nn.with_logical_constraint(out, (BATCH, SEQ, EMBED))

    def _use_fused_ff(self, k: int) -> bool:
        from learning_jax_sharding_tpu.ops.int4_ff import int4_ff_eligible

        return (
            self.quantization == "int4"
            and self.quantized_matmul_fn is None
            and not self.use_bias
            and self.features == k
            and int4_ff_eligible(k, self.hidden, self.quantization_group)
        )


def make_norm(kind: str, dtype, param_dtype, name: str, eps: float = 1e-6) -> nn.Module:
    """``"layernorm"`` (GPT-2 style, scale+bias) or ``"rmsnorm"`` (LLaMA
    style, scale only — one fewer reduction and parameter vector; the modern
    default). Scale/bias carry the ``(EMBED,)`` logical axis either way."""
    if kind == "layernorm":
        return nn.LayerNorm(
            epsilon=eps,
            dtype=dtype,
            param_dtype=param_dtype,
            scale_init=nn.with_logical_partitioning(nn.initializers.ones_init(), (EMBED,)),
            bias_init=nn.with_logical_partitioning(nn.initializers.zeros_init(), (EMBED,)),
            name=name,
        )
    if kind == "rmsnorm":
        return nn.RMSNorm(
            epsilon=eps,
            dtype=dtype,
            param_dtype=param_dtype,
            scale_init=nn.with_logical_partitioning(nn.initializers.ones_init(), (EMBED,)),
            name=name,
        )
    raise ValueError(f"unknown norm {kind!r}: expected 'layernorm' or 'rmsnorm'")


class FusedNorm(nn.Module):
    """Param-compatible replacement for :func:`make_norm` backed by the
    Pallas fused residual+norm kernel (``ops/fused_norm.py``): identical
    ``scale``/``bias`` param names, shapes, and ``(EMBED,)`` logical axes
    as ``nn.LayerNorm``/``nn.RMSNorm``, so checkpoints transfer verbatim
    across the ``fused_norm`` flag. Called as ``module(x, resid)`` →
    ``(normed, x + resid)`` — the whole block boundary (residual add +
    norm) in one HBM pass. Single-device oriented: GSPMD cannot partition
    the custom call, so multi-device training should keep the flag off
    (the math is identical either way)."""

    kind: str
    eps: float = 1e-6
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, resid=None):
        from learning_jax_sharding_tpu.ops.fused_norm import (
            fused_residual_norm,
        )

        m = x.shape[-1]
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones_init(), (EMBED,)),
            (m,), self.param_dtype,
        )
        bias = None
        if self.kind == "layernorm":
            bias = self.param(
                "bias",
                nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), (EMBED,)
                ),
                (m,), self.param_dtype,
            )
        x = x.astype(self.dtype)
        if resid is not None:
            resid = resid.astype(self.dtype)
        return fused_residual_norm(
            x, resid, scale, bias, eps=self.eps, kind=self.kind
        )


class TransformerBlock(nn.Module):
    """Pre-LN block: x + Attn(LN(x)); x + FF(LN(x)).

    The composition BASELINE.json names "case4+case6": case-6's logically
    partitioned attention and case-4's DP×MP feed-forward, joined by residuals
    and LayerNorms (neither exists in the reference).
    """

    features: int
    num_heads: int
    head_dim: int
    hidden: int
    num_kv_heads: Optional[int] = None
    rope: bool = False
    rope_theta: float = 10_000.0
    window: Optional[int] = None
    dropout_rate: float = 0.0
    causal: bool = True
    use_bias: bool = False
    norm_eps: float = 1e-6
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    attn_fn: Optional[Callable] = None
    remat_attention: bool = False
    num_experts: int = 0          # >0 swaps the dense FF for a routed MoE FF
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_dispatch: str = "einsum"  # "einsum" (EP-shardable) | "scatter"
                                  # (scatter/gather, single-device) |
                                  # "alltoall" (explicit EP exchange; needs
                                  # moe_dispatch_fn — moe.py/moe_dispatch.py)
    moe_dispatch_fn: Optional[Callable] = None
    decode: bool = False          # KV-cached autoregressive attention
    max_decode_len: int = 0
    kv_cache_dtype: Optional[Any] = None  # decode-cache storage: None =
                                  # compute dtype; jnp.int8 = quantized cache
    decode_attention: str = "auto"  # "dense" | "blocked" | "auto" (see
                                  # models.attention.MultiHeadAttention)
    decode_block_k: Optional[int] = None
    decode_attn_fn: Optional[Callable] = None
    decode_ragged: bool = False   # per-row cache positions (mixed-length
                                  # serving; see models.attention)
    decode_paged: bool = False    # paged KV pools + host-owned block tables
    decode_page_count: int = 0
    quantization: Optional[str] = None   # "int4" → fused-kernel projections
    quantization_group: int = 128
    quantized_matmul_fn: Optional[Callable] = None
    comm_compress_fn: Optional[Callable] = None  # int8-wire FF down reduction
    norm: str = "layernorm"       # "layernorm" | "rmsnorm"
    fused_norm: bool = False      # block boundaries through the Pallas
                                  # fused residual+norm kernel (param-tree
                                  # identical; see FusedNorm)
    scan: bool = False            # under nn.scan: return (x, None) pairs

    def _norm(self, name: str):
        if self.fused_norm:
            return FusedNorm(
                kind=self.norm, eps=self.norm_eps, dtype=self.dtype,
                param_dtype=self.param_dtype, name=name,
            )
        mod = make_norm(
            self.norm, self.dtype, self.param_dtype, name, self.norm_eps
        )
        return lambda x, resid=None: (
            (mod(x), x) if resid is None else (mod(x + resid), x + resid)
        )

    @nn.compact
    def __call__(
        self, x: jax.Array, deterministic: bool = True, chunk_lengths=None
    ):
        x = nn.with_logical_constraint(x, (BATCH, SEQ, EMBED))
        h, _ = self._norm("ln_attn")(x)
        attn_out = MultiHeadAttention(
            features=self.features,
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            num_kv_heads=self.num_kv_heads,
            rope=self.rope,
            rope_theta=self.rope_theta,
            window=self.window,
            dropout_rate=self.dropout_rate,
            causal=self.causal,
            use_bias=self.use_bias,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            attn_fn=self.attn_fn,
            remat_attention=self.remat_attention,
            decode=self.decode,
            max_decode_len=self.max_decode_len,
            kv_cache_dtype=self.kv_cache_dtype,
            decode_attention=self.decode_attention,
            decode_block_k=self.decode_block_k,
            decode_attn_fn=self.decode_attn_fn,
            decode_ragged=self.decode_ragged,
            decode_paged=self.decode_paged,
            decode_page_count=self.decode_page_count,
            quantization=self.quantization,
            quantization_group=self.quantization_group,
            quantized_matmul_fn=self.quantized_matmul_fn,
            name="attn",
        )(h, deterministic=deterministic, chunk_lengths=chunk_lengths)
        # The block boundary: residual add + norm — ONE fused HBM pass
        # under fused_norm, the plain pair otherwise (identical math).
        h, x = self._norm("ln_ff")(attn_out, x)
        if self.num_experts > 0:
            from learning_jax_sharding_tpu.models.moe import MoEFeedForward

            x = x + MoEFeedForward(
                features=self.features,
                hidden=self.hidden,
                num_experts=self.num_experts,
                top_k=self.moe_top_k,
                capacity_factor=self.moe_capacity_factor,
                dispatch=self.moe_dispatch,
                dispatch_fn=self.moe_dispatch_fn,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="moe",
            )(h, deterministic=deterministic)
        else:
            x = x + FeedForward(
                features=self.features,
                hidden=self.hidden,
                use_bias=self.use_bias,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                quantization=self.quantization,
                quantization_group=self.quantization_group,
                quantized_matmul_fn=self.quantized_matmul_fn,
                comm_compress_fn=self.comm_compress_fn,
                name="ff",
            )(h)
        x = nn.with_logical_constraint(x, (BATCH, SEQ, EMBED))
        # nn.scan's carry protocol wants (carry, per-step output) pairs.
        return (x, None) if self.scan else x


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Model hyperparameters (the reference hard-codes its dims inline,
    `/root/reference/case6_attention.py:149-151`; SURVEY.md §5 asks for a
    config object)."""

    vocab_size: int = 50304          # GPT-2 vocab rounded up to a 128 multiple
    num_layers: int = 12
    features: int = 768
    num_heads: int = 12
    head_dim: int = 64
    num_kv_heads: Optional[int] = None  # < num_heads → GQA; 1 → MQA
    rope: bool = False               # rotary positions instead of the learned table
    rope_theta: float = 10_000.0
    window: Optional[int] = None     # causal sliding-window attention size
    hidden: int = 3072
    max_seq_len: int = 1024
    dropout_rate: float = 0.0
    causal: bool = True
    use_bias: bool = False           # biases on all projections (GPT-2 style)
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False              # rematerialize each block's activations
    remat_policy: Optional[str] = None  # what remat SAVES: None/'nothing'
                                     # (recompute all), 'dots', 'dots_no_batch'
                                     # (see resolve_remat_policy)
    remat_attention: bool = False    # rematerialize only the O(S²) attention
                                     # internals (cheap; lifts the batch cap)
    scan_layers: bool = False        # one nn.scan'd stacked block instead of
                                     # N unrolled blocks: O(1) compile time in
                                     # depth, params gain a leading (LAYERS,)
                                     # dim; math is identical (tests prove it)
    attn_fn: Optional[Callable] = None
    num_experts: int = 0             # >0: MoE FF in every block (EP over mesh)
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_dispatch: str = "einsum"     # routing implementation (models/moe.py):
                                     # "einsum" shards under EP rules;
                                     # "scatter" deletes the O(E*C*M*T) routing
                                     # FLOPs via scatter/gather (1-device);
                                     # "alltoall" explicit EP exchange (set
                                     # moe_dispatch_fn = make_moe_a2a_fn(mesh))
    moe_dispatch_fn: Optional[Callable] = None
    norm: str = "layernorm"          # "layernorm" | "rmsnorm"
    fused_norm: bool = False         # block boundaries (residual add + norm)
                                     # through the Pallas fused kernel
                                     # (ops/fused_norm.py); param-tree
                                     # identical to the plain path, so the
                                     # flag can flip on existing checkpoints.
                                     # Single-device oriented (GSPMD cannot
                                     # partition the custom call)
    decode: bool = False             # inference mode: KV cache, chunked input
    kv_cache_dtype: Optional[Any] = None  # decode KV-cache storage dtype:
                                     # None = compute dtype; jnp.int8 =
                                     # quantized cache with per-(token, head)
                                     # scales (~half the cache bytes of bf16)
    decode_attention: str = "auto"   # decode-attention backend: "dense"
                                     # (attend the whole cache buffer),
                                     # "blocked" (length-aware Pallas kernel,
                                     # ops/decode_attention.py), or "auto"
                                     # (blocked on TPU, dense elsewhere)
    decode_block_k: Optional[int] = None  # blocked-backend cache block size
    decode_attn_fn: Optional[Callable] = None  # mesh-aware blocked-kernel
                                     # override (make_decode_attn_fn);
                                     # injected by the serving entry points
    decode_ragged: bool = False      # per-row cache positions: mixed-length
                                     # prompt batches serve at each row's own
                                     # length (ragged prefill + independent
                                     # row advance; models.attention)
    decode_paged: bool = False       # PAGED KV cache: per-layer physical page
                                     # POOLS (decode_page_count pages of
                                     # decode_block_k tokens each) indirected
                                     # through per-row block tables — cache
                                     # HBM scales with allocated pages, not
                                     # B × max_seq_len. Requires decode_ragged
                                     # + the blocked backend + an explicit
                                     # decode_block_k (the page size); the
                                     # host allocator owns the tables
                                     # (models/serving.py)
    decode_page_count: int = 0       # physical pages per layer pool, incl.
                                     # the reserved scratch page 0
    quantization: Optional[str] = None  # "int4": every projection consumes a
                                     # quantize_tree(bits=4) tree verbatim
                                     # through the fused dequant-matmul
                                     # kernel (serving path; ops/int4_matmul)
    quantization_group: int = 128    # must match quantize_tree group_size
    quantized_matmul_fn: Optional[Callable] = None  # mesh-aware fused-int4
                                     # matmul (make_int4_matmul_fn); injected
                                     # by make_generate_fn on >1-device meshes
    comm_compress_fn: Optional[Callable] = None  # int8-wire TP reduction for
                                     # the FF down projection
                                     # (parallel/compression.py's
                                     # make_compressed_matmul_fn); injected by
                                     # ContinuousEngine(comm_compression=...);
                                     # param-tree identical to the plain path

    def __post_init__(self):
        # Fail fast on typos; 'nothing' IS the default, so only a policy that
        # changes behavior demands remat=True.
        if resolve_remat_policy(self.remat_policy) is not None and not self.remat:
            raise ValueError(
                "remat_policy is set but remat=False — the policy would "
                "be silently ignored; set remat=True (or drop the policy)"
            )
        if self.decode_paged:
            if not self.decode_ragged:
                raise ValueError(
                    "decode_paged requires decode_ragged=True (per-row "
                    "cache positions drive the block tables)"
                )
            if not self.decode_block_k:
                raise ValueError(
                    "decode_paged requires an explicit decode_block_k — "
                    "it is the page size"
                )
            if self.max_seq_len % self.decode_block_k:
                raise ValueError(
                    f"max_seq_len ({self.max_seq_len}) must be a multiple "
                    f"of the page size ({self.decode_block_k})"
                )
            if self.decode_page_count < 2:
                raise ValueError(
                    "decode_page_count must be >= 2 (page 0 is the "
                    "reserved scratch page)"
                )

    def train_step_flops(self, batch: int, seq: int) -> float:
        """Analytic model FLOPs of one train step (fwd + bwd ≈ 3× fwd).

        XLA's ``cost_analysis`` undercounts programs containing Pallas
        kernels (custom calls carry no FLOP estimate) and ``lax.scan`` loops
        (the body is counted once, not trip-count times) — measured on the
        v5e, the flash+fused-loss step reports 4.5T where 6.5T of model math
        runs. MFU accounting therefore uses this standard analytic count
        (PaLM-style): ``6 × matmul_params`` per token plus the attention
        einsums, with causal attention counted at half the S² (what a
        block-skipping kernel actually computes).
        """
        ff_params = 2 * self.features * self.hidden
        if self.num_experts > 0:
            # Per-token ACTIVATED params: top_k routed expert FFs + router.
            ff_params = ff_params * self.moe_top_k + self.features * self.num_experts
        matmul_params_per_layer = (
            self._attn_proj_params + ff_params
        )
        matmul_params = (
            self.num_layers * matmul_params_per_layer
            + self.features * self.vocab_size        # lm_head
        )
        attn_per_token = (
            4 * seq * self.num_heads * self.head_dim * self.num_layers
        ) * (0.5 if self.causal else 1.0)
        per_token = 6 * matmul_params + 3 * attn_per_token
        return float(per_token) * batch * seq

    @property
    def _attn_proj_params(self) -> int:
        """q + k + v + out projection params (k/v shrink under GQA)."""
        kv_heads = self.num_kv_heads if self.num_kv_heads is not None else self.num_heads
        return (
            2 * self.features * self.num_heads * self.head_dim   # q + out
            + 2 * self.features * kv_heads * self.head_dim       # k + v
        )

    @property
    def param_count(self) -> int:
        """Approximate parameter count (embeddings + blocks + head)."""
        ff_params = 2 * self.features * self.hidden             # ff up + down
        if self.num_experts > 0:
            ff_params *= self.num_experts                        # E expert FFs
            ff_params += self.features * self.num_experts        # router
        per_block = (
            self._attn_proj_params                               # qkv + out
            + ff_params
            + 4 * self.features                                  # 2 LN scale+bias
        )
        pos = 0 if self.rope else self.max_seq_len * self.features
        embed = self.vocab_size * self.features + pos
        head = self.features * self.vocab_size
        return embed + self.num_layers * per_block + 2 * self.features + head


#: The BASELINE.json flagship: "case4+case6 composed 125M transformer".
#: 12 × 768 × 12 heads ≈ 124M parameters at GPT-2-small shape.
CONFIG_125M = TransformerConfig()

#: Small config for tests and the emulated-CPU dry run.
CONFIG_TINY = TransformerConfig(
    vocab_size=256,
    num_layers=2,
    features=64,
    num_heads=4,
    head_dim=16,
    hidden=128,
    max_seq_len=64,
    dtype=jnp.float32,
)

#: Tiny MoE variant: 4 experts, top-2 routing (expert-parallel under
#: RULES_DP_TP_EP).
CONFIG_TINY_MOE = dataclasses.replace(CONFIG_TINY, num_experts=4)


class Transformer(nn.Module):
    """Decoder-only LM: embed → N blocks → final LN → logits.

    Token embedding carries logical ``(VOCAB, EMBED)``; the logits head
    ``(EMBED, VOCAB)`` — under TP rules mapping VOCAB→model the head is
    column-parallel, keeping the big vocab matmul sharded.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        *,
        deterministic: bool = True,
        return_hidden: bool = False,
        chunk_lengths: Optional[jax.Array] = None,
    ) -> jax.Array:
        """``chunk_lengths``: ragged decode only (``config.decode_ragged``)
        — per-row valid-token count of this chunk; see
        ``models.attention.MultiHeadAttention.__call__``."""
        cfg = self.config
        b, s = tokens.shape
        if s > cfg.max_seq_len:
            raise ValueError(f"sequence length {s} exceeds max_seq_len {cfg.max_seq_len}")
        if chunk_lengths is not None and not (cfg.decode and cfg.decode_ragged):
            raise ValueError(
                "chunk_lengths requires decode=True and decode_ragged=True"
            )

        embed = nn.Embed(
            cfg.vocab_size,
            cfg.features,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), (VOCAB, EMBED)
            ),
            name="tok_embed",
        )
        if cfg.rope:
            # Positions enter as rotations inside each attention layer
            # (ops/rope.py) — no learned table, no position counter here (the
            # per-layer KV caches track their own indices in decode mode).
            x = embed(tokens)
        else:
            pos_embed = self.param(
                "pos_embed",
                nn.with_logical_partitioning(
                    nn.initializers.normal(stddev=0.02), (SEQ, EMBED)
                ),
                (cfg.max_seq_len, cfg.features),
                cfg.param_dtype,
            )
            if cfg.decode:
                # Chunked autoregressive input: this chunk's absolute
                # positions continue from the running cache position (the
                # per-module KV caches keep their own matching indices).
                # Ragged: a (B,) position counter and per-row gathers — rows
                # advance by their own valid counts.
                pos_var = self.variable(
                    "cache", "position",
                    lambda: jnp.zeros((b,) if cfg.decode_ragged else (), jnp.int32),
                )
                if cfg.decode_ragged:
                    positions = pos_var.value[:, None] + jnp.arange(s)  # (B,S)
                    pos_var.value = pos_var.value + (
                        s if chunk_lengths is None else chunk_lengths
                    )
                    pos_term = jnp.take(pos_embed, positions, axis=0)
                else:
                    positions = pos_var.value + jnp.arange(s)
                    pos_var.value = pos_var.value + s
                    pos_term = jnp.take(pos_embed, positions, axis=0)[None]
                x = embed(tokens) + pos_term.astype(cfg.dtype)
            else:
                x = embed(tokens) + pos_embed[None, :s].astype(cfg.dtype)
        x = nn.with_logical_constraint(x, (BATCH, SEQ, EMBED))

        block_fields = dict(
            features=cfg.features,
            num_heads=cfg.num_heads,
            head_dim=cfg.head_dim,
            num_kv_heads=cfg.num_kv_heads,
            rope=cfg.rope,
            rope_theta=cfg.rope_theta,
            window=cfg.window,
            hidden=cfg.hidden,
            dropout_rate=cfg.dropout_rate,
            causal=cfg.causal,
            use_bias=cfg.use_bias,
            norm_eps=cfg.norm_eps,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            attn_fn=cfg.attn_fn,
            remat_attention=cfg.remat_attention,
            num_experts=cfg.num_experts,
            moe_top_k=cfg.moe_top_k,
            moe_capacity_factor=cfg.moe_capacity_factor,
            moe_dispatch=cfg.moe_dispatch,
            moe_dispatch_fn=cfg.moe_dispatch_fn,
            decode=cfg.decode,
            max_decode_len=cfg.max_seq_len if cfg.decode else 0,
            kv_cache_dtype=cfg.kv_cache_dtype,
            decode_attention=cfg.decode_attention,
            decode_block_k=cfg.decode_block_k,
            decode_attn_fn=cfg.decode_attn_fn,
            decode_ragged=cfg.decode_ragged,
            decode_paged=cfg.decode_paged,
            decode_page_count=cfg.decode_page_count,
            quantization=cfg.quantization,
            quantization_group=cfg.quantization_group,
            quantized_matmul_fn=cfg.quantized_matmul_fn,
            comm_compress_fn=cfg.comm_compress_fn,
            norm=cfg.norm,
            fused_norm=cfg.fused_norm,
        )
        if cfg.scan_layers:
            if cfg.decode:
                raise ValueError(
                    "scan_layers does not support decode mode yet: use the "
                    "unrolled stack for KV-cached generation"
                )
            # One stacked block scanned over a leading (LAYERS,) param dim:
            # XLA traces/compiles the block body ONCE regardless of depth
            # (unrolled 12-layer 125M: ~12x the block HLO), and the weights
            # stay stationary per scan step. split_rngs gives every layer its
            # own init (and dropout) stream; metadata_params records the new
            # leading axis as LAYERS in each param's logical names, so the
            # rule sets (which leave LAYERS unmapped) shard stacked kernels
            # exactly like their unrolled counterparts, layer dim whole.
            block_cls = TransformerBlock
            if cfg.remat:
                # prevent_cse is about XLA de-duplicating the rematerialized
                # ops against the forward; inside lax.scan that cannot happen,
                # so skip the (optimization-barrier) guards. static_argnums
                # counts the module method's args with self=0, so
                # deterministic — which nn.Dropout branches on in Python —
                # is arg 2 and must stay untraced.
                block_cls = nn.remat(
                    TransformerBlock,
                    prevent_cse=False,
                    policy=resolve_remat_policy(cfg.remat_policy),
                    static_argnums=(2,),
                )
            stack = nn.scan(
                block_cls,
                variable_axes={"params": 0, "losses": 0, "intermediates": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast,),
                length=cfg.num_layers,
                metadata_params={nn.meta.PARTITION_NAME: LAYERS},
            )
            x, _ = stack(scan=True, **block_fields, name="blocks")(
                x, deterministic
            )
        else:
            block_cls = TransformerBlock
            if cfg.remat and not cfg.decode:
                # Trade FLOPs for HBM: recompute each block's activations in
                # the backward instead of storing them (SURVEY.md's remat
                # note; key to fitting long sequences). deterministic is arg 2
                # (self=0) and must stay untraced — nn.Dropout branches on it.
                block_cls = nn.remat(
                    TransformerBlock,
                    static_argnums=(2,),
                    policy=resolve_remat_policy(cfg.remat_policy),
                )
            for i in range(cfg.num_layers):
                if cfg.decode:
                    # chunk_lengths rides only the decode path (remat wraps
                    # the training call and pins its positional signature).
                    x = block_cls(**block_fields, name=f"block_{i}")(
                        x, deterministic, chunk_lengths
                    )
                else:
                    x = block_cls(**block_fields, name=f"block_{i}")(
                        x, deterministic
                    )

        if cfg.fused_norm:
            x, _ = FusedNorm(
                kind=cfg.norm, eps=cfg.norm_eps, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype, name="ln_out",
            )(x)
        else:
            x = make_norm(
                cfg.norm, cfg.dtype, cfg.param_dtype, "ln_out", cfg.norm_eps
            )(x)
        if return_hidden:
            # Skip the logits projection: callers pairing this with
            # :func:`fused_next_token_loss` apply the lm_head kernel chunk by
            # chunk so the full (B, S, V) logits never materialize. (Init
            # runs with the default False, so lm_head params always exist.)
            return x
        from learning_jax_sharding_tpu.models.quantize import projection_dense

        logits = projection_dense(
            quantization=cfg.quantization,
            features=cfg.vocab_size,
            kernel_axes=(EMBED, VOCAB),
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(stddev=0.02),
            group_size=cfg.quantization_group,
            quantized_matmul_fn=cfg.quantized_matmul_fn,
            name="lm_head",
        )(x)
        # Keep the vocab dim sharded (VOCAB→model under TP rules): replicating
        # logits here would all-gather ~0.8 GB/device at the 125M bench shape
        # and the cross-entropy reductions partition fine.
        return nn.with_logical_constraint(logits, (BATCH, SEQ, VOCAB))


def fused_next_token_loss(
    hidden: jax.Array,
    batch: dict,
    params: Any,
    *,
    chunk_size: int = 128,
) -> jax.Array:
    """Causal-LM loss with a chunked logits head: O(B·chunk·V) peak memory.

    At large batch the full (B, S, V) logits — bf16 plus the fp32 softmax
    upcast — dominate HBM (measured on the v5e: they OOM the 125M model at
    B=32, S=1024 long before activations do). This computes the head matmul
    and fp32 cross-entropy per sequence chunk inside a ``lax.scan`` with
    ``jax.checkpoint``, so forward AND backward hold logits for only one
    chunk at a time; results are bit-comparable to the unfused loss (CE is
    independent across positions).

    Use with ``apply(..., return_hidden=True)`` (``hidden`` is the final-LN
    output) and ``make_train_step(..., loss_needs_params=True)``.
    """
    b, s, m = hidden.shape
    if s % chunk_size:
        raise ValueError(f"seq len {s} not divisible by chunk_size {chunk_size}")
    kernel = params["lm_head"]["kernel"]

    @jax.checkpoint
    def chunk_total(h_chunk, t_chunk):
        logits = jnp.einsum(
            "bsm,mv->bsv", h_chunk, kernel.astype(h_chunk.dtype)
        )
        logits = nn.with_logical_constraint(logits, (BATCH, SEQ, VOCAB))
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), t_chunk
        ).sum()

    hidden_c = hidden.reshape(b, s // chunk_size, chunk_size, m)
    targets_c = batch["targets"].reshape(b, s // chunk_size, chunk_size)

    def body(acc, ct):
        h, t = ct
        return acc + chunk_total(h, t), None

    total, _ = jax.lax.scan(
        body,
        jnp.zeros((), jnp.float32),
        (hidden_c.transpose(1, 0, 2, 3), targets_c.transpose(1, 0, 2)),
    )
    return total / (b * s)


def next_token_loss(logits: jax.Array, batch: dict) -> jax.Array:
    """Causal-LM loss: mean cross-entropy over all S positions.

    ``batch["targets"]`` must ALREADY be the inputs shifted left by one (the
    data pipeline's job — see ``tests/test_transformer.py::_batch``); no shift
    happens here. Computed in fp32 regardless of compute dtype (same stability
    reasoning as the reference's softmax upcast,
    `/root/reference/case6_attention.py:121-122`).
    """
    logits = logits.astype(jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["targets"]
    ).mean()


def make_next_token_loss(
    *, label_smoothing: float = 0.0, z_loss: float = 0.0
):
    """Configurable causal-LM loss: label smoothing and/or z-loss.

    * ``label_smoothing`` ε: targets become ``(1-ε)·one_hot + ε/V·uniform``.
      Computed WITHOUT materializing the (B, S, V) one-hot — the smoothed
      cross-entropy decomposes as ``(1-ε)·nll + ε·(logsumexp - mean logits)``.
    * ``z_loss`` coefficient: adds ``z_loss · logsumexp(logits)²`` (PaLM-style),
      pulling the partition function toward 1 — keeps logits from drifting,
      which matters for bf16 serving and int8 quantization ranges.

    Defaults reproduce :func:`next_token_loss` exactly.
    """

    def loss_fn(logits: jax.Array, batch: dict) -> jax.Array:
        logits = logits.astype(jnp.float32)
        targets = batch["targets"]
        lse = jax.nn.logsumexp(logits, axis=-1)
        nll = lse - jnp.take_along_axis(
            logits, targets[..., None], axis=-1
        )[..., 0]
        loss = nll
        if label_smoothing:
            uniform_nll = lse - jnp.mean(logits, axis=-1)
            loss = (1.0 - label_smoothing) * nll + label_smoothing * uniform_nll
        if z_loss:
            loss = loss + z_loss * jnp.square(lse)
        return loss.mean()

    return loss_fn
