"""Continuous batching: slot reuse over the ragged KV cache.

The last piece of serving realism the rectangular stack could not express
(after ragged batches, round 3): a REQUEST QUEUE served through a fixed
batch of cache slots, where a finished row's slot is immediately refilled
with the next queued prompt instead of idling until the whole batch
drains. The reference has no inference path at all (SURVEY.md §5); this is
the engine loop that production serving runs.

TPU-shaped design — the host drives, the device stays static:

* two steady-state compiled programs serve any workload — ``refill_step``
  (a fixed ``(B, refill_chunk)`` chunk; each row's valid length rides the
  ragged ``chunk_lengths``, so any mix of fresh prompts, continuing long
  prompts, and idle/decoding rows shares one executable) and
  ``decode_block`` (K tokens per active row, scanned on device) — plus
  the one-shot cache-creating first refill;
* admission is a pure cache-index RESET (per-row counters zero; stale K/V
  beyond a row's new index is invisible to the causal-at-index masks and
  overwritten as the new request advances) — no cache clearing, no
  reallocation;
* prompts longer than ``refill_chunk`` stream through several refill
  calls (the row stays inactive between them; its slot advances by each
  chunk's valid count while every other row advances by 0);
* decoding rows keep their state while other slots refill (they ride the
  refill chunk with length 0 and resume on the next decode block) — the
  batch never DRAINS to admit work, though rows pause for the refill
  dispatches themselves;
* rows freeze IN-SCAN at their generation budget (a per-row ``remaining``
  counter carried through the decode block), so a retired row's
  ``cache_index`` can never advance past ``prompt + max_new_tokens`` —
  the cache-capacity invariant holds on device, not just in host
  bookkeeping;
* SPECULATIVE decoding (``draft_config``): each decode-block step drafts
  ``num_draft`` tokens with the draft model, verifies them in ONE target
  chunk, and accepts PER-ROW — rollback rewinds each row's own
  ``cache_index`` (``models/speculative.py``'s ragged machinery inside
  the engine), so one round emits 1..num_draft+1 tokens per row and the
  block returns per-row counts. With ``temperature > 0`` the block runs
  speculative SAMPLING (Leviathan rejection) whose per-request rejection
  streams are keyed by (request id, generated position, stream tag) —
  sampled speculative outputs are schedule-independent like every other
  engine mode.

Oracles (test-pinned): under GREEDY decoding every request's output is
bit-identical to a rectangular single-prompt ``make_generate_fn`` run —
slot reuse, chunk scheduling, and speculation change throughput, never
results. With ``temperature > 0`` every sampling draw is keyed by
(REQUEST id, generated position), so a request's sampled stream is
reproducible across schedules too: the same queue served with any batch
size, arrival order, or slot assignment yields the same tokens per
request (given the same ``rng``).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from learning_jax_sharding_tpu.models.decoding import (
    apply_dequantize_policy,
    check_sequence_budget,
    derive_decode_config,
    make_cached_apply,
    make_param_caster,
)
from learning_jax_sharding_tpu.models.attention import (
    resolve_decode_backend,
    row_update_masked,
)
from learning_jax_sharding_tpu.models.generate import filtered_logits
from learning_jax_sharding_tpu.models.speculative import (
    _greedy as greedy_pick,
    _pos_key,
    _rollback,
    emit_vector,
    greedy_accept_emit,
)
from learning_jax_sharding_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from learning_jax_sharding_tpu.parallel.logical import Rules, activate


def _reset_rows(
    cache: Any, mask: jax.Array, values: jax.Array | None = None
) -> Any:
    """Set the per-row decode counters (``cache_index`` and ``position``)
    where ``mask`` is True — request admission. ``values`` (``(B,)``,
    default zeros) is the admission index: 0 for a fresh prompt, or the
    shared-prefix length when prefix caching hands the row pre-filled
    pages. Stale K/V past a reset row's index is masked by causal-at-index
    attention and overwritten as the new request writes (same invariant
    speculative rollback relies on, ``models/speculative.py::_rollback``)."""

    def leaf(path, x):
        if getattr(path[-1], "key", None) in ("cache_index", "position"):
            v = (
                jnp.zeros_like(x)
                if values is None
                else jnp.broadcast_to(values.astype(x.dtype), x.shape)
            )
            return jnp.where(mask, v, x)
        return x

    return jax.tree_util.tree_map_with_path(leaf, cache)


def make_continuous_engine(
    config: TransformerConfig,
    mesh: Mesh,
    rules: Rules,
    *,
    batch_size: int,
    max_new_tokens: int,
    eos_id: Optional[int] = None,
    refill_chunk: int = 64,
    decode_block_steps: int = 16,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    min_p: float | None = None,
    vocab_limit: int | None = None,
    inference_dtype: Any | None = None,
    dequantize: bool | str = False,
    draft_config: Optional[TransformerConfig] = None,
    num_draft: int = 4,
    paged_pages: Optional[int] = None,
    page_size: int = 64,
    prefix_cache: bool = False,
):
    """Build ``serve(params, prompts, rng, draft_params) -> list[np.ndarray]``.

    ``prompts`` is any number of 1-D int32 arrays (the request queue, in
    arrival order); the result list matches its order, each entry
    ``[prompt, generated...]`` — generation stops at ``eos_id`` (included
    in the output) or after ``max_new_tokens``.

    ``batch_size`` fixes the device batch (cache slots); ``refill_chunk``
    fixes the admission chunk length (longer prompts stream through
    several refill calls); ``decode_block_steps`` fixes how many decode
    rounds each dispatch scans on device (the host loop pays one
    round-trip per block; rows freeze in-scan at EOS or at their budget,
    so a retired row's cache index never advances past
    ``prompt + max_new_tokens``). All are compile-time shapes: the whole
    engine runs on two executables regardless of queue size or length mix.

    ``draft_config``: enable SPECULATIVE decode blocks — a draft model
    proposes ``num_draft`` tokens per round, the target verifies them in
    one chunked forward, acceptance and cache rollback are PER-ROW. Pass
    the draft params as ``serve(..., draft_params=...)``. At
    ``temperature == 0`` output stays bit-identical to non-speculative
    greedy serving (test-pinned) — the draft changes only how many target
    dispatches the tokens cost. At ``temperature > 0`` the block runs
    speculative sampling (acceptance ``u·q < p``, residual draws from
    ``norm(max(p − q, 0))``) with draws keyed by (request id, generated
    position, stream tag): outputs follow the target's filtered sampling
    distribution and are schedule-independent, though not token-identical
    to non-speculative sampling (different draw structure).

    ``temperature > 0``: every draw is keyed by (request id, generated
    position) folded into ``rng`` — sampled outputs are reproducible
    across schedules (batch size, arrival order, slot assignment).

    ``dequantize``: serve QUANTIZED target weights, exactly as
    ``make_generate_fn`` does — ``True`` for an int8/int4 tree from
    ``quantize_tree`` dequantized inside the jitted steps, ``"fused"`` /
    ``"fused_w4a8"`` for an int4 tree streamed through the fused
    dequant-matmul kernels (whole-FF + q/k/v on single-device serving; an
    injected shard_map matmul under TP). Applies to the TARGET tree only;
    a speculative draft serves at ``inference_dtype``. Greedy engine
    outputs are bit-identical to the corresponding
    ``make_generate_fn(dequantize=...)`` single runs (test-pinned).

    ``paged_pages``: PAGED KV cache — each layer's K/V live in a physical
    pool of ``paged_pages`` pages of ``page_size`` tokens (page 0 is a
    reserved scratch target), indirected through per-row block tables
    that THIS host loop owns: pages are allocated on demand as a row's
    index approaches a page boundary and freed the moment the request
    retires, so cache HBM scales with tokens actually in flight instead
    of ``batch_size × max_seq_len`` — and slot count is no longer bounded
    by worst-case length. Requires the blocked decode backend. Outputs
    are bit-identical to the unpaged engine (test-pinned); the allocator
    raises if a dispatch would need more pages than the pool holds.
    ``prefix_cache`` (paged only): PREFIX CACHING — when a request
    retires, the pages fully covered by its prompt are RETAINED (keyed by
    their page-aligned token prefix) instead of freed; a later request in
    the same ``serve`` call whose prompt starts with the same tokens is
    admitted with those pages already in its block table and its counters
    set to the shared length, so the shared prefix is neither re-stored
    nor re-prefilled — both the HBM and the prefill compute are saved.
    Sharing is all-or-nothing per page, capped at ``len(prompt) - 1`` (the
    last prompt token always recomputes: its logits seed generation), and
    reference-counted; retained pages with no references are evicted LRU
    when the allocator runs dry, so the pool never shrinks. Outputs are
    bit-identical to the uncached engine (test-pinned): shared pages hold
    exactly the bytes the evicted computation wrote. Scope: one ``serve``
    call (the caches themselves live per call).

    After each ``serve`` call, ``serve.last_stats`` reports what the run
    measured: ``page_high_water`` / ``pages_total`` (paged — the
    footprint), ``prefix_hits`` / ``prefix_pages_reused`` (prefix
    caching), and ``spec_accepted`` / ``spec_proposed`` /
    ``spec_accept_rate`` (speculative — verifier acceptance before
    EOS/budget truncation, the number to tune ``num_draft`` against);
    ``None`` when none of the modes are on.
    """
    if batch_size < 1 or refill_chunk < 1 or decode_block_steps < 1:
        raise ValueError(
            "batch_size, refill_chunk, decode_block_steps must be >= 1"
        )
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if refill_chunk > config.max_seq_len:
        raise ValueError(
            f"refill_chunk ({refill_chunk}) exceeds max_seq_len "
            f"({config.max_seq_len})"
        )
    speculative = draft_config is not None
    if speculative:
        if num_draft < 1:
            raise ValueError(f"num_draft must be >= 1, got {num_draft}")
        if draft_config.vocab_size != config.vocab_size:
            raise ValueError(
                f"target vocab {config.vocab_size} != draft vocab "
                f"{draft_config.vocab_size}"
            )
    paged = paged_pages is not None
    if prefix_cache and not paged:
        raise ValueError(
            "prefix_cache requires the paged KV cache (paged_pages=N): "
            "sharing is expressed through block-table entries"
        )

    def check_paged(name, c):
        # ONE copy of the paged preconditions, applied to the target and
        # (when speculative) the draft — their caches page side by side.
        if resolve_decode_backend(c.decode_attention) != "blocked":
            raise ValueError(
                f"paged_pages requires the blocked decode backend for the "
                f"{name} config (decode_attention='blocked', or 'auto' on "
                f"TPU)"
            )
        if c.max_seq_len % page_size:
            raise ValueError(
                f"{name} max_seq_len ({c.max_seq_len}) must be a multiple "
                f"of page_size ({page_size})"
            )

    def pagedify(c):
        return dataclasses.replace(
            c, decode_paged=True, decode_page_count=paged_pages,
            decode_block_k=page_size,
        )

    if paged:
        if paged_pages < 2:
            raise ValueError(
                "paged_pages must be >= 2 (page 0 is the scratch page)"
            )
        check_paged("target", config)
    cfg = derive_decode_config(config, inference_dtype, mesh=mesh, rules=rules)
    cfg = dataclasses.replace(cfg, decode_ragged=True)
    cfg, fused = apply_dequantize_policy(cfg, dequantize, mesh, rules)
    if paged:
        cfg = pagedify(cfg)
    model = Transformer(cfg)
    # The quantization options apply to the TARGET tree only — a draft is
    # small by design and serves at inference_dtype.
    apply = make_cached_apply(
        model, dequantize=bool(dequantize) and not fused,
        dequant_dtype=cfg.param_dtype,
    )
    maybe_cast = make_param_caster(
        inference_dtype, dequantize=bool(dequantize)
    )
    if speculative:
        if paged:
            check_paged("draft", draft_config)
        d_cfg = derive_decode_config(
            draft_config, inference_dtype, mesh=mesh, rules=rules
        )
        d_cfg = dataclasses.replace(d_cfg, decode_ragged=True)
        if paged:
            d_cfg = pagedify(d_cfg)
        d_apply = make_cached_apply(Transformer(d_cfg))

    def _greedy(logits):
        return greedy_pick(logits, vocab_limit)

    def row_keys(rng, rid, pos):
        """(B,) keys from (request id, generated position): the stream a
        request samples from depends only on its own identity and how far
        it has generated — never on scheduling."""

        def one(r, p):
            return jax.random.fold_in(jax.random.fold_in(rng, r), p)

        return jax.vmap(one)(rid, pos)

    def spec_keys(rng, rid, pos, tag):
        """Per-REQUEST rejection streams: ``speculative._pos_key``'s
        position+tag derivation (THE definition of the three stream roles)
        under a request-id fold — position-keyed, so a rolled-back
        position re-derives its draws and a round/block boundary lands
        nowhere in the stream (schedule independence, test-pinned)."""

        def one(r, p):
            return _pos_key(jax.random.fold_in(rng, r), p, tag)

        return jax.vmap(one)(rid, pos)

    def to_flogits(logits):
        """The filtered sampling distribution in logit space — shared with
        ``sample_rows`` via ``generate.filtered_logits`` (THE definition
        of the filter order) so the speculative acceptance distribution
        cannot drift from what plain sampling draws."""
        return filtered_logits(
            logits, temperature, top_k, top_p, min_p, vocab_limit
        )

    def sample_rows(logits, rng, rid, pos):
        """Per-row sampling with (request, position) keys; greedy ignores
        the keys entirely (deterministic)."""
        if temperature == 0.0:
            return _greedy(logits)
        return jax.vmap(jax.random.categorical)(
            row_keys(rng, rid, pos), to_flogits(logits)
        ).astype(jnp.int32)

    def _refill(params, d_params, cache, chunk, lengths, rid, rng):
        # Run the chunk through the target (and the draft, whose cache
        # must mirror the target's valid prefix for verification); the
        # pick is each row's first generated token — position 0 of its
        # stream.
        if speculative:
            t_cache, d_cache = cache
            logits, t_cache = apply(params, t_cache, chunk, lengths)
            _, d_cache = d_apply(d_params, d_cache, chunk, lengths)
            cache = (t_cache, d_cache)
        else:
            logits, cache = apply(params, cache, chunk, lengths)
        pick = jnp.take_along_axis(
            logits, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
        )[:, 0]
        tok = sample_rows(pick, rng, rid, jnp.zeros_like(rid))
        return tok, cache

    @jax.jit
    def refill_step(
        params, d_params, cache, chunk, lengths, reset_mask, reset_to,
        rid, rng,
    ):
        # Admission: set the admitted rows' counters (0, or the shared-
        # prefix length under prefix caching), then run the chunk — every
        # row's cache advance is its own valid length (0 for rows that
        # are decoding or idle this call). The cache-None first call
        # routes to first_refill instead.
        if speculative:
            cache = tuple(
                _reset_rows(c, reset_mask, reset_to) for c in cache
            )
        else:
            cache = _reset_rows(cache, reset_mask, reset_to)
        return _refill(params, d_params, cache, chunk, lengths, rid, rng)

    # Cache creation needs an apply without a cache; same program shape as
    # refill_step minus the reset (Flax creates the zeroed caches —
    # make_cached_apply treats a None cache as the creating call).
    @jax.jit
    def first_refill(params, d_params, chunk, lengths, rid, rng):
        cache = (None, None) if speculative else None
        return _refill(params, d_params, cache, chunk, lengths, rid, rng)

    @jax.jit
    def decode_block(params, cache, tok, active, remaining, rid, rng):
        """``decode_block_steps`` tokens per call, scanned ON DEVICE — the
        host loop costs one dispatch/readback per BLOCK, not per token
        (measured on the tunneled chip: per-token host stepping ran 30×
        slower than the same work scanned). Rows that emit ``eos`` OR
        exhaust their per-row ``remaining`` budget flip inactive IN-scan —
        chunk_lengths 0 from then on, so a retired row stops consuming
        cache mid-block and its index can never pass its admission
        budget."""

        def body(carry, _):
            tok, active, remaining, cache = carry
            logits, cache = apply(params, cache, tok[:, None], active)
            # This draw's generated position: the row has already emitted
            # max_new_tokens - remaining tokens.
            pos = max_new_tokens - remaining
            nxt = sample_rows(logits[:, -1], rng, rid, pos)
            nxt = jnp.where(active == 1, nxt, tok)
            remaining = remaining - active
            if eos_id is not None:
                active = active * (nxt != eos_id).astype(jnp.int32)
            active = active * (remaining > 0).astype(jnp.int32)
            return (nxt, active, remaining, cache), nxt

        (tok, active, remaining, cache), toks = jax.lax.scan(
            body, (tok, active, remaining, cache), None,
            length=decode_block_steps,
        )
        return toks.T, active, remaining, cache   # (B, K) tokens

    @jax.jit
    def decode_block_spec(
        params, d_params, t_cache, d_cache, tok, active, pos, remaining,
        rid, rng,
    ):
        """Speculative decode block: ``decode_block_steps`` draft-verify
        ROUNDS, each emitting 1..num_draft+1 tokens per row with PER-ROW
        acceptance and rollback (the ragged-cache machinery of
        ``models/speculative.py::generate_ragged``, driven inside the
        engine's scan). ``pos`` is each row's current cache index
        (prompt_len + emitted - 1); EOS and budget truncate a round's
        per-row emission exactly, so the buffer/counts the block returns
        are final — the host appends them verbatim.

        ``temperature > 0``: speculative SAMPLING (Leviathan rejection) —
        the draft proposes from the filtered distribution, acceptance is
        ``u·q < p`` per position, the slot-m token samples the residual
        ``norm(max(p − q, 0))`` — with every draw keyed by (request id,
        generated position, stream tag) via ``spec_keys``, so a request's
        sampled output is independent of batch composition, round
        boundaries, and block boundaries (rollback re-derives draws)."""
        width = decode_block_steps * (num_draft + 1)
        idx = jnp.arange(num_draft + 1)

        def body(carry, _):
            (tok, active, pos, remaining, count, buffer, acc, prop,
             t_cache, d_cache) = carry
            # Each row's next GENERATED position (the refill's pick was
            # position 0 of its stream).
            gen = max_new_tokens - remaining

            # 1. Draft proposes per row (frozen rows ride with length 0).
            if temperature == 0.0:

                def draft_step(c, j):
                    prev, dc = c
                    lg, dc = d_apply(d_params, dc, prev[:, None], active)
                    nxt = jnp.where(active == 1, _greedy(lg[:, -1]), prev)
                    return (nxt, dc), nxt

                (last_d, d_cache), drafts = jax.lax.scan(
                    draft_step, (tok, d_cache), jnp.arange(num_draft)
                )
                q_all = None
            else:

                def draft_step(c, j):
                    prev, dc = c
                    lg, dc = d_apply(d_params, dc, prev[:, None], active)
                    fl = to_flogits(lg[:, -1])
                    nxt = jax.vmap(jax.random.categorical)(
                        spec_keys(rng, rid, gen + j, 0), fl
                    ).astype(jnp.int32)
                    nxt = jnp.where(active == 1, nxt, prev)
                    return (nxt, dc), (nxt, jax.nn.softmax(fl, axis=-1))

                (last_d, d_cache), (drafts, q_all) = jax.lax.scan(
                    draft_step, (tok, d_cache), jnp.arange(num_draft)
                )
            drafts = drafts.T
            _, d_cache = d_apply(d_params, d_cache, last_d[:, None], active)

            # 2. One chunked target verify.
            chunk = jnp.concatenate([tok[:, None], drafts], axis=1)
            t_logits, t_cache = apply(
                params, t_cache, chunk, active * (num_draft + 1)
            )

            # 3. Per-row acceptance; emitted = accepted drafts + the
            #    bonus/correction (greedy) or residual sample (sampling) —
            #    the shared cores, models/speculative.py.
            if temperature == 0.0:
                m, emitted, _ = greedy_accept_emit(drafts, _greedy(t_logits))
            else:
                q_all = jnp.moveaxis(q_all, 0, 1)        # (B, num_draft, V)
                p_all = jax.nn.softmax(to_flogits(t_logits), axis=-1)
                p_at = jnp.take_along_axis(
                    p_all[:, :num_draft], drafts[..., None], axis=-1
                )[..., 0]
                q_at = jnp.take_along_axis(
                    q_all, drafts[..., None], axis=-1
                )[..., 0]
                u = jax.vmap(
                    lambda j: jax.vmap(jax.random.uniform)(
                        spec_keys(rng, rid, gen + j, 1)
                    ),
                    out_axes=1,
                )(jnp.arange(num_draft))                 # (B, num_draft)
                accept = u * q_at < p_at
                m = jnp.sum(
                    jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1
                )
                q_pad = jnp.concatenate(
                    [q_all, jnp.zeros_like(q_all[:, :1])], axis=1
                )

                def take_m(x):
                    return jnp.take_along_axis(
                        x, m[:, None, None], axis=1
                    )[:, 0]

                p_m = take_m(p_all)
                residual = jnp.maximum(p_m - take_m(q_pad), 0.0)
                mass = jnp.sum(residual, axis=-1, keepdims=True)
                residual = jnp.where(mass > 0, residual / mass, p_m)
                token_m = jax.vmap(jax.random.categorical)(
                    spec_keys(rng, rid, gen + m, 2), jnp.log(residual)
                ).astype(jnp.int32)
                emitted = emit_vector(drafts, m, token_m)

            # 4. Truncate each row's emission at EOS and at its budget.
            raw = 1 + m
            if eos_id is not None:
                hit = (emitted == eos_id) & (idx[None, :] < raw[:, None])
                any_hit = jnp.any(hit, axis=1)
                first = jnp.argmax(hit, axis=1)
                n_stop = jnp.where(any_hit, first + 1, raw)
            else:
                any_hit = jnp.zeros_like(active, dtype=bool)
                n_stop = raw
            n_emit = jnp.minimum(n_stop, remaining) * active

            # 5. Append at each row's own offset; advance the pending
            #    token to the last emitted one.
            buffer = row_update_masked(
                buffer, emitted, count, n_emit, seq_dim=1
            )
            new_tok = jnp.take_along_axis(
                emitted, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
            )[:, 0]
            tok = jnp.where(active == 1, new_tok, tok)

            # 6. Per-row rollback: the row's new index is pos + n_emit
            #    (frozen rows: +0, i.e. their current index — one
            #    broadcast serves all rows).
            pos = pos + n_emit
            t_cache = _rollback(t_cache, pos)
            d_cache = _rollback(d_cache, pos)

            remaining = remaining - n_emit
            count = count + n_emit
            # Acceptance telemetry: verifier acceptance per live round
            # (before EOS/budget truncation — the DRAFT's quality, which
            # is what the operator tunes num_draft against).
            acc = acc + m * active
            prop = prop + active * num_draft
            stopped_eos = any_hit & (n_stop <= n_emit) & (active == 1)
            active = (
                active
                * (remaining > 0).astype(jnp.int32)
                * (1 - stopped_eos.astype(jnp.int32))
            )
            return (
                tok, active, pos, remaining, count, buffer, acc, prop,
                t_cache, d_cache
            ), None

        b = tok.shape[0]
        buffer = jnp.zeros((b, width), jnp.int32)
        count = jnp.zeros((b,), jnp.int32)
        acc = jnp.zeros((b,), jnp.int32)
        prop = jnp.zeros((b,), jnp.int32)
        (tok, active, pos, remaining, count, buffer, acc, prop,
         t_cache, d_cache), _ = (
            jax.lax.scan(
                body,
                (tok, active, pos, remaining, count, buffer, acc, prop,
                 t_cache, d_cache),
                None,
                length=decode_block_steps,
            )
        )
        return buffer, count, acc, prop, active, remaining, t_cache, d_cache

    def serve(params, prompts, rng=None, draft_params=None):
        if speculative and draft_params is None:
            raise ValueError(
                "draft_config was given: pass draft_params to serve()"
            )
        if not speculative and draft_params is not None:
            raise ValueError("draft_params requires draft_config")
        rng = jax.random.key(0) if rng is None else rng
        b = batch_size
        headroom = num_draft + 1 if speculative else 0
        prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
        budget_cfgs = (
            [("target", cfg), ("draft", d_cfg)] if speculative
            else [("target", cfg)]
        )
        for p in prompts:
            if p.size < 1:
                raise ValueError("empty prompt")
            for name, c in budget_cfgs:
                # The draft cache must fit the same worst case as the
                # target's: its index walks in lockstep through prefill,
                # proposals, and rollback.
                check_sequence_budget(
                    p.size + max_new_tokens + headroom, c.max_seq_len,
                    f"prompt ({p.size}) + max_new_tokens ({max_new_tokens})"
                    + (f" + draft headroom ({headroom})" if headroom else "")
                    + f" for {name}",
                )
        params = maybe_cast(params)
        if speculative:
            draft_params = maybe_cast(draft_params)
        queue = deque(enumerate(prompts))
        results: dict[int, list[int]] = {}

        # Host-side slot state. A slot is: idle (req < 0), refilling
        # (pending prompt tokens remain), or decoding (active).
        req = [-1] * b                 # request id per slot
        plen = [0] * b                 # admitted prompt length per slot
        pending: list[np.ndarray] = [np.zeros((0,), np.int32)] * b
        emitted = [0] * b
        out: list[list[int]] = [[] for _ in range(b)]
        tok = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        cache = None
        spec_accepted = spec_proposed = 0   # acceptance telemetry

        if paged:
            # Host-owned page allocator: page 0 is scratch; a slot holds a
            # prefix of logical blocks mapped to arbitrary physical pages.
            free_pages = list(range(paged_pages - 1, 0, -1))
            held: list[list[int]] = [[] for _ in range(b)]
            t_cap = cfg.max_seq_len // page_size
            table_np = np.zeros((b, t_cap), np.int32)
            high_water = 0
            tables_dirty = True
            # Prefix-cache state: page-aligned token-prefix bytes → the
            # page holding that prefix's LAST page of K/V; refcounts for
            # pages shared by live slots; ref-0 registered pages stay
            # evictable in LRU order (dict preserves insertion order).
            registry: dict[bytes, int] = {}
            key_of_page: dict[int, bytes] = {}
            refcnt: dict[int, int] = {}
            cached_lru: dict[int, None] = {}
            shared_count = [0] * b     # leading registry pages per slot
            prefix_hits = prefix_pages_reused = 0

            def take_page():
                if free_pages:
                    return free_pages.pop()
                if cached_lru:
                    # Evict the oldest reference-free cached page — the
                    # pool must serve live requests before retained ones.
                    pid = next(iter(cached_lru))
                    del cached_lru[pid]
                    del registry[key_of_page.pop(pid)]
                    del refcnt[pid]
                    return pid
                raise RuntimeError(
                    f"page pool exhausted ({paged_pages - 1} pages "
                    f"× {page_size} tokens): raise paged_pages or "
                    "lower concurrency"
                )

            def ensure(slot, tokens_through):
                # Allocate pages so positions [0, tokens_through) are
                # mapped before the dispatch that writes them.
                nonlocal high_water, tables_dirty
                need = -(-int(tokens_through) // page_size)
                while len(held[slot]) < need:
                    p = take_page()
                    table_np[slot, len(held[slot])] = p
                    held[slot].append(p)
                    tables_dirty = True
                high_water = max(
                    high_water, (paged_pages - 1) - len(free_pages)
                )

            def release(slot):
                nonlocal tables_dirty
                if prefix_cache:
                    pages, ns = held[slot], shared_count[slot]
                    # Private pages: RETAIN the ones fully inside the
                    # prompt (immutable once written — generation never
                    # rewrites earlier positions) under their token-prefix
                    # key; free the rest (generated-region K/V). DEEPEST
                    # page first into the LRU — admission chains break at
                    # the first missing page, so eviction must take chain
                    # tails before roots or the stranded descendants
                    # retain HBM with zero hit potential.
                    p_toks = np.asarray(
                        out[slot][: plen[slot]], np.int32
                    )
                    full = plen[slot] // page_size
                    for j in range(len(pages) - 1, ns - 1, -1):
                        pid = pages[j]
                        if j < full:
                            key = p_toks[: (j + 1) * page_size].tobytes()
                            if key not in registry:
                                registry[key] = pid
                                key_of_page[pid] = key
                                refcnt[pid] = 0
                                cached_lru[pid] = None
                                continue
                        free_pages.append(pid)
                    for pid in reversed(pages[:ns]):  # drop shared refs,
                        refcnt[pid] -= 1              # tails first too
                        if refcnt[pid] == 0:
                            cached_lru[pid] = None
                    shared_count[slot] = 0
                else:
                    free_pages.extend(held[slot])
                held[slot] = []
                table_np[slot, :] = 0
                tables_dirty = True

            def set_tables(cache):
                # Push the host tables into every layer's block_table leaf
                # (target AND draft trees; the draft's table may be
                # narrower — same prefix, same page ids). Skipped entirely
                # when no allocation changed since the last push — the
                # steady-state decode loop mostly doesn't allocate.
                nonlocal tables_dirty
                if not tables_dirty:
                    return cache
                tables_dirty = False

                def leaf(path, x):
                    if getattr(path[-1], "key", None) == "block_table":
                        return jnp.asarray(table_np[:, : x.shape[1]])
                    return x

                return jax.tree_util.tree_map_with_path(leaf, cache)

        def retire(slot):
            results[req[slot]] = out[slot]
            req[slot] = -1
            active[slot] = False
            if paged:
                release(slot)

        def consume(slot, tokens):
            # Append a decode dispatch's tokens for one slot; retire at
            # EOS or budget — ONE copy of the retirement rule for both
            # engine modes.
            for t in tokens:
                out[slot].append(int(t))
                emitted[slot] += 1
                tok[slot] = int(t)
                if (eos_id is not None and t == eos_id) or (
                    emitted[slot] >= max_new_tokens
                ):
                    retire(slot)
                    break

        def rid_arr():
            return jnp.asarray(np.maximum(req, 0), jnp.int32)

        try:
            with activate(mesh, rules):
                while queue or any(r >= 0 for r in req):
                    # 1. Admit queued requests into idle slots.
                    reset = np.zeros((b,), bool)
                    reset_to = np.zeros((b,), np.int32)
                    for slot in range(b):
                        if req[slot] < 0 and queue:
                            rid, prompt = queue.popleft()
                            req[slot] = rid
                            plen[slot] = prompt.size
                            pending[slot] = prompt
                            emitted[slot] = 0
                            out[slot] = list(prompt)
                            reset[slot] = True
                            if paged and prefix_cache:
                                # Longest chain of retained pages whose
                                # token prefix matches; the last prompt
                                # token always recomputes (its logits
                                # seed generation).
                                shared = []
                                for k in range(
                                    1, (prompt.size - 1) // page_size + 1
                                ):
                                    pid = registry.get(
                                        prompt[: k * page_size].tobytes()
                                    )
                                    if pid is None:
                                        break
                                    shared.append(pid)
                                for j, pid in enumerate(shared):
                                    refcnt[pid] = refcnt.get(pid, 0) + 1
                                    cached_lru.pop(pid, None)
                                    table_np[slot, j] = pid
                                    held[slot].append(pid)
                                    tables_dirty = True
                                shared_count[slot] = len(shared)
                                if shared:
                                    s_len = len(shared) * page_size
                                    pending[slot] = prompt[s_len:]
                                    reset_to[slot] = s_len
                                    prefix_hits += 1
                                    prefix_pages_reused += len(shared)

                    # 2. One refill chunk for every slot with pending prompt
                    #    tokens (fresh or continuing); decoding rows ride along
                    #    with length 0.
                    lengths = np.zeros((b,), np.int32)
                    chunk = np.zeros((b, refill_chunk), np.int32)
                    for slot in range(b):
                        n = min(pending[slot].size, refill_chunk)
                        if n:
                            chunk[slot, :n] = pending[slot][:n]
                            lengths[slot] = n
                    if lengths.any():
                        if paged:
                            for slot in range(b):
                                if lengths[slot]:
                                    consumed = plen[slot] - pending[slot].size
                                    ensure(slot, consumed + int(lengths[slot]))
                            if cache is None:
                                # Create faithful zero caches with a NO-OP
                                # refill (every length 0 — no writes, no
                                # advances), so the real first chunk runs
                                # through the steady-state path with the
                                # block tables already installed.
                                _, cache = first_refill(
                                    params, draft_params,
                                    jnp.zeros_like(jnp.asarray(chunk)),
                                    jnp.zeros((b,), jnp.int32), rid_arr(), rng,
                                )
                            cache = set_tables(cache)
                        if cache is None:
                            tok_new, cache = first_refill(
                                params, draft_params, jnp.asarray(chunk),
                                jnp.asarray(lengths), rid_arr(), rng,
                            )
                        else:
                            tok_new, cache = refill_step(
                                params, draft_params, cache, jnp.asarray(chunk),
                                jnp.asarray(lengths), jnp.asarray(reset),
                                jnp.asarray(reset_to), rid_arr(), rng,
                            )
                        tok_new = np.asarray(tok_new)
                        for slot in range(b):
                            if lengths[slot]:
                                pending[slot] = pending[slot][lengths[slot]:]
                                if pending[slot].size == 0 and req[slot] >= 0:
                                    # Prompt complete: its first token came from
                                    # this chunk's last valid position.
                                    t = int(tok_new[slot])
                                    out[slot].append(t)
                                    emitted[slot] = 1
                                    tok[slot] = t
                                    if (eos_id is not None and t == eos_id) or (
                                        max_new_tokens == 1
                                    ):
                                        retire(slot)
                                    else:
                                        active[slot] = True
                        continue   # admit/refill until no prompt tokens remain

                    # 3. One decode BLOCK for the active rows.
                    if active.any():
                        remaining = np.asarray(
                            [max(0, max_new_tokens - e) for e in emitted],
                            np.int32,
                        )
                        if paged:
                            # Cover every position this block can write: K new
                            # tokens per row (plain), or K rounds of up to
                            # num_draft+1 plus the verify chunk's headroom
                            # (speculative) — capped by the row's remaining
                            # budget either way.
                            for slot in range(b):
                                if not active[slot]:
                                    continue
                                pos_s = plen[slot] + emitted[slot] - 1
                                if speculative:
                                    span = (
                                        min(
                                            int(remaining[slot]),
                                            decode_block_steps * (num_draft + 1),
                                        )
                                        + num_draft + 1
                                    )
                                else:
                                    span = min(
                                        int(remaining[slot]), decode_block_steps
                                    )
                                ensure(slot, pos_s + span)
                            cache = set_tables(cache)
                        if speculative:
                            # Each row's current cache index: prompt + emitted
                            # - 1 (its pending token is not yet in the cache).
                            pos = np.asarray(
                                [max(0, p + e - 1) for p, e in zip(plen, emitted)],
                                np.int32,
                            )
                            t_cache, d_cache = cache
                            buffer, counts, acc, prop, _, _, t_cache, d_cache = (
                                decode_block_spec(
                                    params, draft_params, t_cache, d_cache,
                                    jnp.asarray(tok),
                                    jnp.asarray(active.astype(np.int32)),
                                    jnp.asarray(pos), jnp.asarray(remaining),
                                    rid_arr(), rng,
                                )
                            )
                            cache = (t_cache, d_cache)
                            buffer = np.asarray(buffer)
                            counts = np.asarray(counts)
                            spec_accepted += int(np.asarray(acc).sum())
                            spec_proposed += int(np.asarray(prop).sum())
                            for slot in range(b):
                                if active[slot]:
                                    consume(slot, buffer[slot, : counts[slot]].tolist())
                        else:
                            toks, _, _, cache = decode_block(
                                params, cache, jnp.asarray(tok),
                                jnp.asarray(active.astype(np.int32)),
                                jnp.asarray(remaining), rid_arr(), rng,
                            )
                            toks = np.asarray(toks)
                            for slot in range(b):
                                if active[slot]:
                                    consume(slot, toks[slot].tolist())

        finally:
            # Stats must reflect THIS call even when it raises — pool
            # exhaustion is exactly when the measured footprint matters.
            stats = {}
            if paged:
                stats.update(
                    page_high_water=high_water,
                    pages_total=paged_pages - 1,
                    page_size=page_size,
                )
                if prefix_cache:
                    stats.update(
                        prefix_hits=prefix_hits,
                        prefix_pages_reused=prefix_pages_reused,
                    )
            if speculative:
                stats.update(
                    spec_accepted=spec_accepted,
                    spec_proposed=spec_proposed,
                    spec_accept_rate=(
                        spec_accepted / spec_proposed if spec_proposed else None
                    ),
                )
            serve.last_stats = stats or None
        return [np.asarray(results[i], np.int32) for i in range(len(prompts))]

    serve.last_stats = None
    return serve
