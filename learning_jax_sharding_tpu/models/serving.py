"""Continuous batching: slot reuse over the ragged KV cache.

The last piece of serving realism the rectangular stack could not express
(after ragged batches, round 3): a REQUEST QUEUE served through a fixed
batch of cache slots, where a finished row's slot is immediately refilled
with the next queued prompt instead of idling until the whole batch
drains. The reference has no inference path at all (SURVEY.md §5); this is
the engine loop that production serving runs.

TPU-shaped design — the host drives, the device stays static:

* two steady-state compiled programs serve any workload — ``refill_step``
  (a fixed ``(B, refill_chunk)`` chunk; each row's valid length rides the
  ragged ``chunk_lengths``, so any mix of fresh prompts, continuing long
  prompts, and idle/decoding rows shares one executable) and
  ``decode_block`` (K tokens per active row, scanned on device) — plus
  the one-shot cache-creating first refill;
* admission is a pure cache-index RESET (per-row counters zero; stale K/V
  beyond a row's new index is invisible to the causal-at-index masks and
  overwritten as the new request advances) — no cache clearing, no
  reallocation;
* prompts longer than ``refill_chunk`` stream through several refill
  calls (the row stays inactive between them; its slot advances by each
  chunk's valid count while every other row advances by 0);
* decoding rows keep their state while other slots refill (they ride the
  refill chunk with length 0 and resume on the next decode block) — the
  batch never DRAINS to admit work, though rows pause for the refill
  dispatches themselves.

Oracle (test-pinned): under GREEDY decoding every request's output is
bit-identical to a rectangular single-prompt ``make_generate_fn`` run —
slot reuse and chunk scheduling change throughput, never results. With
``temperature > 0`` the engine draws per-dispatch keys, so sampled
outputs depend on scheduling (queue composition and slot assignment);
use greedy when reproducibility against single runs matters.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from learning_jax_sharding_tpu.models.decoding import (
    check_sequence_budget,
    derive_decode_config,
    make_cached_apply,
    make_param_caster,
)
from learning_jax_sharding_tpu.models.generate import _sample
from learning_jax_sharding_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from learning_jax_sharding_tpu.parallel.logical import Rules, activate


def _reset_rows(cache: Any, mask: jax.Array) -> Any:
    """Zero the per-row decode counters (``cache_index`` and ``position``)
    where ``mask`` is True — request admission. Stale K/V past a reset
    row's index is masked by causal-at-index attention and overwritten as
    the new request writes (same invariant speculative rollback relies
    on, ``models/speculative.py::_rollback``)."""

    def leaf(path, x):
        if getattr(path[-1], "key", None) in ("cache_index", "position"):
            return jnp.where(mask, jnp.zeros_like(x), x)
        return x

    return jax.tree_util.tree_map_with_path(leaf, cache)


def make_continuous_engine(
    config: TransformerConfig,
    mesh: Mesh,
    rules: Rules,
    *,
    batch_size: int,
    max_new_tokens: int,
    eos_id: Optional[int] = None,
    refill_chunk: int = 64,
    decode_block_steps: int = 16,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    min_p: float | None = None,
    vocab_limit: int | None = None,
    inference_dtype: Any | None = None,
):
    """Build ``serve(params, prompts, rng) -> list[np.ndarray]``.

    ``prompts`` is any number of 1-D int32 arrays (the request queue, in
    arrival order); the result list matches its order, each entry
    ``[prompt, generated...]`` — generation stops at ``eos_id`` (included
    in the output) or after ``max_new_tokens``.

    ``batch_size`` fixes the device batch (cache slots); ``refill_chunk``
    fixes the admission chunk length (longer prompts stream through
    several refill calls); ``decode_block_steps`` fixes how many tokens
    each decode dispatch scans on device (the host loop pays one
    round-trip per block — rows that retire mid-block on BUDGET waste at
    most block−1 device steps before their slot resets at refill; EOS
    rows freeze in-scan). All are compile-time shapes: the whole engine
    runs on two executables regardless of queue size or length mix.
    """
    if batch_size < 1 or refill_chunk < 1 or decode_block_steps < 1:
        raise ValueError(
            "batch_size, refill_chunk, decode_block_steps must be >= 1"
        )
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if refill_chunk > config.max_seq_len:
        raise ValueError(
            f"refill_chunk ({refill_chunk}) exceeds max_seq_len "
            f"({config.max_seq_len})"
        )
    cfg = derive_decode_config(config, inference_dtype, mesh=mesh, rules=rules)
    cfg = dataclasses.replace(cfg, decode_ragged=True)
    model = Transformer(cfg)
    apply = make_cached_apply(model)
    maybe_cast = make_param_caster(inference_dtype)

    def sample(logits, rng):
        return _sample(
            logits, temperature, rng, top_k, top_p, min_p, vocab_limit
        )

    @jax.jit
    def refill_step(params, cache, chunk, lengths, reset_mask, rng):
        # Admission: zero the admitted rows' counters, then run the chunk —
        # every row's cache advance is its own valid length (0 for rows
        # that are decoding or idle this call). The cache-None first call
        # routes to first_refill instead.
        cache = _reset_rows(cache, reset_mask)
        logits, cache = apply(params, cache, chunk, lengths)
        pick = jnp.take_along_axis(
            logits, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
        )[:, 0]
        return sample(pick, rng), cache

    # Cache creation needs an apply without a cache; same program shape as
    # refill_step minus the reset (Flax creates the zeroed caches).
    @jax.jit
    def first_refill(params, chunk, lengths, rng):
        logits, cache = apply(params, None, chunk, lengths)
        pick = jnp.take_along_axis(
            logits, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
        )[:, 0]
        return sample(pick, rng), cache

    @jax.jit
    def decode_block(params, cache, tok, active, rng):
        """``decode_block_steps`` tokens per call, scanned ON DEVICE — the
        host loop costs one dispatch/readback per BLOCK, not per token
        (measured on the tunneled chip: per-token host stepping ran 30×
        slower than the same work scanned). Rows that emit ``eos`` flip
        inactive IN-scan — chunk_lengths 0, so they stop consuming cache
        mid-block exactly like the stepwise path."""

        def body(carry, rng_step):
            tok, active, cache = carry
            logits, cache = apply(params, cache, tok[:, None], active)
            nxt = sample(logits[:, -1], rng_step)
            nxt = jnp.where(active == 1, nxt, tok)
            if eos_id is not None:
                active = active * (nxt != eos_id).astype(jnp.int32)
            return (nxt, active, cache), nxt

        rngs = jax.random.split(rng, decode_block_steps)
        (tok, active, cache), toks = jax.lax.scan(
            body, (tok, active, cache), rngs
        )
        return toks.T, active, cache   # (B, K) tokens

    def serve(params, prompts, rng=None):
        rng = jax.random.key(0) if rng is None else rng
        b = batch_size
        prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
        for p in prompts:
            if p.size < 1:
                raise ValueError("empty prompt")
            check_sequence_budget(
                p.size + max_new_tokens, cfg.max_seq_len,
                f"prompt ({p.size}) + max_new_tokens ({max_new_tokens})",
            )
        params = maybe_cast(params)
        queue = deque(enumerate(prompts))
        results: dict[int, list[int]] = {}

        # Host-side slot state. A slot is: idle (req < 0), refilling
        # (pending prompt tokens remain), or decoding (active).
        req = [-1] * b                 # request id per slot
        pending: list[np.ndarray] = [np.zeros((0,), np.int32)] * b
        emitted = [0] * b
        out: list[list[int]] = [[] for _ in range(b)]
        tok = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        cache = None
        step = 0

        def retire(slot):
            results[req[slot]] = out[slot]
            req[slot] = -1
            active[slot] = False

        with activate(mesh, rules):
            while queue or any(r >= 0 for r in req):
                # 1. Admit queued requests into idle slots.
                reset = np.zeros((b,), bool)
                for slot in range(b):
                    if req[slot] < 0 and queue:
                        rid, prompt = queue.popleft()
                        req[slot] = rid
                        pending[slot] = prompt
                        emitted[slot] = 0
                        out[slot] = list(prompt)
                        reset[slot] = True

                # 2. One refill chunk for every slot with pending prompt
                #    tokens (fresh or continuing); decoding rows ride along
                #    with length 0.
                lengths = np.zeros((b,), np.int32)
                chunk = np.zeros((b, refill_chunk), np.int32)
                for slot in range(b):
                    n = min(pending[slot].size, refill_chunk)
                    if n:
                        chunk[slot, :n] = pending[slot][:n]
                        lengths[slot] = n
                if lengths.any():
                    step += 1
                    sub = jax.random.fold_in(rng, step)
                    if cache is None:
                        tok_new, cache = first_refill(
                            params, jnp.asarray(chunk), jnp.asarray(lengths),
                            sub,
                        )
                    else:
                        tok_new, cache = refill_step(
                            params, cache, jnp.asarray(chunk),
                            jnp.asarray(lengths), jnp.asarray(reset), sub,
                        )
                    tok_new = np.asarray(tok_new)
                    for slot in range(b):
                        if lengths[slot]:
                            pending[slot] = pending[slot][lengths[slot]:]
                            if pending[slot].size == 0 and req[slot] >= 0:
                                # Prompt complete: its first token came from
                                # this chunk's last valid position.
                                t = int(tok_new[slot])
                                out[slot].append(t)
                                emitted[slot] = 1
                                tok[slot] = t
                                if (eos_id is not None and t == eos_id) or (
                                    max_new_tokens == 1
                                ):
                                    retire(slot)
                                else:
                                    active[slot] = True
                    continue   # admit/refill until no prompt tokens remain

                # 3. One decode BLOCK for the active rows.
                if active.any():
                    step += 1
                    sub = jax.random.fold_in(rng, step)
                    toks, _, cache = decode_block(
                        params, cache, jnp.asarray(tok),
                        jnp.asarray(active.astype(np.int32)), sub,
                    )
                    toks = np.asarray(toks)
                    for slot in range(b):
                        if not active[slot]:
                            continue
                        for t in toks[slot].tolist():
                            out[slot].append(int(t))
                            emitted[slot] += 1
                            tok[slot] = int(t)
                            if (eos_id is not None and t == eos_id) or (
                                emitted[slot] >= max_new_tokens
                            ):
                                retire(slot)
                                break

        return [np.asarray(results[i], np.int32) for i in range(len(prompts))]

    return serve
