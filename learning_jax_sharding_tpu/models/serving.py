"""Continuous batching: a PERSISTENT engine serving requests over time.

The last piece of serving realism the rectangular stack could not express
(after ragged batches, round 3): a REQUEST QUEUE served through a fixed
batch of cache slots, where a finished row's slot is immediately refilled
with the next queued prompt instead of idling until the whole batch
drains. The reference has no inference path at all (SURVEY.md §5); this is
the engine loop that production serving runs.

Round 5 makes the engine PERSISTENT (``ContinuousEngine``): the compiled
programs, the KV cache, the paged page pool, and the prefix-cache
registry all live on the engine OBJECT, not inside a ``serve()`` call —
so a second call re-prefills nothing it already holds (prefix hits span
calls and sessions), allocates nothing (the cache-creating first refill
runs once per engine, ever), and requests can be ADMITTED OVER TIME
(``add_request`` / ``step``) instead of only as a one-shot queue. The
engine also measures what production engines measure: per-request TTFT,
per-token latency (TPOT), and inter-token gaps (ITL), with p50/p99.

TPU-shaped design — the host drives, the device stays static:

* two steady-state compiled programs serve any workload — ``refill_step``
  (a fixed ``(B, refill_chunk)`` chunk; each row's valid length rides the
  ragged ``chunk_lengths``, so any mix of fresh prompts, continuing long
  prompts, and idle/decoding rows shares one executable) and
  ``decode_block`` (K tokens per active row, scanned on device) — plus
  the one-shot cache-creating first refill (once per ENGINE, not per
  call);
* admission is a pure cache-index RESET (per-row counters zero; stale K/V
  beyond a row's new index is invisible to the causal-at-index masks and
  overwritten as the new request advances) — no cache clearing, no
  reallocation;
* prompts longer than ``refill_chunk`` stream through several refill
  calls (the row stays inactive between them; its slot advances by each
  chunk's valid count while every other row advances by 0);
* decoding rows keep their state while other slots refill (they ride the
  refill chunk with length 0 and resume on the next decode block) — the
  batch never DRAINS to admit work, though rows pause for the refill
  dispatches themselves;
* rows freeze IN-SCAN at their generation budget (a per-row ``remaining``
  counter carried through the decode block), so a retired row's
  ``cache_index`` can never advance past ``prompt + max_new_tokens`` —
  the cache-capacity invariant holds on device, not just in host
  bookkeeping;
* SPECULATIVE decoding (``draft_config``): each decode-block step drafts
  ``num_draft`` tokens with the draft model, verifies them in ONE target
  chunk, and accepts PER-ROW — rollback rewinds each row's own
  ``cache_index`` (``models/speculative.py``'s ragged machinery inside
  the engine), so one round emits 1..num_draft+1 tokens per row and the
  block returns per-row counts. With ``temperature > 0`` the block runs
  speculative SAMPLING (Leviathan rejection) whose per-request rejection
  streams are keyed by (request id, generated position, stream tag) —
  sampled speculative outputs are schedule-independent like every other
  engine mode.

* MIXED scheduling (``mixed=True``, round 9): one FUSED program per
  iteration advances all decoding rows by one token AND pushes a
  token-budgeted refill chunk for admitting/streaming rows (refill rows
  ride their ragged ``chunk_lengths``, decode rows ride with length 1) —
  decode never stalls behind another slot's prefill, and admission lands
  at chunk granularity on every dispatch instead of at decode-block
  boundaries.

Oracles (test-pinned): under GREEDY decoding every request's output is
bit-identical to a rectangular single-prompt ``make_generate_fn`` run —
slot reuse, chunk scheduling, speculation, and engine persistence change
throughput, never results. With ``temperature > 0`` every sampling draw
is keyed by (REQUEST id, generated position), so a request's sampled
stream is reproducible across schedules too: the same queue served with
any batch size, arrival order, or slot assignment yields the same tokens
per request (given the same ``rng``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import time
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from learning_jax_sharding_tpu.models.decoding import (
    apply_dequantize_policy,
    check_sequence_budget,
    derive_decode_config,
    make_cached_apply,
    make_param_caster,
)
from learning_jax_sharding_tpu.models.attention import (
    resolve_decode_backend,
    row_update_masked,
)
from learning_jax_sharding_tpu.models.generate import filtered_logits
from learning_jax_sharding_tpu.models.speculative import (
    _greedy as greedy_pick,
    _pos_key,
    _rollback,
    emit_vector,
    greedy_accept_emit,
)
from learning_jax_sharding_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from learning_jax_sharding_tpu.parallel.compression import (
    CommCompression,
    get_codec,
    make_compressed_matmul_fn,
)
from learning_jax_sharding_tpu.parallel.logical import Rules, activate
from learning_jax_sharding_tpu.robustness.chaos import InjectedFault, chaos_hook
from learning_jax_sharding_tpu.telemetry import (
    GoodputLedger,
    MetricsRegistry,
    Tracer,
)
from learning_jax_sharding_tpu.telemetry.compile_watch import cache_size
from learning_jax_sharding_tpu.utils.profiling import annotate

#: Dispatch failures the engine RECOVERS from (quarantine/requeue)
#: instead of propagating: the chaos harness's injected faults and the
#: NaN-trap FloatingPointError a checking()-style dispatch raises. Real
#: infrastructure errors (OOM, XLA internal) still propagate — recovery
#: must never guess.
_RECOVERABLE_DISPATCH = (InjectedFault, FloatingPointError)

#: Cache leaves with a leading PHYSICAL-PAGE dim on paged engines — the
#: leaves ``kv_page_spill``/``kv_page_fill`` move one page of. Per-slot
#: counters (cache_index, position, block_table) stay: a retained prefix
#: page carries K/V only; the mapping is host state.
_PAGE_LEAF_KEYS = ("cached_key", "cached_value", "key_scale", "value_scale")


class AdmissionError(RuntimeError):
    """Admission control rejected the request (bounded queue full, or
    the degradation ladder reached its shedding level). The caller
    should back off / retry elsewhere — nothing was enqueued."""


@dataclasses.dataclass
class RequestFailure:
    """A request that retired WITHOUT completing, surfaced through
    ``pop_finished`` so failures are a terminal status, never a silent
    drop. ``tokens`` carries the partial ``[prompt, generated...]``
    output when the request had been admitted (None when it failed in
    the queue). Status ``"rerouted"`` is terminal only for THIS engine:
    the fleet router drained the request for failover/handoff and will
    recompute it bit-identically on another replica — visible here so a
    failover never masquerades as a fresh admission."""

    rid: int
    status: str              # deadline|poisoned|malformed|shutdown|rerouted
    error: str | None = None
    tokens: np.ndarray | None = None


def _reset_rows(
    cache: Any, mask: jax.Array, values: jax.Array | None = None
) -> Any:
    """Set the per-row decode counters (``cache_index`` and ``position``)
    where ``mask`` is True — request admission. ``values`` (``(B,)``,
    default zeros) is the admission index: 0 for a fresh prompt, or the
    shared-prefix length when prefix caching hands the row pre-filled
    pages. Stale K/V past a reset row's index is masked by causal-at-index
    attention and overwritten as the new request writes (same invariant
    speculative rollback relies on, ``models/speculative.py::_rollback``)."""

    def leaf(path, x):
        if getattr(path[-1], "key", None) in ("cache_index", "position"):
            v = (
                jnp.zeros_like(x)
                if values is None
                else jnp.broadcast_to(values.astype(x.dtype), x.shape)
            )
            return jnp.where(mask, v, x)
        return x

    return jax.tree_util.tree_map_with_path(leaf, cache)


@dataclasses.dataclass
class _Request:
    """Host bookkeeping for one request, from arrival to retirement."""

    rid: int
    prompt: np.ndarray
    arrival_t: float
    admit_t: float | None = None
    first_token_t: float | None = None
    finish_t: float | None = None
    tokens: np.ndarray | None = None      # final [prompt, generated...]
    status: str = "ok"    # or deadline|poisoned|malformed|shutdown|rerouted
    error: str | None = None
    deadline_s: float | None = None       # per-request TTL override
    strikes: int = 0                      # dispatch faults while admitted
    version: int = 0                      # weights version pinned at admission
    adapter: str | None = None            # AdapterPool tenant (None = base)
    enqueue_t: float | None = None        # when THIS engine queued it (a
    #                                       rerouted request keeps its fleet
    #                                       arrival_t but re-enqueues here)
    ingested: bool = False                # admitted via kv_ingest: the prefill
    #                                       happened on another replica
    tenant: str | None = None             # cost-attribution / SLO label


class ContinuousEngine:
    """A persistent continuous-batching engine.

    Construction compiles the engine's programs and validates the
    configuration; the returned object then serves any number of
    workloads through TWO entry styles:

    * **one-shot**: ``engine.serve(params, prompts, rng=..., draft_params=...)``
      — drain a whole queue, return outputs in queue order (the original
      ``make_continuous_engine`` contract, bit-identity oracles intact);
    * **streaming**: ``engine.add_request(prompt)`` at any time (an
      arrival process), ``engine.step(params, ...)`` to run ONE scheduler
      iteration (admission + one refill or decode dispatch), and
      ``engine.pop_finished()`` to collect completed requests — the shape
      a serving frontend drives.

    What persists across calls (the round-5 redesign — previously all of
    this was rebuilt per ``serve()`` call):

    * the compiled programs AND the KV cache — the cache-creating first
      refill runs once per engine ever (``engine.cache_creations`` counts
      it, test-pinned at 1 across calls);
    * the paged page pool and its allocator;
    * the PREFIX-CACHE registry/refcounts/LRU — a request in a later
      ``serve()`` call (or streaming session) whose prompt starts with a
      previously retired prompt's page-aligned prefix is admitted with
      those pages already mapped: the shared-system-prompt workload this
      feature exists for. NOTE the registry keys pages by TOKEN BYTES
      only: it assumes the engine serves ONE fixed set of params. Call
      ``flush_prefix_cache()`` when swapping checkpoints.

    ``prompts`` entries are 1-D int32 arrays; each result is
    ``[prompt, generated...]`` — generation stops at ``eos_id`` (included)
    or after ``max_new_tokens``.

    ``batch_size`` fixes the device batch (cache slots); ``refill_chunk``
    fixes the admission chunk length (longer prompts stream through
    several refill calls); ``decode_block_steps`` fixes how many decode
    rounds each dispatch scans on device (the host loop pays one
    round-trip per block; rows freeze in-scan at EOS or at their budget,
    so a retired row's cache index never advances past
    ``prompt + max_new_tokens``). All are compile-time shapes: the whole
    engine runs on two executables regardless of queue size or length mix.

    ``draft_config``: enable SPECULATIVE decode blocks — a draft model
    proposes ``num_draft`` tokens per round, the target verifies them in
    one chunked forward, acceptance and cache rollback are PER-ROW. Pass
    the draft params as ``serve(..., draft_params=...)``. At
    ``temperature == 0`` output stays bit-identical to non-speculative
    greedy serving (test-pinned) — the draft changes only how many target
    dispatches the tokens cost. At ``temperature > 0`` the block runs
    speculative sampling (acceptance ``u·q < p``, residual draws from
    ``norm(max(p − q, 0))``) with draws keyed by (request id, generated
    position, stream tag): outputs follow the target's filtered sampling
    distribution and are schedule-independent, though not token-identical
    to non-speculative sampling (different draw structure).

    ``temperature > 0``: every draw is keyed by (request id, generated
    position) folded into ``rng`` — sampled outputs are reproducible
    across schedules (batch size, arrival order, slot assignment).
    ``serve()`` numbers requests by QUEUE INDEX per call (the pinned
    schedule-independence contract); streaming ``add_request`` assigns
    engine-global monotonic ids.

    ``decode_chain``: dispatch up to this many decode blocks (and refill
    chunks) BACK-TO-BACK, carrying tok/active/remaining device-to-device
    and syncing the host once per chain. Rows freeze on device at
    EOS/budget exactly as within one block, so chaining cannot change
    results (test-pinned). Measured on the tunneled chip
    (``scripts/perf_block_ladder.py``): each jitted CALL costs ~120 ms
    in the dispatch itself, so the first-order decode lever is
    ``decode_block_steps`` (tokens per compiled program — 823 → 2,637
    tok/s from K=16 to K=128 on the standard queue; size K ≈
    max_new_tokens so rows retire at block boundaries); chaining stacks
    a further gain on decode (K=64 chain=2 > K=64) and is the MAIN
    lever for REFILL, whose chunk contents are host-known (long-prompt
    prefill 13.0k → 20.2k tok/s at S=4096). The cost of both is
    scheduling granularity: retirement/admission coarsen by up to a
    chain/block, and token-visibility telemetry (ITL) becomes
    chain-granular — size to the workload (throughput queues high,
    latency-sensitive arrivals low; ``decode_chain`` is a public
    attribute, tunable per phase at runtime).

    ``mixed=True``: the FUSED refill+decode scheduler (round 9). The
    split engine dispatches refill OR decode per iteration, so every
    decoding row pauses while another slot's prompt streams through
    refill chunks — measured at 86-87% of engine time on the 125M
    serving bench, the direct cause of its ITL p99 and queue-wait tails.
    The mixed engine runs ONE compiled program per iteration
    (``mixed_step`` / ``spec_mixed_step``) in which every decoding row
    advances one token (speculative: one draft-verify round with per-row
    rollback) AND pending prompts push refill chunks under
    ``token_budget`` — a per-dispatch token ceiling (decode rows funded
    first; refill takes the remainder; uncapped when nothing is
    decoding). Admission happens at EVERY dispatch, at chunk
    granularity. The two-steady-state-programs invariant holds — fixed
    ``(B, refill_chunk)`` shapes, no recompiles — and ``decode_chain``
    still carries device-to-device (each link is one mixed step, so a
    chain emits ``chain`` decode tokens per host sync). PURE-DECODE
    phases (no pending prompt tokens anywhere) fall through to the
    K-token ``decode_block`` — a fused link costs one dispatch per token
    and exists to overlap refill; with nothing to overlap, the scanned
    block's decode throughput wins and admission loses nothing (a queued
    request only rides out a block when every slot is busy). Greedy outputs
    stay bit-identical to the split engine (ragged rows are independent:
    each row's computation is exactly what the split programs run for
    it), and sampled streams are identical too (draws keyed by request
    id and position, never by schedule) — test-pinned. ``token_budget``
    is a public runtime-tunable attribute like ``decode_chain``: size it
    to the per-dispatch latency you can afford between decode tokens
    (see PERF.md round 9 for the measured ladder).

    ``dequantize``: serve QUANTIZED target weights, exactly as
    ``make_generate_fn`` does — ``True`` for an int8/int4 tree from
    ``quantize_tree`` dequantized inside the jitted steps, ``"fused"`` /
    ``"fused_w4a8"`` for an int4 tree streamed through the fused
    dequant-matmul kernels (whole-FF + q/k/v on single-device serving; an
    injected shard_map matmul under TP). ``draft_dequantize`` applies the
    same policy (``True`` → in-jit dequant) to the DRAFT tree — pass a
    quantized draft to ``serve(..., draft_params=...)``. Greedy engine
    outputs are bit-identical to the corresponding
    ``make_generate_fn(dequantize=...)`` single runs (test-pinned).

    ``paged_pages``: PAGED KV cache — each layer's K/V live in a physical
    pool of ``paged_pages`` pages of ``page_size`` tokens (page 0 is a
    reserved scratch target), indirected through per-row block tables
    that the host loop owns: pages are allocated on demand as a row's
    index approaches a page boundary and freed the moment the request
    retires, so cache HBM scales with tokens actually in flight instead
    of ``batch_size × max_seq_len`` — and slot count is no longer bounded
    by worst-case length. Requires the blocked decode backend. Outputs
    are bit-identical to the unpaged engine (test-pinned); the allocator
    raises if a dispatch would need more pages than the pool holds.
    ``prefix_cache`` (paged only): PREFIX CACHING — when a request
    retires, the pages fully covered by its prompt are RETAINED (keyed by
    their page-aligned token prefix) instead of freed; a later request
    whose prompt starts with the same tokens is admitted with those pages
    already in its block table and its counters set to the shared length,
    so the shared prefix is neither re-stored nor re-prefilled — both the
    HBM and the prefill compute are saved, ACROSS ``serve()`` calls.
    Sharing is all-or-nothing per page, capped at ``len(prompt) - 1`` (the
    last prompt token always recomputes: its logits seed generation), and
    reference-counted; retained pages with no references are evicted LRU
    when the allocator runs dry (chain tails strictly before their roots,
    across retirements), so the pool never shrinks. Outputs are
    bit-identical to the uncached engine (test-pinned): shared pages hold
    exactly the bytes the evicted computation wrote.

    After each ``serve`` call (and on demand via ``latency_stats()``):

    * ``last_stats`` — ``page_high_water`` / ``pages_total`` (paged — the
      LIVE footprint, excluding retained reference-free prefix pages,
      which are reported separately as ``prefix_pages_retained``),
      ``prefix_hits`` / ``prefix_pages_reused`` (prefix caching), and
      ``spec_accepted`` / ``spec_proposed`` / ``spec_accept_rate``
      (speculative — verifier acceptance before EOS/budget truncation,
      the number to tune ``num_draft`` against); ``None`` when none of
      the modes are on.
    * ``last_latency`` — per-request latency telemetry: ``ttft_p50/p99``
      (arrival → first generated token visible on the host),
      ``tpot_p50/p99`` (per-request mean inter-token time after the
      first), ``itl_p50/p99`` (raw host-visibility gaps — block-granular
      by design: tokens land ``decode_block_steps`` at a time), and
      ``queue_wait_p50/p99`` (arrival → slot admission).

    TELEMETRY (round 6): the engine meters into a
    :class:`~learning_jax_sharding_tpu.telemetry.MetricsRegistry`
    (``engine.registry`` — counters/gauges/histograms with Prometheus
    text exposition; engine-local unless one is passed in, and passing a
    shared one makes the counters fleet totals while ``last_stats``
    windows then span every engine metering into it) and traces
    into a :class:`~learning_jax_sharding_tpu.telemetry.Tracer`
    (``engine.tracer`` — a per-request span timeline arrival → admit →
    first token → finish plus per-dispatch refill/decode spans,
    exportable as Perfetto-loadable Chrome trace JSON). ``last_stats``
    and ``last_latency`` are re-derived from the registry (window deltas
    over cumulative counters), so their shapes and values keep the
    pinned contract. ``compile_counts()`` reports per-program compile
    counts and ``collective_inventory()`` the per-dispatch collective
    ops from the compiled HLO.

    DIAGNOSIS (round 7): the engine feeds a flight recorder
    (``engine.recorder`` — process-wide default ring; arrival/admission/
    preemption/retirement/cache-creation events plus every tracer span
    closure when attached) whose ``dump_diagnostics()`` writes a
    post-mortem bundle; an optional ``slo=``
    :class:`~learning_jax_sharding_tpu.telemetry.SLOMonitor` receives
    TTFT/TPOT/ITL/queue-wait/e2e per retirement (streaming percentiles +
    burn-rate targets, exported through the engine registry); and
    ``collective_axis_volume()`` attributes each program's collective
    bytes to the mesh axes that carry them.

    RECOVERY (round 10): detection is wired to action —

    * ``deadline_s`` (engine default, per-request override on
      ``add_request``): a request older than its TTL is EVICTED with
      terminal status ``"deadline"`` — queued or mid-flight — and
      surfaced through ``pop_finished`` as a :class:`RequestFailure`
      (partial tokens included), never a silent drop.
    * ``max_queue``: bounded admission — an arrival past the bound is
      SHED (:class:`AdmissionError`, nothing enqueued), so backpressure
      reaches the frontend instead of growing an unbounded queue whose
      every entry will miss its SLO together.
    * ``degradation=``
      :class:`~learning_jax_sharding_tpu.robustness.DegradationLadder`
      (requires ``slo=``): the monitor's burn rate walks disable
      speculation → halve ``token_budget`` → shed admits, with
      hysteresis; every transition lands in the flight recorder and the
      ``engine_degradation_level`` gauge. De-escalation restores the
      knobs it took over.
    * poison quarantine (``max_dispatch_strikes``): a dispatch that
      raises a recoverable fault (injected NaN-trap/hang-watchdog abort
      — see :mod:`~learning_jax_sharding_tpu.robustness.chaos`) strikes
      every involved request; repeat offenders are FAILED
      (``"poisoned"``) and isolated, the rest are requeued and
      re-admitted one at a time (probation) so the poison trips alone —
      then recomputed exactly (the ``_unadmit`` recompute-preemption
      guarantee), so survivors' outputs are bit-identical to a
      fault-free run (test-pinned).
    * ``close()`` drains: every in-flight/queued request gets terminal
      status ``"shutdown"`` before the device state drops — callers
      polling ``pop_finished`` always terminate. Idempotent.

    FLEET (round 11): the engine is one REPLICA of a
    :class:`~learning_jax_sharding_tpu.fleet.FleetRouter` fleet —

    * ``drain_requests(status="rerouted")`` is the failover drain: every
      queued/in-flight request retires here with a ``"rerouted"``
      terminal status (``engine_rerouted_total``,
      ``latency_stats()["rerouted"]`` — a failover is visible, never
      disguised as fresh admissions) and returns requeueable records
      that RECOMPUTE BIT-IDENTICALLY on a survivor (the ``_unadmit``
      recompute guarantee: draws are keyed by (request id, position)).
    * ``export_kv`` / ``ingest_kv`` are the DISAGGREGATED handoff: a
      dedicated prefill engine (``max_new_tokens=1``) retires a request
      at its first token, its cache row streams to a decode engine
      through the explicit resharding transfer plan
      (``fleet.kv_transfer`` — host-plan bytes, no hidden XLA
      collectives: the ``kv_export``/``kv_ingest`` goldens pin both
      device programs), and the decode engine continues the stream
      bit-identically to a single engine of the same mesh shape.
      Unpaged, non-speculative engines only.

    * ``comm_compression=CommCompression(...)`` turns on the COMM
      COMPRESSION layer: the fused-step families compile the serving
      block's one TP all-reduce (the FF down projection) as a
      block-scaled int8 gather (~``1/itemsize`` of the wire bytes), and
      every counted host transfer — page spill/fill, disaggregated KV
      handoff via the fleet, cross-device-set swap staging — ships
      int8 (or delta-vs-base) blocks through the
      ``parallel.resharding`` codec seam, with wire AND raw bytes
      booked. A drift governor probes the compressed apply against a
      plain oracle every ``drift_check_every`` dispatches; breaching
      ``drift_budget`` trips a dedicated degradation ladder that
      disables compression and retraces every program back to the
      bit-identical plain contraction.
    """

    def __init__(
        self,
        config: TransformerConfig,
        mesh: Mesh,
        rules: Rules,
        *,
        batch_size: int,
        max_new_tokens: int,
        eos_id: Optional[int] = None,
        refill_chunk: int = 64,
        decode_block_steps: int = 16,
        decode_chain: int = 1,
        mixed: bool = False,
        token_budget: int | None = None,
        horizon: int = 1,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        min_p: float | None = None,
        vocab_limit: int | None = None,
        inference_dtype: Any | None = None,
        dequantize: bool | str = False,
        draft_config: Optional[TransformerConfig] = None,
        draft_dequantize: bool = False,
        num_draft: int = 4,
        paged_pages: Optional[int] = None,
        page_size: int = 64,
        prefix_cache: bool = False,
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[Tracer] = None,
        slo: Any | None = None,
        recorder: Any | None = None,
        deadline_s: float | None = None,
        max_queue: int | None = None,
        degradation: Any | None = None,
        max_dispatch_strikes: int = 2,
        adapter_pool: Any | None = None,
        comm_compression: Any | None = None,
    ):
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if max_dispatch_strikes < 1:
            raise ValueError(
                f"max_dispatch_strikes must be >= 1, got "
                f"{max_dispatch_strikes}"
            )
        if degradation is not None and slo is None:
            raise ValueError(
                "degradation needs slo=SLOMonitor(...): the ladder is "
                "driven by the monitor's burn rate"
            )
        if batch_size < 1 or refill_chunk < 1 or decode_block_steps < 1:
            raise ValueError(
                "batch_size, refill_chunk, decode_block_steps must be >= 1"
            )
        if decode_chain < 1:
            raise ValueError(f"decode_chain must be >= 1, got {decode_chain}")
        if horizon < 1:
            raise ValueError(f"horizon must be >= 1, got {horizon}")
        if horizon > 1 and not mixed:
            raise ValueError(
                "horizon > 1 requires mixed=True: the multi-step scan "
                "fuses the MIXED iteration body (the split engine's "
                "decode_block already amortizes its loop on device)"
            )
        if token_budget is not None and not mixed:
            raise ValueError("token_budget requires mixed=True")
        if token_budget is not None and token_budget < 1:
            raise ValueError(
                f"token_budget must be >= 1, got {token_budget}"
            )
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if refill_chunk > config.max_seq_len:
            raise ValueError(
                f"refill_chunk ({refill_chunk}) exceeds max_seq_len "
                f"({config.max_seq_len})"
            )
        speculative = draft_config is not None
        if speculative:
            if num_draft < 1:
                raise ValueError(f"num_draft must be >= 1, got {num_draft}")
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError(
                    f"target vocab {config.vocab_size} != draft vocab "
                    f"{draft_config.vocab_size}"
                )
        if draft_dequantize and not speculative:
            raise ValueError("draft_dequantize requires draft_config")
        paged = paged_pages is not None
        if prefix_cache and not paged:
            raise ValueError(
                "prefix_cache requires the paged KV cache (paged_pages=N): "
                "sharing is expressed through block-table entries"
            )
        if adapter_pool is not None:
            # Multi-LoRA serving (round 12) composes with the FUSED
            # engine only: the adapter gather lives inside
            # ``adapter_mixed_step``, and every split-program fallback
            # (refill_step / decode_block) would run adapter rows
            # through the BASE weights.
            if not mixed:
                raise ValueError(
                    "adapter_pool requires mixed=True: adapters are "
                    "gathered per row inside the fused step"
                )
            if paged:
                raise ValueError(
                    "adapter_pool requires the unpaged cache: the per-row "
                    "vmapped apply maps over batch-major cache rows, which "
                    "the paged pool's page-major leaves do not have (the "
                    "AdapterPool does its own page-granular residency "
                    "accounting instead)"
                )
            if degradation is not None:
                raise ValueError(
                    "adapter_pool does not compose with degradation=: the "
                    "ladder's split-program fallbacks would serve adapter "
                    "rows with the base weights"
                )
        # Comm compression (this PR): quantized serving collectives +
        # compressed KV movement. ``True`` means the defaults; anything
        # else must be a ``CommCompression`` so the knobs are validated
        # in one place (its ``__post_init__``).
        comp = CommCompression() if comm_compression is True else comm_compression
        if comp is not None:
            if not isinstance(comp, CommCompression):
                raise ValueError(
                    "comm_compression must be True or a "
                    f"parallel.compression.CommCompression, got {comp!r}"
                )
            if comp.collectives and not mixed:
                raise ValueError(
                    "comm_compression with collectives=True requires "
                    "mixed=True: the quantized TP matmul is compiled into "
                    "the fused step families, and the drift governor "
                    "probes at fused-dispatch granularity"
                )

        def check_paged(name, c):
            # ONE copy of the paged preconditions, applied to the target and
            # (when speculative) the draft — their caches page side by side.
            if resolve_decode_backend(c.decode_attention) != "blocked":
                raise ValueError(
                    f"paged_pages requires the blocked decode backend for the "
                    f"{name} config (decode_attention='blocked', or 'auto' on "
                    f"TPU)"
                )
            if c.max_seq_len % page_size:
                raise ValueError(
                    f"{name} max_seq_len ({c.max_seq_len}) must be a multiple "
                    f"of page_size ({page_size})"
                )

        def pagedify(c):
            return dataclasses.replace(
                c, decode_paged=True, decode_page_count=paged_pages,
                decode_block_k=page_size,
            )

        if paged:
            if paged_pages < 2:
                raise ValueError(
                    "paged_pages must be >= 2 (page 0 is the scratch page)"
                )
            check_paged("target", config)
        cfg = derive_decode_config(
            config, inference_dtype, mesh=mesh, rules=rules
        )
        cfg = dataclasses.replace(cfg, decode_ragged=True)
        cfg, fused = apply_dequantize_policy(cfg, dequantize, mesh, rules)
        if paged:
            cfg = pagedify(cfg)
        if comp is not None and comp.collectives:
            # Compile the quantized TP all-reduce into every apply-family
            # program: the FF down projection — the serving block's one
            # all-reduce site — routes through the block-scaled int8
            # gather (``parallel.compression.make_compressed_matmul_fn``).
            # The injected fn reads ``comp.enabled`` at TRACE time, so a
            # drift-budget trip + cache clear retraces every program back
            # to the plain (bit-identical) contraction.
            cfg = dataclasses.replace(
                cfg,
                comm_compress_fn=make_compressed_matmul_fn(
                    mesh, rules, comp
                ),
            )
        model = Transformer(cfg)
        apply = make_cached_apply(
            model, dequantize=bool(dequantize) and not fused,
            dequant_dtype=cfg.param_dtype,
        )
        maybe_cast = make_param_caster(
            inference_dtype, dequantize=bool(dequantize)
        )
        d_cfg = None
        if speculative:
            if paged:
                check_paged("draft", draft_config)
            d_cfg = derive_decode_config(
                draft_config, inference_dtype, mesh=mesh, rules=rules
            )
            d_cfg = dataclasses.replace(d_cfg, decode_ragged=True)
            if paged:
                d_cfg = pagedify(d_cfg)
            # The draft may be served quantized too (`draft_dequantize` —
            # in-jit int8/int4 dequant, the non-fused policy: a draft is
            # small, the fused kernels' launch floor would dominate it).
            d_apply = make_cached_apply(
                Transformer(d_cfg), dequantize=draft_dequantize,
                dequant_dtype=d_cfg.param_dtype,
            )
            d_cast = make_param_caster(
                inference_dtype, dequantize=draft_dequantize
            )
        else:
            d_apply = None
            d_cast = maybe_cast

        comp_probe = None
        if comp is not None and comp.collectives:
            # Drift oracle: the SAME weights and cache served through a
            # plain-collective apply (``comm_compress_fn=None`` — same
            # param tree, since _CompressedDense declares the identical
            # down/kernel). The probe runs one greedy decode step under
            # both applies and counts active rows whose argmax diverged;
            # the caches it produces are discarded, so probing never
            # perturbs the served stream.
            oracle_apply = make_cached_apply(
                Transformer(
                    dataclasses.replace(cfg, comm_compress_fn=None)
                ),
                dequantize=bool(dequantize) and not fused,
                dequant_dtype=cfg.param_dtype,
            )

            @jax.jit
            def comp_probe(params, cache, tok, active):
                lc, _ = apply(params, cache, tok[:, None], active)
                lo, _ = oracle_apply(params, cache, tok[:, None], active)
                agree = (
                    jnp.argmax(lc[:, -1], axis=-1)
                    == jnp.argmax(lo[:, -1], axis=-1)
                )
                live = active == 1
                return jnp.sum(live), jnp.sum(live & ~agree)

        def _greedy(logits):
            return greedy_pick(logits, vocab_limit)

        def row_keys(rng, rid, pos):
            """(B,) keys from (request id, generated position): the stream a
            request samples from depends only on its own identity and how far
            it has generated — never on scheduling."""

            def one(r, p):
                return jax.random.fold_in(jax.random.fold_in(rng, r), p)

            return jax.vmap(one)(rid, pos)

        def spec_keys(rng, rid, pos, tag):
            """Per-REQUEST rejection streams: ``speculative._pos_key``'s
            position+tag derivation (THE definition of the three stream roles)
            under a request-id fold — position-keyed, so a rolled-back
            position re-derives its draws and a round/block boundary lands
            nowhere in the stream (schedule independence, test-pinned)."""

            def one(r, p):
                return _pos_key(jax.random.fold_in(rng, r), p, tag)

            return jax.vmap(one)(rid, pos)

        def to_flogits(logits):
            """The filtered sampling distribution in logit space — shared with
            ``sample_rows`` via ``generate.filtered_logits`` (THE definition
            of the filter order) so the speculative acceptance distribution
            cannot drift from what plain sampling draws."""
            return filtered_logits(
                logits, temperature, top_k, top_p, min_p, vocab_limit
            )

        def sample_rows(logits, rng, rid, pos):
            """Per-row sampling with (request, position) keys; greedy ignores
            the keys entirely (deterministic)."""
            if temperature == 0.0:
                return _greedy(logits)
            return jax.vmap(jax.random.categorical)(
                row_keys(rng, rid, pos), to_flogits(logits)
            ).astype(jnp.int32)

        def _refill(params, d_params, cache, chunk, lengths, rid, rng):
            # Run the chunk through the target (and the draft, whose cache
            # must mirror the target's valid prefix for verification); the
            # pick is each row's first generated token — position 0 of its
            # stream.
            if speculative:
                t_cache, d_cache = cache
                logits, t_cache = apply(params, t_cache, chunk, lengths)
                _, d_cache = d_apply(d_params, d_cache, chunk, lengths)
                cache = (t_cache, d_cache)
            else:
                logits, cache = apply(params, cache, chunk, lengths)
            pick = jnp.take_along_axis(
                logits, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
            )[:, 0]
            tok = sample_rows(pick, rng, rid, jnp.zeros_like(rid))
            return tok, cache

        @jax.jit
        def refill_step(
            params, d_params, cache, chunk, lengths, reset_mask, reset_to,
            rid, rng,
        ):
            # Admission: set the admitted rows' counters (0, or the shared-
            # prefix length under prefix caching), then run the chunk — every
            # row's cache advance is its own valid length (0 for rows that
            # are decoding or idle this call). The cache-None first call
            # routes to first_refill instead.
            if speculative:
                cache = tuple(
                    _reset_rows(c, reset_mask, reset_to) for c in cache
                )
            else:
                cache = _reset_rows(cache, reset_mask, reset_to)
            return _refill(params, d_params, cache, chunk, lengths, rid, rng)

        # Cache creation needs an apply without a cache; same program shape as
        # refill_step minus the reset (Flax creates the zeroed caches —
        # make_cached_apply treats a None cache as the creating call).
        @jax.jit
        def first_refill(params, d_params, chunk, lengths, rid, rng):
            cache = (None, None) if speculative else None
            return _refill(params, d_params, cache, chunk, lengths, rid, rng)

        @jax.jit
        def decode_block(params, cache, tok, active, remaining, rid, rng):
            """``decode_block_steps`` tokens per call, scanned ON DEVICE — the
            host loop costs one dispatch/readback per BLOCK, not per token
            (measured on the tunneled chip: per-token host stepping ran 30×
            slower than the same work scanned). Rows that emit ``eos`` OR
            exhaust their per-row ``remaining`` budget flip inactive IN-scan —
            chunk_lengths 0 from then on, so a retired row stops consuming
            cache mid-block and its index can never pass its admission
            budget."""

            def body(carry, _):
                tok, active, remaining, cache = carry
                logits, cache = apply(params, cache, tok[:, None], active)
                # This draw's generated position: the row has already emitted
                # max_new_tokens - remaining tokens.
                pos = max_new_tokens - remaining
                nxt = sample_rows(logits[:, -1], rng, rid, pos)
                nxt = jnp.where(active == 1, nxt, tok)
                remaining = remaining - active
                if eos_id is not None:
                    active = active * (nxt != eos_id).astype(jnp.int32)
                active = active * (remaining > 0).astype(jnp.int32)
                return (nxt, active, remaining, cache), nxt

            (tok, active, remaining, cache), toks = jax.lax.scan(
                body, (tok, active, remaining, cache), None,
                length=decode_block_steps,
            )
            return toks.T, active, remaining, cache   # (B, K) tokens

        def spec_round(carry, params, d_params, rid, rng, apply_fn=apply):
            """ONE draft-verify ROUND with PER-ROW acceptance and rollback —
            THE shared speculative core of the engine: ``decode_block_spec``
            scans it ``decode_block_steps`` times, ``spec_mixed_step`` runs
            it once after its fused refill sub-step, so the acceptance /
            emission / rollback rules cannot drift between the two program
            families. Frozen rows (``active == 0`` — idle, refilling, or
            retired) ride every sub-call with length 0 and ``n_emit`` 0, so
            the round's rollback broadcast re-asserts their current ``pos``
            without moving it.

            ``apply_fn`` is the VERIFIER's apply (default: the target
            model's). The multi-LoRA engine passes its per-row
            adapter-gathered apply here — the draft always proposes with
            the BASE weights (a proposal distribution never defines the
            output; the verifier does), so one shared draft serves every
            tenant in the batch."""
            idx = jnp.arange(num_draft + 1)
            (tok, active, pos, remaining, count, buffer, acc, prop,
             t_cache, d_cache) = carry
            # Each row's next GENERATED position (the refill's pick was
            # position 0 of its stream).
            gen = max_new_tokens - remaining

            # 1. Draft proposes per row (frozen rows ride with length 0).
            if temperature == 0.0:

                def draft_step(c, j):
                    prev, dc = c
                    lg, dc = d_apply(d_params, dc, prev[:, None], active)
                    nxt = jnp.where(active == 1, _greedy(lg[:, -1]), prev)
                    return (nxt, dc), nxt

                (last_d, d_cache), drafts = jax.lax.scan(
                    draft_step, (tok, d_cache), jnp.arange(num_draft)
                )
                q_all = None
            else:

                def draft_step(c, j):
                    prev, dc = c
                    lg, dc = d_apply(d_params, dc, prev[:, None], active)
                    fl = to_flogits(lg[:, -1])
                    nxt = jax.vmap(jax.random.categorical)(
                        spec_keys(rng, rid, gen + j, 0), fl
                    ).astype(jnp.int32)
                    nxt = jnp.where(active == 1, nxt, prev)
                    return (nxt, dc), (nxt, jax.nn.softmax(fl, axis=-1))

                (last_d, d_cache), (drafts, q_all) = jax.lax.scan(
                    draft_step, (tok, d_cache), jnp.arange(num_draft)
                )
            drafts = drafts.T
            _, d_cache = d_apply(
                d_params, d_cache, last_d[:, None], active
            )

            # 2. One chunked target verify.
            chunk = jnp.concatenate([tok[:, None], drafts], axis=1)
            t_logits, t_cache = apply_fn(
                params, t_cache, chunk, active * (num_draft + 1)
            )

            # 3. Per-row acceptance; emitted = accepted drafts + the
            #    bonus/correction (greedy) or residual sample (sampling) —
            #    the shared cores, models/speculative.py.
            if temperature == 0.0:
                m, emitted, _ = greedy_accept_emit(
                    drafts, _greedy(t_logits)
                )
            else:
                q_all = jnp.moveaxis(q_all, 0, 1)    # (B, num_draft, V)
                p_all = jax.nn.softmax(to_flogits(t_logits), axis=-1)
                p_at = jnp.take_along_axis(
                    p_all[:, :num_draft], drafts[..., None], axis=-1
                )[..., 0]
                q_at = jnp.take_along_axis(
                    q_all, drafts[..., None], axis=-1
                )[..., 0]
                u = jax.vmap(
                    lambda j: jax.vmap(jax.random.uniform)(
                        spec_keys(rng, rid, gen + j, 1)
                    ),
                    out_axes=1,
                )(jnp.arange(num_draft))             # (B, num_draft)
                accept = u * q_at < p_at
                m = jnp.sum(
                    jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1
                )
                q_pad = jnp.concatenate(
                    [q_all, jnp.zeros_like(q_all[:, :1])], axis=1
                )

                def take_m(x):
                    return jnp.take_along_axis(
                        x, m[:, None, None], axis=1
                    )[:, 0]

                p_m = take_m(p_all)
                residual = jnp.maximum(p_m - take_m(q_pad), 0.0)
                mass = jnp.sum(residual, axis=-1, keepdims=True)
                residual = jnp.where(mass > 0, residual / mass, p_m)
                token_m = jax.vmap(jax.random.categorical)(
                    spec_keys(rng, rid, gen + m, 2), jnp.log(residual)
                ).astype(jnp.int32)
                emitted = emit_vector(drafts, m, token_m)

            # 4. Truncate each row's emission at EOS and at its budget.
            raw = 1 + m
            if eos_id is not None:
                hit = (emitted == eos_id) & (idx[None, :] < raw[:, None])
                any_hit = jnp.any(hit, axis=1)
                first = jnp.argmax(hit, axis=1)
                n_stop = jnp.where(any_hit, first + 1, raw)
            else:
                any_hit = jnp.zeros_like(active, dtype=bool)
                n_stop = raw
            n_emit = jnp.minimum(n_stop, remaining) * active

            # 5. Append at each row's own offset; advance the pending
            #    token to the last emitted one.
            buffer = row_update_masked(
                buffer, emitted, count, n_emit, seq_dim=1
            )
            new_tok = jnp.take_along_axis(
                emitted, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
            )[:, 0]
            tok = jnp.where(active == 1, new_tok, tok)

            # 6. Per-row rollback: the row's new index is pos + n_emit
            #    (frozen rows: +0, i.e. their current index — one
            #    broadcast serves all rows).
            pos = pos + n_emit
            t_cache = _rollback(t_cache, pos)
            d_cache = _rollback(d_cache, pos)

            remaining = remaining - n_emit
            count = count + n_emit
            # Acceptance telemetry: verifier acceptance per live round
            # (before EOS/budget truncation — the DRAFT's quality, which
            # is what the operator tunes num_draft against).
            acc = acc + m * active
            prop = prop + active * num_draft
            stopped_eos = any_hit & (n_stop <= n_emit) & (active == 1)
            active = (
                active
                * (remaining > 0).astype(jnp.int32)
                * (1 - stopped_eos.astype(jnp.int32))
            )
            return (
                tok, active, pos, remaining, count, buffer, acc, prop,
                t_cache, d_cache
            )

        def _spec_carry_init(tok, active, pos, remaining, width):
            b = tok.shape[0]
            return (
                tok, active, pos, remaining,
                jnp.zeros((b,), jnp.int32),          # count
                jnp.zeros((b, width), jnp.int32),    # buffer
                jnp.zeros((b,), jnp.int32),          # acc
                jnp.zeros((b,), jnp.int32),          # prop
            )

        @jax.jit
        def decode_block_spec(
            params, d_params, t_cache, d_cache, tok, active, pos, remaining,
            rid, rng,
        ):
            """Speculative decode block: ``decode_block_steps`` draft-verify
            ROUNDS (``spec_round`` — the shared core), each emitting
            1..num_draft+1 tokens per row with PER-ROW acceptance and
            rollback (the ragged-cache machinery of
            ``models/speculative.py::generate_ragged``, driven inside the
            engine's scan). ``pos`` is each row's current cache index
            (prompt_len + emitted - 1); EOS and budget truncate a round's
            per-row emission exactly, so the buffer/counts the block returns
            are final — the host appends them verbatim.

            ``temperature > 0``: speculative SAMPLING (Leviathan rejection) —
            the draft proposes from the filtered distribution, acceptance is
            ``u·q < p`` per position, the slot-m token samples the residual
            ``norm(max(p − q, 0))`` — with every draw keyed by (request id,
            generated position, stream tag) via ``spec_keys``, so a request's
            sampled output is independent of batch composition, round
            boundaries, and block boundaries (rollback re-derives draws)."""
            width = decode_block_steps * (num_draft + 1)

            def body(carry, _):
                return spec_round(carry, params, d_params, rid, rng), None

            (tok, active, pos, remaining, count, buffer, acc, prop,
             t_cache, d_cache), _ = (
                jax.lax.scan(
                    body,
                    _spec_carry_init(tok, active, pos, remaining, width)
                    + (t_cache, d_cache),
                    None,
                    length=decode_block_steps,
                )
            )
            # tok and pos ride the return so CHAINED dispatches can carry
            # them device-to-device (decode_chain — no host sync between
            # chained blocks).
            return (
                buffer, count, acc, prop, tok, pos, active, remaining,
                t_cache, d_cache,
            )

        def _mixed_core(
            apply_fn, params, cache, chunk, lengths, reset_mask, reset_to,
            tok, active, remaining, rid, rng,
        ):
            # THE fused-iteration body, shared by ``mixed_step`` (plain
            # apply) and ``adapter_mixed_step`` (per-row adapter-gathered
            # apply) so the scheduling/sampling rules cannot drift between
            # the single-tenant and multi-tenant program families.
            cache = _reset_rows(cache, reset_mask, reset_to)
            dec = active == 1   # decoding rows never hold pending tokens
            eff_len = jnp.where(dec, 1, lengths)
            chunk = chunk.at[:, 0].set(jnp.where(dec, tok, chunk[:, 0]))
            logits, cache = apply_fn(params, cache, chunk, eff_len)
            pick = jnp.take_along_axis(
                logits, jnp.maximum(eff_len - 1, 0)[:, None, None], axis=1
            )[:, 0]
            # Refill rows sample their stream's position 0 (the refill
            # pick); decode rows their current generated position — the
            # same keys the split programs use.
            pos = jnp.where(dec, max_new_tokens - remaining, 0)
            nxt = sample_rows(pick, rng, rid, pos)
            tok = jnp.where(dec, nxt, tok)
            remaining = remaining - dec.astype(jnp.int32)
            if eos_id is not None:
                active = active * jnp.where(
                    dec, (nxt != eos_id).astype(jnp.int32), 1
                )
            active = active * jnp.where(
                dec, (remaining > 0).astype(jnp.int32), 1
            )
            return nxt, tok, active, remaining, cache

        @jax.jit
        def mixed_step(
            params, cache, chunk, lengths, reset_mask, reset_to, tok,
            active, remaining, rid, rng,
        ):
            """ONE FUSED engine iteration (``mixed=True``): every DECODING
            row advances one token AND every scheduled REFILL row pushes its
            budgeted prompt chunk, in a single compiled dispatch — decode
            never waits for another slot's prefill to stream through.

            Decode rows ride the ragged chunk with length 1 (their pending
            token spliced into column 0); refill rows ride with their
            host-scheduled ``chunk_lengths`` (admission resets applied
            first, exactly as in ``refill_step``); idle rows ride with
            length 0. The per-row computation is identical to what
            ``refill_step`` / ``decode_block``'s scan body would have done
            for that row — ragged rows are independent — so greedy token
            streams stay bit-identical to the split-program engine
            (test-pinned). Carries (tok/active/remaining) ride the return so
            ``decode_chain`` links can flow device-to-device with one host
            sync per chain."""
            return _mixed_core(
                apply, params, cache, chunk, lengths, reset_mask, reset_to,
                tok, active, remaining, rid, rng,
            )

        def _merge_row(p, a):
            # One ROW's adapter folded into the base tree — the EXACT op
            # order of ``training.lora.merge_lora`` (scale · A@B, then
            # astype into the kernel dtype), with the python-float
            # ``alpha/rank`` scale replaced by the pool's per-slot scale
            # array cast to the A@B dtype (same promotion a weak-typed
            # scalar takes), so a pooled tenant's merged weights are
            # BIT-IDENTICAL to ``merge_lora``'s — the multi-tenant
            # bit-identity oracle rests on this mirror.
            if not isinstance(p, dict):
                return p
            out = {}
            for k, v in p.items():
                sub = a.get(k) if isinstance(a, dict) else None
                if (
                    sub is not None and isinstance(sub, dict)
                    and set(sub) == {"lora_a", "lora_b", "scale"}
                ):
                    ab = sub["lora_a"] @ sub["lora_b"]
                    out[k] = v + (sub["scale"].astype(ab.dtype) * ab).astype(
                        v.dtype
                    )
                else:
                    out[k] = _merge_row(v, sub if sub is not None else {})
            return out

        def _adapter_apply(sel):
            # Per-row adapter-gathered apply: ``sel`` is the pool tree
            # already GATHERED at each row's adapter slot (leaves
            # (B, ...) — the gather runs once, outside the vmap). Each
            # row folds its own adapter into the base and runs the model
            # at batch 1; vmap stacks the rows back into one fused
            # program, so heterogeneous tenants share a single dispatch.
            def apply_rows(params, cache, chunk, lens):
                cache_b = jax.tree.map(lambda x: x[:, None], cache)

                def one(sel_row, cache_row, ch, ln):
                    merged = _merge_row(params, sel_row)
                    lg, c2 = apply(merged, cache_row, ch[None], ln[None])
                    return lg[0], jax.tree.map(lambda x: x[0], c2)

                return jax.vmap(one)(sel, cache_b, chunk, lens)

            return apply_rows

        @jax.jit
        def adapter_mixed_step(
            params, pool, aidx, cache, chunk, lengths, reset_mask,
            reset_to, tok, active, remaining, rid, rng,
        ):
            """``mixed_step`` with a PER-ROW adapter gather (multi-LoRA
            serving): ``pool`` is the stacked adapter tree
            (``tenancy.AdapterPool.tree`` — leading slot dim), ``aidx``
            each row's adapter slot (0 = the base/zero adapter). One
            fused program serves requests for DIFFERENT tenants'
            adapters in the same batch, bit-identical to each tenant
            solo against ``merge_lora``-folded weights (test-pinned)."""
            sel = jax.tree.map(lambda s: s[aidx], pool)
            return _mixed_core(
                _adapter_apply(sel), params, cache, chunk, lengths,
                reset_mask, reset_to, tok, active, remaining, rid, rng,
            )

        def _spec_mixed_core(
            apply_fn, params, d_params, t_cache, d_cache, chunk, lengths,
            reset_mask, reset_to, tok, active, pos, remaining, rid, rng,
        ):
            # The speculative fused-iteration body (shared with the
            # adapter-gathered variant, like ``_mixed_core``): the
            # verifier AND the refill stream run through ``apply_fn``;
            # the draft always proposes with the base weights.
            t_cache = _reset_rows(t_cache, reset_mask, reset_to)
            d_cache = _reset_rows(d_cache, reset_mask, reset_to)
            r_logits, t_cache = apply_fn(params, t_cache, chunk, lengths)
            _, d_cache = d_apply(d_params, d_cache, chunk, lengths)
            r_pick = jnp.take_along_axis(
                r_logits, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
            )[:, 0]
            first_tok = sample_rows(r_pick, rng, rid, jnp.zeros_like(rid))
            pos = pos + lengths
            (tok, active, pos, remaining, count, buffer, acc, prop,
             t_cache, d_cache) = spec_round(
                _spec_carry_init(tok, active, pos, remaining, num_draft + 1)
                + (t_cache, d_cache),
                params, d_params, rid, rng, apply_fn=apply_fn,
            )
            return (
                first_tok, buffer, count, acc, prop, tok, pos, active,
                remaining, t_cache, d_cache,
            )

        @jax.jit
        def spec_mixed_step(
            params, d_params, t_cache, d_cache, chunk, lengths, reset_mask,
            reset_to, tok, active, pos, remaining, rid, rng,
        ):
            """The speculative fused iteration: the budgeted refill chunk
            streams through TARGET AND DRAFT (decoding rows ride with
            length 0), then ONE draft-verify round (``spec_round`` — the
            same per-row acceptance/rollback core as ``decode_block_spec``)
            advances every decoding row by 1..num_draft+1 tokens. ``pos``
            tracks every row's cache index: refill rows advance by their
            chunk length BEFORE the round, so the round's rollback
            broadcast re-asserts (never clobbers) their refill advance."""
            return _spec_mixed_core(
                apply, params, d_params, t_cache, d_cache, chunk, lengths,
                reset_mask, reset_to, tok, active, pos, remaining, rid, rng,
            )

        @jax.jit
        def adapter_spec_mixed_step(
            params, pool, aidx, d_params, t_cache, d_cache, chunk, lengths,
            reset_mask, reset_to, tok, active, pos, remaining, rid, rng,
        ):
            """``spec_mixed_step`` with the per-row adapter gather: refill
            and VERIFICATION run each row against its own merged weights
            (so accepted tokens are exactly what the tenant's solo merged
            model would emit — greedy exactness through the verifier);
            the shared draft proposes with the base weights, which only
            moves the acceptance rate, never the output distribution."""
            sel = jax.tree.map(lambda s: s[aidx], pool)
            return _spec_mixed_core(
                _adapter_apply(sel), params, d_params, t_cache, d_cache,
                chunk, lengths, reset_mask, reset_to, tok, active, pos,
                remaining, rid, rng,
            )

        def _multi_scan(apply_fn):
            # THE device-resident multi-step loop (ROADMAP item 1): a
            # ``lax.scan`` over the EXACT ``_mixed_core`` body, with the
            # slot bookkeeping the host used to re-derive every iteration
            # (tok/active/remaining) carried in the scan state instead.
            # The host plans the whole horizon's refill schedule up front
            # (stacked (N, B, ...) plan arrays ride as scan xs) and
            # touches Python ONCE per horizon — one dispatch, one sync.
            # Per-step ``lax.cond`` early-exit: a step the host did not
            # plan (``live`` 0 — the fixed-shape horizon's trailing
            # padding) or whose plan row has no refill while the carry
            # holds no active row skips the model apply entirely, so
            # padded steps cost control flow, not FLOPs. The ``live``
            # gate is load-bearing, not an optimization: the host only
            # consumes tokens from PLANNED links, so an unplanned step
            # must not advance any row (a speculative row can still be
            # active past the optimistic chain cap).
            def run(params, cache, chunks, lengths, reset_mask, reset_to,
                    live, tok, active, remaining, rid, rng):
                def body(carry, x):
                    tok, active, remaining, cache = carry
                    chunk, lens, rmask, rto, lv = x

                    def step(_):
                        nxt, tok2, active2, remaining2, cache2 = (
                            _mixed_core(
                                apply_fn, params, cache, chunk, lens,
                                rmask, rto, tok, active, remaining, rid,
                                rng,
                            )
                        )
                        return (tok2, active2, remaining2, cache2), nxt

                    def frozen(_):
                        return (tok, active, remaining, cache), tok

                    has_work = jnp.logical_and(
                        lv > 0,
                        jnp.logical_or(
                            jnp.any(lens > 0), jnp.any(active == 1)
                        ),
                    )
                    return jax.lax.cond(has_work, step, frozen, None)

                (tok, active, remaining, cache), toks = jax.lax.scan(
                    body, (tok, active, remaining, cache),
                    (chunks, lengths, reset_mask, reset_to, live),
                )
                return toks, tok, active, remaining, cache

            return run

        @jax.jit
        def multi_step(
            params, cache, chunks, lengths, reset_mask, reset_to, live,
            tok, active, remaining, rid, rng,
        ):
            """``horizon`` fused engine iterations in ONE dispatch: a
            ``lax.scan`` whose body is exactly ``mixed_step``'s
            (``_mixed_core`` — shared, so the two program families cannot
            drift), consuming one host-planned (chunk, lengths, resets)
            plan row per step and carrying tok/active/remaining/cache
            device-side. Per-row retirement happens IN-scan (remaining
            hits 0 / EOS flips ``active``), and a ``cond`` skips steps
            with no work, so the program is one executable per horizon
            and the host syncs once per N tokens instead of once per
            token. Token streams are bit-identical to N sequential
            ``mixed_step`` iterations (test-pinned): the per-row
            computation is the same, and sampling draws are keyed by
            (request id, generated position), never by schedule."""
            return _multi_scan(apply)(
                params, cache, chunks, lengths, reset_mask, reset_to,
                live, tok, active, remaining, rid, rng,
            )

        @jax.jit
        def adapter_multi_step(
            params, pool, aidx, cache, chunks, lengths, reset_mask,
            reset_to, live, tok, active, remaining, rid, rng,
        ):
            """``multi_step`` with the per-row adapter gather: ``sel`` is
            gathered ONCE outside the scan (``aidx`` is fixed for the
            whole horizon — admission only lands at horizon boundaries),
            then every scanned step applies each row's merged weights,
            bit-identical to N ``adapter_mixed_step`` iterations."""
            sel = jax.tree.map(lambda s: s[aidx], pool)
            return _multi_scan(_adapter_apply(sel))(
                params, cache, chunks, lengths, reset_mask, reset_to,
                live, tok, active, remaining, rid, rng,
            )

        def _spec_multi_scan(apply_fn):
            # The speculative multi-step loop: scans ``_spec_mixed_core``
            # with the per-row rollback state (pos) and BOTH caches in
            # the carry; each step's emission buffer/count/acceptance
            # telemetry ride the scan ys (stacked (N, B, ...) — the host
            # consumes them per planned link after the one sync).
            def run(params, d_params, t_cache, d_cache, chunks, lengths,
                    reset_mask, reset_to, live, tok, active, pos,
                    remaining, rid, rng):
                width = num_draft + 1

                def body(carry, x):
                    tok, active, pos, remaining, t_cache, d_cache = carry
                    chunk, lens, rmask, rto, lv = x

                    def step(_):
                        (first_tok, buffer, count, acc, prop, tok2, pos2,
                         active2, remaining2, t2, d2) = _spec_mixed_core(
                            apply_fn, params, d_params, t_cache, d_cache,
                            chunk, lens, rmask, rto, tok, active, pos,
                            remaining, rid, rng,
                        )
                        return (
                            (tok2, active2, pos2, remaining2, t2, d2),
                            (first_tok, buffer, count, acc, prop),
                        )

                    def frozen(_):
                        zi = jnp.zeros_like(tok)
                        zb = jnp.zeros((tok.shape[0], width), jnp.int32)
                        return (
                            (tok, active, pos, remaining, t_cache,
                             d_cache),
                            (tok, zb, zi, zi, zi),
                        )

                    has_work = jnp.logical_and(
                        lv > 0,
                        jnp.logical_or(
                            jnp.any(lens > 0), jnp.any(active == 1)
                        ),
                    )
                    return jax.lax.cond(has_work, step, frozen, None)

                carry0 = (tok, active, pos, remaining, t_cache, d_cache)
                (tok, active, pos, remaining, t_cache, d_cache), ys = (
                    jax.lax.scan(
                        body, carry0,
                        (chunks, lengths, reset_mask, reset_to, live),
                    )
                )
                first_toks, buffers, counts, accs, props = ys
                return (
                    first_toks, buffers, counts, accs, props, tok, pos,
                    active, remaining, t_cache, d_cache,
                )

            return run

        @jax.jit
        def spec_multi_step(
            params, d_params, t_cache, d_cache, chunks, lengths,
            reset_mask, reset_to, live, tok, active, pos, remaining, rid,
            rng,
        ):
            """The speculative ``multi_step``: ``horizon`` scanned
            ``spec_mixed_step`` bodies, each a budgeted refill sub-step
            plus one draft-verify round, with the per-row rollback index
            (``pos``) and both caches carried device-side. A step's
            1..num_draft+1 accepted tokens land in its ys buffer row; the
            host appends them per planned link after the single sync —
            bit-identical to N sequential ``spec_mixed_step``
            iterations."""
            return _spec_multi_scan(apply)(
                params, d_params, t_cache, d_cache, chunks, lengths,
                reset_mask, reset_to, live, tok, active, pos, remaining,
                rid, rng,
            )

        @jax.jit
        def adapter_spec_multi_step(
            params, pool, aidx, d_params, t_cache, d_cache, chunks,
            lengths, reset_mask, reset_to, live, tok, active, pos,
            remaining, rid, rng,
        ):
            """``spec_multi_step`` with the per-row adapter gather (once,
            outside the scan — see ``adapter_multi_step``): verification
            runs each row against its own merged weights, the shared
            draft proposes with the base weights, exactly as in
            ``adapter_spec_mixed_step``."""
            sel = jax.tree.map(lambda s: s[aidx], pool)
            return _spec_multi_scan(_adapter_apply(sel))(
                params, d_params, t_cache, d_cache, chunks, lengths,
                reset_mask, reset_to, live, tok, active, pos, remaining,
                rid, rng,
            )

        @jax.jit
        def kv_export(cache, slot):
            """One slot's cache ROW — every cache leaf indexed at ``slot``
            on its batch dim, per-row counters included (fixed shapes, so
            the export is one executable for the engine's lifetime). The
            prefill half of the DISAGGREGATED handoff (round 11): a pure
            per-device gather whose golden contract
            (``analysis/golden/kv_export.json``) pins that extracting a
            row adds no collectives — the cross-replica byte movement
            rides the explicit host transfer plan
            (``fleet.kv_transfer``), where it is counted, never hidden
            in XLA resharding."""
            return jax.tree.map(
                lambda x: jax.lax.dynamic_index_in_dim(
                    x, slot, 0, keepdims=False
                ),
                cache,
            )

        @jax.jit
        def kv_ingest(cache, rows, slot, index):
            """Write an externally produced cache row into ``slot`` and
            set its per-row counters to ``index`` (the row's valid
            length) — the decode half of the disaggregated handoff.
            Bytes past ``index`` are invisible to the causal-at-index
            masks (the ``_reset_rows`` invariant), so the transfer plan
            only has to deliver the valid prefix; its own golden
            (``analysis/golden/kv_ingest.json``) pins that the update
            adds no collectives when the rows arrive in this cache's own
            row layout (``kv_row_shardings``)."""

            def leaf(path, x, row):
                if getattr(path[-1], "key", None) in (
                    "cache_index", "position"
                ):
                    row = jnp.asarray(index)
                return jax.lax.dynamic_update_index_in_dim(
                    x, row.astype(x.dtype), slot, 0
                )

            return jax.tree_util.tree_map_with_path(leaf, cache, rows)

        @jax.jit
        def kv_page_spill(cache, pid):
            """One physical PAGE's K/V — every page-pool leaf
            (``_PAGE_LEAF_KEYS``) indexed at ``pid`` on its pool dim,
            returned as a flatten-ordered LIST (the page has no per-slot
            counters; a list avoids inventing a partial tree structure).
            The demotion half of the KV tier ladder (round 15): a pure
            per-device gather whose golden
            (``analysis/golden/kv_page_spill.json``) pins that demoting
            a page adds no collectives — the HBM→host bytes ride the
            counted ``parallel.resharding`` host plan."""
            return [
                jax.lax.dynamic_index_in_dim(x, pid, 0, keepdims=False)
                for path, x in jax.tree_util.tree_flatten_with_path(cache)[0]
                if getattr(path[-1], "key", None) in _PAGE_LEAF_KEYS
            ]

        @jax.jit
        def kv_page_fill(cache, page_rows, pid):
            """Write a spilled page's K/V rows back into physical page
            ``pid`` — the promotion half of the tier ladder, inverse of
            ``kv_page_spill`` (same flatten-ordered leaf list). Its own
            golden (``analysis/golden/kv_page_fill.json``) pins zero
            collectives when the rows arrive in this cache's page-row
            layout (pool dim dropped from each leaf's spec)."""
            flat, treedef = jax.tree_util.tree_flatten_with_path(cache)
            it = iter(page_rows)
            out = []
            for path, x in flat:
                if getattr(path[-1], "key", None) in _PAGE_LEAF_KEYS:
                    row = next(it)
                    x = jax.lax.dynamic_update_index_in_dim(
                        x, row.astype(x.dtype), pid, 0
                    )
                out.append(x)
            return jax.tree_util.tree_unflatten(treedef, out)

        # --- engine configuration and compiled programs -------------------
        self._mesh, self._rules = mesh, rules
        self._cfg, self._d_cfg = cfg, d_cfg
        self._b = batch_size
        self._max_new = max_new_tokens
        self._eos = eos_id
        self._refill_chunk = refill_chunk
        self._block_steps = decode_block_steps
        # Public and runtime-tunable: a frontend can raise it for
        # throughput phases and drop it to 1 for latency-sensitive
        # arrival bursts (read at each dispatch).
        self.decode_chain = decode_chain
        self._mixed = bool(mixed)
        # Public and runtime-tunable like decode_chain: the per-dispatch
        # token ceiling of the MIXED scheduler (decode rows funded first,
        # refill takes the remainder). The default funds one full refill
        # chunk alongside a full decode wave; read at each dispatch.
        self.token_budget = (
            token_budget if token_budget is not None
            else refill_chunk + batch_size
        )
        # Public and runtime-tunable like decode_chain/token_budget: the
        # number of fused engine iterations ONE dispatch advances
        # (ROADMAP item 1). ``horizon=1`` IS today's loop — same
        # programs, same goldens, same telemetry counters (test-pinned);
        # ``horizon>1`` routes the steady-state mixed path through the
        # scanned ``multi_step`` family (one executable per horizon) and
        # demotes the host to the async boundary planner
        # (``_plan_next_horizon``). Read at each dispatch.
        self.horizon = horizon
        self._num_draft = num_draft
        self._speculative = speculative
        # Recovery policies (round 10): request TTLs, admission control,
        # the burn-rate degradation ladder, and poison quarantine.
        self._deadline_s = deadline_s
        self._any_req_deadline = False
        self._max_queue = max_queue
        self._ladder = degradation
        self._max_strikes = max_dispatch_strikes
        self._spec_disabled = False
        self._shed_all = False
        self._base_budget: int | None = None
        self._paged = paged
        self._paged_pages = paged_pages
        self._page_size = page_size
        self._prefix = prefix_cache
        self._maybe_cast = maybe_cast
        self._d_cast = d_cast
        self._first_refill_fn = first_refill
        self._refill_step_fn = refill_step
        self._decode_block_fn = decode_block
        self._decode_block_spec_fn = decode_block_spec
        self._mixed_step_fn = mixed_step
        self._spec_mixed_step_fn = spec_mixed_step
        self._adapter_mixed_step_fn = adapter_mixed_step
        self._adapter_spec_mixed_step_fn = adapter_spec_mixed_step
        self._multi_step_fn = multi_step
        self._spec_multi_step_fn = spec_multi_step
        self._adapter_multi_step_fn = adapter_multi_step
        self._adapter_spec_multi_step_fn = adapter_spec_multi_step
        self._kv_export_fn = kv_export
        self._kv_ingest_fn = kv_ingest
        self._kv_page_spill_fn = kv_page_spill
        self._kv_page_fill_fn = kv_page_fill
        # Comm compression: the validated config, the drift probe, and
        # the host-side KV codec every counted transfer threads through.
        # The drift ladder is a dedicated one-level DegradationLadder —
        # same hysteresis machinery as the SLO ladder (round 10), driven
        # by drift-rate burn instead of SLO burn; level 1 means the
        # budget is breached and compression turns itself off.
        self._comp = comp
        self._comp_probe_fn = comp_probe
        if comp is not None and comp.collectives:
            from learning_jax_sharding_tpu.robustness.policies import (
                DegradationLadder,
            )

            self._comp_ladder = DegradationLadder(patience=1, max_level=1)
        else:
            self._comp_ladder = None
        self._comp_n = 0
        self._kv_codec = (
            get_codec(comp.kv_codec, block=comp.block)
            if comp is not None else None
        )

        # --- persistent state ---------------------------------------------
        self.rng = jax.random.key(0)
        self.cache_creations = 0     # lifetime count of cache-creating calls
        self.last_stats: dict | None = None
        self.last_latency: dict | None = None
        self._cache = None
        self._queue: deque[_Request] = deque()
        self._finished: dict[int, _Request] = {}
        self._next_rid = 0
        self._cast_src: tuple | None = None
        self._cast_out: tuple | None = None
        # Most recent dispatch arguments (closures over the engine's
        # live state — cleared when the served params change, see
        # _cast_params) — collective_inventory() re-lowers the compiled
        # programs with them to read per-step collective counts off the
        # HLO. NOTE abstract ShapeDtypeStruct capture does not work
        # here: AOT lowering treats a struct's sharding as a hard
        # constraint, and host-committed inputs that live dispatch
        # happily transfers then refuse to lower against the mesh.
        self._last_first_refill_args = None
        self._last_refill_args = None
        self._last_decode_args = None
        self._last_decode_plain_args = None   # degraded-spec decode_block
        self._last_mixed_args = None
        self._last_multi_args = None          # multi-step scan (horizon>1)
        # The async planner's staged next-horizon plan: (fingerprint,
        # plan) — consumed by the next _multi_dispatch only when the
        # boundary state still matches the prediction (see
        # _plan_next_horizon), so staging can never change results.
        self._staged_plan = None
        self._last_kv_export_args = None      # disaggregated handoff
        self._last_kv_ingest_args = None
        self._last_kv_page_spill_args = None  # KV tier ladder (round 15)
        self._last_kv_page_fill_args = None
        # Tenancy (round 12): zero-downtime weight hot-swap + multi-LoRA.
        # ``weights_version`` is pinned onto every request AT ADMISSION —
        # in-flight requests finish (or recompute bit-identically) on the
        # version they were admitted under, never a silent mid-sequence
        # weight change; ``finished_versions`` is the attribution log
        # (rid → version) the zero-downtime oracle audits.
        self.weights_version = 0
        self.finished_versions: dict[int, int] = {}
        self._staged_swap: dict | None = None
        self._installed: tuple | None = None   # committed (params, draft)
        self._swap_jit_cache: dict = {}        # device_reshard programs
        self._swap_plan_cache: dict = {}       # host transfer plans
        # KV economy (round 15): the prefix-registry DIGEST the fleet
        # router queries for prefix-aware placement, plus the tier
        # ladder's spill/fill bookkeeping. ``prefix_epoch`` bumps on any
        # registry KEY change (register, evict, spill, fill, flush), so
        # a digest is valid exactly while its epoch matches.
        self.prefix_epoch = 0
        self._digest_cache: tuple | None = None     # (epoch, hashes) memo
        self.expected_prefix: dict[int, int] = {}   # rid → predicted hit toks
        self.prefix_realized: dict[int, int] = {}   # rid → realized hit toks
        self._page_plan_cache: dict = {}            # spill/fill host plans
        self._adapter_pool = adapter_pool
        self._init_telemetry(registry, tracer, slo, recorder)
        if adapter_pool is not None:
            adapter_pool.bind(self.registry, self.recorder)
        self._init_slots()
        if paged:
            self._init_pool()
        self.reset_stats()

    # --- state initialisation --------------------------------------------

    def _init_telemetry(self, registry, tracer, slo=None, recorder=None):
        # Engine-local by default: each engine is its own measurement
        # window and trace timeline. A shared registry AGGREGATES: the
        # cumulative engine_* counters then carry every engine's
        # activity, so a scraper sees fleet totals — but window-derived
        # per-call stats (last_stats/last_latency) would include the
        # other engines' increments too. Keep the default (engine-local)
        # when per-engine stats matter; share only for fleet export.
        self.registry = (
            registry if registry is not None else MetricsRegistry()
        )
        self.tracer = tracer if tracer is not None else Tracer()
        # The flight recorder defaults to the PROCESS ring (post-mortems
        # want the whole process's recent history in one place); the SLO
        # monitor, if handed in unbound, exports through this engine's
        # registry/recorder.
        from learning_jax_sharding_tpu.telemetry import (
            default_flight_recorder,
        )

        self.recorder = (
            recorder if recorder is not None else default_flight_recorder()
        )
        # Span closures ride the ring next to the lifecycle events (the
        # dispatch timeline a post-mortem needs). With several engines on
        # one recorder, the last attachment wins the recorder's default
        # tracer for dump(); dump_diagnostics always passes its own.
        self.recorder.attach_tracer(self.tracer)
        self.slo = slo
        if slo is not None:
            if slo.registry is None:
                slo.registry = self.registry
            if slo.recorder is None:
                slo.recorder = self.recorder
        r = self.registry
        self._c_requests = r.counter(
            "engine_requests_total", "requests enqueued")
        self._c_finished = r.counter(
            "engine_requests_finished_total", "requests retired")
        self._c_tokens = r.counter(
            "engine_tokens_generated_total", "generated tokens emitted")
        self._c_preempt = r.counter(
            "engine_preemptions_total",
            "recompute preemptions under page-pool pressure")
        self._c_pfx_hits = r.counter(
            "engine_prefix_hits_total",
            "admissions that reused retained prefix pages")
        self._c_pfx_pages = r.counter(
            "engine_prefix_pages_reused_total",
            "prefix pages mapped on admission")
        self._c_spec_acc = r.counter(
            "engine_spec_accepted_total",
            "draft tokens accepted by the verifier")
        self._c_spec_prop = r.counter(
            "engine_spec_proposed_total", "draft tokens proposed")
        self._c_refill_s = r.counter(
            "engine_refill_seconds_total",
            "host-observed refill dispatch+sync seconds")
        self._c_decode_s = r.counter(
            "engine_decode_seconds_total",
            "host-observed decode dispatch+sync seconds")
        self._c_refill_n = r.counter(
            "engine_refill_dispatches_total", "refill dispatches")
        self._c_decode_n = r.counter(
            "engine_decode_dispatches_total", "decode dispatches")
        self._c_mixed_s = r.counter(
            "engine_mixed_seconds_total",
            "host-observed fused refill+decode dispatch+sync seconds")
        self._c_mixed_n = r.counter(
            "engine_mixed_dispatches_total",
            "fused refill+decode dispatches")
        self._c_stall_s = r.counter(
            "engine_decode_stall_seconds_total",
            "dispatch seconds during which decoding rows sat idle "
            "behind another slot's refill")
        self._c_multi_n = r.counter(
            "engine_multi_dispatches_total",
            "fused multi-step dispatches (horizon > 1 — one scanned "
            "program advancing N engine iterations)")
        self._c_multi_links = r.counter(
            "engine_multi_links_total",
            "engine iterations advanced inside multi-step dispatches "
            "(steps_per_dispatch = links / dispatches)")
        self._c_plan_staged = r.counter(
            "engine_plan_staged_total",
            "next-horizon refill plans staged by the async planner "
            "while a multi-step program was in flight")
        self._c_plan_reused = r.counter(
            "engine_plan_reused_total",
            "staged plans consumed at the next horizon boundary (the "
            "boundary state matched the planner's prediction)")
        self._c_creations = r.counter(
            "engine_cache_creations_total", "cache-creating first refills")
        self._c_shed = r.counter(
            "engine_shed_total",
            "arrivals rejected by admission control (bounded queue or "
            "degradation-ladder shedding)")
        self._c_deadline = r.counter(
            "engine_deadline_evictions_total",
            "requests failed by their TTL deadline (queued or in-flight)")
        self._c_quarantined = r.counter(
            "engine_quarantined_total",
            "requests failed as poison after repeated dispatch faults")
        self._c_dispatch_faults = r.counter(
            "engine_dispatch_faults_total",
            "dispatches aborted by a recoverable fault")
        self._c_req_failed = r.counter(
            "engine_requests_failed_total",
            "requests retired with a non-ok terminal status")
        self._c_rerouted = r.counter(
            "engine_rerouted_total",
            "requests drained with status 'rerouted' — failover/handoff "
            "requeue onto another fleet replica, never a lost request")
        self._c_kv_exports = r.counter(
            "engine_kv_exports_total",
            "retired-request KV rows exported for disaggregated handoff")
        self._c_kv_ingests = r.counter(
            "engine_kv_ingests_total",
            "externally prefilled requests ingested (disaggregated "
            "handoff)")
        self._c_pg_spills = r.counter(
            "engine_kv_page_spills_total",
            "retained prefix pages demoted (spilled) out of HBM to a "
            "host tier")
        self._c_pg_fills = r.counter(
            "engine_kv_page_fills_total",
            "prefix pages promoted (filled) back into HBM from a tier")
        self._c_pg_bytes_out = r.counter(
            "engine_kv_page_spill_bytes_total",
            "bytes moved HBM → host demoting prefix pages")
        self._c_pg_bytes_in = r.counter(
            "engine_kv_page_fill_bytes_total",
            "bytes moved host → HBM promoting prefix pages")
        self._c_kv_raw_bytes = r.counter(
            "engine_kv_raw_bytes_total",
            "pre-codec bytes of counted KV/page/swap host transfers — "
            "the *_bytes_total counters book WIRE bytes, so the gap to "
            "this counter is what the codec saved")
        self._c_comp_probes = r.counter(
            "engine_comp_drift_probes_total",
            "compressed-vs-plain-oracle drift probes run")
        self._c_comp_disagree = r.counter(
            "engine_comp_drift_disagreements_total",
            "active rows whose greedy pick diverged from the plain "
            "oracle during a drift probe")
        self._c_comp_trips = r.counter(
            "engine_comp_drift_trips_total",
            "drift-budget breaches that auto-disabled the quantized "
            "serving collectives (one-way until an operator re-enables)")
        self._c_pfx_expected = r.counter(
            "engine_prefix_expected_total",
            "admissions the router placed expecting a prefix hit")
        self._c_tier_miss = r.counter(
            "engine_tier_misses_total",
            "admissions whose realized prefix hit fell short of the "
            "router's prediction (page evicted/raced away mid-route) — "
            "the request gracefully re-prefilled the missing tokens")
        self._c_swap_staged = r.counter(
            "engine_swap_staged_total",
            "weight swaps staged (resharded into the serving layout off "
            "the hot path)")
        self._c_swap_commits = r.counter(
            "engine_swap_commits_total",
            "weight swaps atomically committed between dispatches")
        self._c_swap_aborted = r.counter(
            "engine_swap_aborted_total",
            "weight swaps aborted during staging — the engine kept the "
            "old version, in-flight requests unaffected")
        self._c_swap_bytes = r.counter(
            "engine_swap_bytes_total",
            "bytes moved staging swapped weight trees into the serving "
            "layout")
        self._c_adapter_n = r.counter(
            "engine_adapter_dispatches_total",
            "fused dispatches that gathered per-row adapters")
        self._c_adapter_rows = r.counter(
            "engine_adapter_rows_total",
            "occupied row-dispatches served under a non-base adapter")
        self._g_degraded = r.gauge(
            "engine_degradation_level",
            "current graceful-degradation ladder level (0 = normal)")
        self._g_queue = r.gauge(
            "engine_queue_depth", "requests waiting for a slot")
        self._g_active = r.gauge(
            "engine_active_slots", "slots actively decoding")
        self._g_pages = r.gauge(
            "engine_pages_live", "live (non-retained) pages held")
        self._g_retained = r.gauge(
            "engine_prefix_pages_retained",
            "reference-free retained prefix pages")
        self._g_comp_on = r.gauge(
            "engine_comm_compression_active",
            "1 while quantized serving collectives are compiled in")
        self._g_comp_ratio = r.gauge(
            "engine_kv_compression_ratio",
            "raw/wire byte ratio of the most recent counted KV transfer "
            "batch (1.0 when no codec is attached)")
        self._g_comp_on.set(
            1 if (self._comp is not None and self._comp.active) else 0
        )
        self._g_comp_ratio.set(1.0)
        self._h_ttft = r.histogram(
            "engine_ttft_seconds", "arrival to first visible token")
        self._h_tpot = r.histogram(
            "engine_tpot_seconds", "per-request mean inter-token seconds")
        self._h_itl = r.histogram(
            "engine_itl_seconds", "raw host-visibility gaps")
        self._h_wait = r.histogram(
            "engine_queue_wait_seconds", "arrival to slot admission")
        self._h_e2e = r.histogram(
            "engine_e2e_seconds", "arrival to retirement")
        self._h_swap_stall = r.histogram(
            "engine_swap_stall_seconds",
            "stage-to-commit latency of weight swaps (drain or preempt)")
        # Goodput ledger (round 14): exhaustive wall-clock attribution
        # for the engine loop. step() is the top-level frame (its
        # unclaimed remainder is host scheduling, bucket "sched");
        # dispatch/sync regions book "device" (re-bucketed to "compile"
        # when the executable cache grew), admission/page/handoff/swap/
        # recovery/telemetry paths open their own frames, and idle is
        # derived — reconcile() must hold after any run (tier-1 gated).
        # Meters into this registry as ledger_seconds_total{bucket=...}.
        self.ledger = GoodputLedger(registry=r)
        # Request-scoped trace sink (telemetry.tracecontext.TraceStore).
        # The fleet router attaches its store (and the replica name) to
        # every replica; a solo driver may attach its own — legs are
        # recorded at retirement from the stamps _Request already
        # carries, so the sink costs nothing when absent.
        self.trace_sink = None
        self.trace_replica = "engine"
        # fn-identity → program-family memo for _program_family (device
        # frames tag their ledger seconds with the dispatching program).
        self._fam_cache: dict[int, str] = {}

    #: jitted-fn attribute → program-family name, mirroring the names
    #: :meth:`_dispatched_programs` publishes — the ledger's per-family
    #: device attribution must key identically or overlap_report rows
    #: would never match a costmodel prediction.
    _FN_FAMILY_ATTRS = (
        ("_first_refill_fn", "first_refill"),
        ("_refill_step_fn", "refill_step"),
        ("_decode_block_spec_fn", "decode_block_spec"),
        ("_decode_block_fn", "decode_block"),
        ("_adapter_spec_mixed_step_fn", "adapter_mixed_step"),
        ("_adapter_mixed_step_fn", "adapter_mixed_step"),
        ("_spec_mixed_step_fn", "mixed_step"),
        ("_mixed_step_fn", "mixed_step"),
        ("_adapter_spec_multi_step_fn", "adapter_multi_step"),
        ("_adapter_multi_step_fn", "adapter_multi_step"),
        ("_spec_multi_step_fn", "multi_step"),
        ("_multi_step_fn", "multi_step"),
        ("_kv_export_fn", "kv_export"),
        ("_kv_ingest_fn", "kv_ingest"),
        ("_kv_page_spill_fn", "kv_page_spill"),
        ("_kv_page_fill_fn", "kv_page_fill"),
    )

    def _program_family(self, fn):
        """Program-family name for a jitted engine fn (None for frames
        with no fn — blocking readbacks book as "unattributed")."""
        if fn is None:
            return None
        fam = self._fam_cache.get(id(fn))
        if fam is None:
            fam = "unattributed"
            for attr, name in self._FN_FAMILY_ATTRS:
                if getattr(self, attr, None) is fn:
                    fam = name
                    break
            self._fam_cache[id(fn)] = fam
        return fam

    @contextlib.contextmanager
    def _led_device(self, fn=None, family=None):
        """Ledger frame for a dispatch or blocking readback: books to
        the ``device`` bucket (tagged with ``fn``'s program family for
        :meth:`overlap_report`), unless ``fn``'s executable cache GREW
        inside the region — then the call paid a trace+compile, not a
        device step, and the whole frame re-buckets to ``compile`` (the
        compile-steal idiom; ``cache_size`` probes the jit cache).

        ``family`` tags a frame WITHOUT a cache probe — the sync-frame
        form: under async dispatch the dispatch frame books only enqueue
        microseconds, so the blocking readback that drains a program's
        in-flight seconds must carry the SAME family tag or the
        overlap_report attribution would book the device time as
        unattributed."""
        before = cache_size(fn) if fn is not None else None
        with self.ledger.measure(
            "device",
            family=family if family is not None
            else self._program_family(fn),
        ) as f:
            yield f
            if before is not None:
                after = cache_size(fn)
                if after is not None and (before is None or after > before):
                    f.rebucket("compile")

    def _win_delta(self, counter):
        # The stats window (reset_stats → snapshot) over a cumulative
        # counter: value minus its base at the last reset.
        return counter.value - self._win_base.get(counter.name, 0.0)

    def _init_slots(self):
        b = self._b
        # A slot is: idle (req < 0), refilling (pending prompt tokens
        # remain), or decoding (active).
        self._req = [-1] * b               # request id per slot
        self._plen = [0] * b               # admitted prompt length per slot
        self._pending: list[np.ndarray] = [np.zeros((0,), np.int32)] * b
        self._emitted = [0] * b
        self._out: list[list[int]] = [[] for _ in range(b)]
        self._ttimes: list[list[float]] = [[] for _ in range(b)]
        self._slot_req: list[_Request | None] = [None] * b
        self._tok = np.zeros((b,), np.int32)
        self._active = np.zeros((b,), bool)
        # Per-slot adapter slot index into the AdapterPool's stacked tree
        # (0 = the base/zero adapter; always allocated — harmlessly all
        # zero on engines without a pool).
        self._aidx = np.zeros((b,), np.int32)
        # Admission reset flags live on the ENGINE, not in step() locals:
        # they are consumed by the first SUCCESSFUL refill dispatch, so a
        # raise between admission and dispatch (pool exhaustion) cannot
        # lose a row's counter reset (review finding, round 5).
        self._needs_reset = np.zeros((b,), bool)
        self._reset_to = np.zeros((b,), np.int32)
        # Retired-request → slot map while the slot's KV is still intact
        # (export window for the disaggregated handoff); entries drop the
        # moment the slot is reused by a later admission/ingestion.
        self._export_ok: dict[int, int] = {}

    def _init_pool(self):
        # Host-owned page allocator: page 0 is scratch; a slot holds a
        # prefix of logical blocks mapped to arbitrary physical pages.
        b = self._b
        self._free_pages = list(range(self._paged_pages - 1, 0, -1))
        self._held: list[list[int]] = [[] for _ in range(b)]
        t_cap = self._cfg.max_seq_len // self._page_size
        self._table_np = np.zeros((b, t_cap), np.int32)
        self._tables_dirty = True
        # Prefix-cache state (the metrics registry is the separate,
        # public ``self.registry``): page-aligned token-prefix bytes →
        # the page holding that prefix's LAST page of K/V; refcounts for pages
        # shared by live slots; ref-0 registered pages stay evictable in
        # LRU order (dict preserves insertion order).
        self._prefix_registry: dict[bytes, int] = {}
        self._key_of_page: dict[int, bytes] = {}
        self._refcnt: dict[int, int] = {}
        self._cached_lru: dict[int, None] = {}
        self._shared_count = [0] * b   # leading registry pages per slot
        self.prefix_epoch += 1         # any prior digest is now stale
        self._g_pages.set(0)
        self._g_retained.set(0)

    def reset_stats(self):
        """Start a stats window (``serve()`` calls this at entry;
        streaming users call it to start a measurement window). The
        registry's counters are CUMULATIVE (Prometheus semantics) and
        are never zeroed — the window is a base snapshot, and
        ``last_stats``/``latency_stats`` report deltas against it, so
        per-call stats keep their pinned meaning while a scraper sees
        monotone series."""
        self._completed: list[dict] = []
        self._itl: list[float] = []
        self._win_base = {
            c.name: c.value
            for c in (
                self._c_preempt, self._c_pfx_hits, self._c_pfx_pages,
                self._c_spec_acc, self._c_spec_prop, self._c_refill_s,
                self._c_decode_s, self._c_mixed_s, self._c_stall_s,
                self._c_multi_n, self._c_multi_links,
                self._c_plan_staged, self._c_plan_reused,
                self._c_requests, self._c_finished, self._c_shed,
                self._c_deadline, self._c_req_failed, self._c_rerouted,
                self._c_pg_spills, self._c_pg_fills,
                self._c_pg_bytes_out, self._c_pg_bytes_in,
                self._c_pfx_expected, self._c_tier_miss,
            )
        }
        # Window high-water for the page-pool gauge (live value rides on).
        self._g_pages.reset_high_water()
        self.ledger.begin_window()

    def reset(self):
        """Abandon all in-flight work and return the engine to idle.

        Frees every page (INCLUDING the prefix registry — retained K/V
        may be mid-write when this is called), clears the queue and
        slots; keeps the compiled programs and the allocated cache
        arrays (admission resets their counters)."""
        self._queue.clear()
        self._init_slots()
        if self._paged:
            self._init_pool()

    def drain_requests(
        self, *, status: str = "rerouted", error: str | None = None
    ) -> list[dict]:
        """DRAIN-AND-HANDOFF (round 11): retire EVERY queued and
        in-flight request with terminal ``status`` (surfaced through
        ``pop_finished`` — default ``"rerouted"``, the fleet router's
        failover drain, counted by ``engine_rerouted_total`` and
        ``latency_stats()["rerouted"]`` so a failover is visible instead
        of looking like fresh admissions elsewhere) and return
        requeueable records ``{rid, prompt, deadline_s, arrival_t}`` in
        slot-then-queue order.

        The drained requests RECOMPUTE EXACTLY on whatever engine
        re-admits them — the same guarantee as ``_unadmit``'s recompute
        preemption: greedy decoding is deterministic and every sampling
        draw is keyed by (request id, generated position), never by
        schedule or replica. Device state needs no repair (admission
        resets per-row counters); the compiled programs and cache stay
        for the next dispatch."""
        now = time.perf_counter()
        records: list[dict] = []

        def rec(r):
            records.append(dict(
                rid=r.rid, prompt=r.prompt, deadline_s=r.deadline_s,
                arrival_t=r.arrival_t,
            ))

        for slot in range(self._b):
            if self._slot_req[slot] is not None:
                rec(self._slot_req[slot])
                self._fail_slot(slot, status, error, now)
        while self._queue:
            r = self._queue.popleft()
            rec(r)
            self._fail_request(r, status, error, now=now)
        self._g_queue.set(0)
        self._g_active.set(0)
        self.recorder.record(
            "engine.drain", status=status, n=len(records),
        )
        return records

    def close(self):
        """Shut the engine down to idle: every in-flight or queued
        request is DRAINED TO A TERMINAL STATUS (``"shutdown"`` — a
        :class:`RequestFailure` with any partial tokens, surfaced
        through ``pop_finished``; never a silent drop a caller would
        poll forever), then the device state (KV cache + page pool +
        prefix registry) is released so HBM can be reclaimed.
        IDEMPOTENT: closing an idle/closed engine is a no-op beyond the
        state drop. Completed-but-unpopped results are host-side and
        survive. The engine stays usable: the next dispatch re-creates
        the cache (``cache_creations`` increments)."""
        self.drain_requests(status="shutdown", error="engine closed")
        self._cache = None
        self._cast_src = self._cast_out = None
        self._clear_dispatch_args()
        self._export_ok = {}
        if self._paged:
            self._init_pool()
        self.recorder.record("engine.close")

    def flush_prefix_cache(self):
        """Drop EVERY retained prefix page — call between checkpoints:
        the registry keys pages by token bytes only, so K/V computed
        under old params would silently serve new-params requests.
        Requires an IDLE engine (a live request sharing a registered
        page, or retiring after the flush, would re-expose or re-register
        old-params K/V — swap params only between requests)."""
        if not self._paged:
            return
        if self.has_work():
            raise RuntimeError(
                "flush_prefix_cache() requires an idle engine: drain "
                "in-flight work first (params must not change mid-request)"
            )
        self._drop_prefix_registry()

    def _drop_prefix_registry(self):
        # The registry-dropping core of ``flush_prefix_cache``, minus its
        # idle guard: a swap COMMIT calls this directly — commit requires
        # empty SLOTS only (retained pages are reference-free then), and
        # queued requests are fine: they admit after the commit, under
        # the new version, and can never see old-params K/V.
        for pid in list(self._cached_lru):
            del self._cached_lru[pid]
            del self._prefix_registry[self._key_of_page.pop(pid)]
            del self._refcnt[pid]
            self._free_pages.append(pid)
        # A dropped registry invalidates every exported digest — the
        # router's prefix-aware placement must stop scoring stale hits
        # (old-params K/V must never be routed TO, either).
        self.prefix_epoch += 1
        # Refresh the export gauges: retained pages just went to zero and
        # a scraper must not keep seeing the flushed K/V.
        self._update_high_water()

    # --- page allocator ----------------------------------------------------

    def _take_page(self):
        # Chaos seam: kind="oom" raises this allocator's own
        # RuntimeError, driving the recompute-preemption backpressure
        # path without actually draining the pool.
        chaos_hook("engine.page_alloc", free=len(self._free_pages))
        if self._free_pages:
            return self._free_pages.pop()
        if self._cached_lru:
            # Evict the oldest reference-free cached page — the pool must
            # serve live requests before retained ones.
            pid = next(iter(self._cached_lru))
            del self._cached_lru[pid]
            del self._prefix_registry[self._key_of_page.pop(pid)]
            del self._refcnt[pid]
            self.prefix_epoch += 1
            return pid
        raise RuntimeError(
            f"page pool exhausted ({self._paged_pages - 1} pages "
            f"× {self._page_size} tokens): raise paged_pages or "
            "lower concurrency"
        )

    def _live_pages(self) -> int:
        # LIVE pages only: retained reference-free prefix pages are
        # reclaimable at will, so they are not footprint — they are
        # reported separately (``prefix_pages_retained``).
        return (
            (self._paged_pages - 1)
            - len(self._free_pages)
            - len(self._cached_lru)
        )

    def _update_high_water(self):
        # The gauge carries both the live value (export) and the window
        # maximum (``last_stats["page_high_water"]``).
        self._g_pages.set(self._live_pages())
        self._g_retained.set(len(self._cached_lru))

    def _ensure(self, slot, tokens_through):
        # Allocate pages so positions [0, tokens_through) are mapped
        # before the dispatch that writes them.
        need = -(-int(tokens_through) // self._page_size)
        if len(self._held[slot]) >= need:
            return   # steady-state decode mostly allocates nothing
        with self.ledger.measure("page_alloc"):
            while len(self._held[slot]) < need:
                p = self._take_page()
                self._table_np[slot, len(self._held[slot])] = p
                self._held[slot].append(p)
                self._tables_dirty = True
            self._update_high_water()

    def _release(self, slot, register=True):
        # ``register=False``: the slot is being UN-admitted (backpressure),
        # so its prompt pages may be only partially written — never
        # register them; just free privates and drop shared refs.
        page_size = self._page_size
        if self._prefix and not register:
            pages, ns = self._held[slot], self._shared_count[slot]
            self._free_pages.extend(pages[ns:])
            for pid in reversed(pages[:ns]):
                self._refcnt[pid] -= 1
                if self._refcnt[pid] == 0:
                    self._cached_lru[pid] = None
            self._shared_count[slot] = 0
            self._held[slot] = []
            self._table_np[slot, :] = 0
            self._tables_dirty = True
            self._update_high_water()
            return
        if self._prefix:
            pages, ns = self._held[slot], self._shared_count[slot]
            # Private pages: RETAIN the ones fully inside the prompt
            # (immutable once written — generation never rewrites earlier
            # positions) under their token-prefix key; free the rest
            # (generated-region K/V). DEEPEST page first into the LRU —
            # admission chains break at the first missing page, so
            # eviction must take chain tails before roots or the stranded
            # descendants retain HBM with zero hit potential.
            p_toks = np.asarray(self._out[slot][: self._plen[slot]], np.int32)
            full = self._plen[slot] // page_size
            for j in range(len(pages) - 1, ns - 1, -1):
                pid = pages[j]
                if j < full:
                    key = p_toks[: (j + 1) * page_size].tobytes()
                    if key not in self._prefix_registry:
                        self._prefix_registry[key] = pid
                        self._key_of_page[pid] = key
                        self._refcnt[pid] = 0
                        self._cached_lru[pid] = None
                        self.prefix_epoch += 1
                        continue
                self._free_pages.append(pid)
            for pid in reversed(pages[:ns]):   # drop shared refs,
                self._refcnt[pid] -= 1         # tails first too
                if self._refcnt[pid] == 0:
                    self._cached_lru[pid] = None
            # LRU refresh across RETIREMENTS (advisor r4): a chain root
            # registered by an earlier retirement would sit OLDER in the
            # LRU than a tail registered just now, so eviction could take
            # the root first and strand its descendants as unmatchable.
            # Touch this prompt's whole chain deepest-first, so every
            # ancestor ends up newer than its deepest tail.
            for k in range(full, 0, -1):
                pid = self._prefix_registry.get(p_toks[: k * page_size].tobytes())
                if pid is not None and pid in self._cached_lru:
                    del self._cached_lru[pid]
                    self._cached_lru[pid] = None
            self._shared_count[slot] = 0
        else:
            self._free_pages.extend(self._held[slot])
        self._held[slot] = []
        self._table_np[slot, :] = 0
        self._tables_dirty = True
        self._update_high_water()

    def _set_tables(self, cache):
        # Push the host tables into every layer's block_table leaf
        # (target AND draft trees; the draft's table may be narrower —
        # same prefix, same page ids). Skipped entirely when no
        # allocation changed since the last push — the steady-state
        # decode loop mostly doesn't allocate.
        if not self._tables_dirty:
            return cache
        self._tables_dirty = False
        table_np = self._table_np

        def leaf(path, x):
            if getattr(path[-1], "key", None) == "block_table":
                # .copy(): the full-width slice is a contiguous view and
                # jnp.asarray may alias it zero-copy — the host table is
                # mutated in place by later allocations/releases.
                return jnp.asarray(table_np[:, : x.shape[1]].copy())
            return x

        return jax.tree_util.tree_map_with_path(leaf, cache)

    # --- request lifecycle -------------------------------------------------

    def _validate_prompt(self, p: np.ndarray):
        if p.size < 1:
            raise ValueError("empty prompt")
        headroom = self._num_draft + 1 if self._speculative else 0
        budget_cfgs = (
            [("target", self._cfg), ("draft", self._d_cfg)]
            if self._speculative else [("target", self._cfg)]
        )
        for name, c in budget_cfgs:
            # The draft cache must fit the same worst case as the
            # target's: its index walks in lockstep through prefill,
            # proposals, and rollback.
            check_sequence_budget(
                p.size + self._max_new + headroom, c.max_seq_len,
                f"prompt ({p.size}) + max_new_tokens ({self._max_new})"
                + (f" + draft headroom ({headroom})" if headroom else "")
                + f" for {name}",
            )

    def _check_draft_args(self, draft_params):
        if self._speculative and draft_params is None:
            raise ValueError(
                "draft_config was given: pass draft_params to serve()/step()"
            )
        if not self._speculative and draft_params is not None:
            raise ValueError("draft_params requires draft_config")

    def _cast_params(self, params, draft_params):
        # The eager inference cast runs once per (params, draft_params)
        # OBJECT pair, not once per step — the cached copies are keyed by
        # identity and hold a reference, so the same tree passed across
        # steps (and across serve() calls) is cast exactly once.
        if self._cast_src is not None and (
            self._cast_src[0] is params and self._cast_src[1] is draft_params
        ):
            return self._cast_out
        out = (
            self._maybe_cast(params),
            self._d_cast(draft_params) if draft_params is not None else None,
        )
        self._cast_src = (params, draft_params)
        self._cast_out = out
        # The stored dispatch-args closures reference the PREVIOUS cast
        # trees — stale for collective_inventory(), and keeping them
        # would hold both parameter trees in HBM across a checkpoint
        # swap. Drop them; the next dispatch re-captures.
        self._clear_dispatch_args()
        return out

    def _clear_dispatch_args(self):
        self._last_first_refill_args = None
        self._last_refill_args = self._last_decode_args = None
        self._last_decode_plain_args = None
        self._last_mixed_args = None
        self._last_multi_args = None
        self._staged_plan = None
        self._last_kv_export_args = None
        self._last_kv_ingest_args = None
        self._last_kv_page_spill_args = None
        self._last_kv_page_fill_args = None

    # --- zero-downtime weight hot-swap (round 12) --------------------------

    def swap_weights(
        self, new_params, *, version: int, draft_params=None,
        mode: str = "drain",
    ) -> bool:
        """Stage ``new_params`` for a ZERO-DOWNTIME weight swap and
        commit it atomically between dispatches.

        Staging happens NOW, off the dispatch hot path: the tree is run
        through the engine's inference cast and RESHARDED into the
        serving layout (``parallel.resharding.reshard_tree`` — the
        single-program device path for an intra-mesh layout change, the
        explicit counted host plan across device sets; plans and
        compiled movers are cached across swaps). The engine keeps
        serving the OLD version throughout; nothing the scheduler
        touches changes until the commit.

        The COMMIT flips ``weights_version`` to ``version`` and installs
        the staged tree as the engine's own weights (later ``step()``
        calls may omit ``params``; a stale caller-passed tree is
        overridden). It fires only when ZERO slots are occupied:

        * ``mode="drain"`` (default): admission pauses, in-flight
          requests FINISH ON THE OLD WEIGHTS, and the first
          ``step()`` that finds the slots empty commits — then re-admits
          the queued backlog under the new version in that same step, so
          a loaded engine swaps with zero dropped/failed requests.
        * ``mode="preempt"``: every in-flight request is requeued
          (recompute preemption — it RECOMPUTES BIT-IDENTICALLY under
          the new version, the ``_unadmit`` guarantee) and the commit
          happens immediately.

        Every request is attributable to exactly one version: pinned at
        admission (``_Request.version``), logged at retirement
        (``finished_versions``), never changed mid-sequence. On paged
        engines the commit drops the prefix registry (old-params K/V
        must not seed new-params requests).

        A fault injected at the ``engine.swap_stage`` chaos seam (or a
        recoverable staging failure) ABORTS the swap: the engine stays
        on the old version, in-flight requests are unaffected, and the
        abort lands in ``engine_swap_aborted_total`` and the flight
        recorder. Returns True when staged (the commit may still be
        pending), False on an aborted staging."""
        from learning_jax_sharding_tpu.parallel.resharding import (
            reshard_tree,
        )

        if mode not in ("drain", "preempt"):
            raise ValueError(
                f"mode must be 'drain' or 'preempt', got {mode!r}"
            )
        self._check_draft_args(draft_params)
        if self._staged_swap is not None:
            raise RuntimeError(
                f"a weight swap is already staged (version "
                f"{self._staged_swap['version']}): it commits when the "
                "slots drain — stage the next version after that"
            )
        ref = self._cast_out

        def stage(tree, ref_tree):
            if tree is None:
                return None, 0
            if ref_tree is None:
                # Never dispatched: no serving layout to mirror yet —
                # the cast tree is staged as-given and the first
                # dispatch places it like any initial params.
                return tree, 0
            dst = jax.tree.map(lambda x: x.sharding, ref_tree)
            # The engine's KV codec rides the swap too: the intra-mesh
            # device fast path stays exact (the swap_reshard golden's
            # program), but a cross-device-set HOST leg ships weights as
            # block-scaled int8 — the quantized grad-sync premise
            # (zero.py) applied to staging traffic, and the staged tree
            # is what every later dispatch AND recompute serves, so
            # version attribution stays exact.
            with activate(self._mesh, self._rules):
                out, stats = reshard_tree(
                    tree, dst, plan_cache=self._swap_plan_cache,
                    jit_cache=self._swap_jit_cache, codec=self._kv_codec,
                )
            return out, int(stats["bytes"])

        t0 = time.perf_counter()
        with self.ledger.measure("swap"):
            try:
                chaos_hook("engine.swap_stage", version=version, mode=mode)
                cast = self._maybe_cast(new_params)
                d_cast = (
                    self._d_cast(draft_params)
                    if draft_params is not None else None
                )
                cast, p_bytes = stage(cast, ref[0] if ref else None)
                d_cast, d_bytes = stage(d_cast, ref[1] if ref else None)
            except _RECOVERABLE_DISPATCH as e:
                self._c_swap_aborted.inc()
                self.recorder.record(
                    "engine.swap_abort", version=version, mode=mode,
                    error=str(e),
                )
                return False
            moved = p_bytes + d_bytes
            self._staged_swap = dict(
                version=version, mode=mode,
                raw=(new_params, draft_params), cast=(cast, d_cast),
                staged_t=time.perf_counter(),
            )
            self._c_swap_staged.inc()
            self._c_swap_bytes.inc(moved)
            self.recorder.record(
                "engine.swap_stage", version=version, mode=mode, bytes=moved,
                stage_s=time.perf_counter() - t0,
                occupied=sum(q >= 0 for q in self._req),
                queue_depth=len(self._queue),
            )
            if mode == "preempt":
                for slot in range(self._b):
                    if self._req[slot] >= 0:
                        self._unadmit(slot)
                        self._c_preempt.inc()
            # An idle engine (and every preempt-mode swap) commits here
            # and now; a draining engine commits in the step() that
            # empties it.
            self._try_commit_swap()
        return True

    def _try_commit_swap(self) -> bool:
        # The atomic switch: between dispatches, only with EMPTY slots —
        # no in-flight request can ever straddle two versions.
        s = self._staged_swap
        if s is None or any(q >= 0 for q in self._req):
            return False
        with self.ledger.measure("swap"):
            if self._paged:
                # Old-params K/V must not seed new-params requests; slots
                # are empty, so every retained page is reference-free.
                self._drop_prefix_registry()
            self._installed = s["raw"]
            # Prime the identity-keyed cast cache with the STAGED trees:
            # the next dispatch's _cast_params hits it, so the swap costs
            # the hot path nothing (staging already cast and resharded).
            self._cast_src = s["raw"]
            self._cast_out = s["cast"]
            self._clear_dispatch_args()
            prev = self.weights_version
            self.weights_version = s["version"]
            self._staged_swap = None
            stall = time.perf_counter() - s["staged_t"]
            self._c_swap_commits.inc()
            self._h_swap_stall.observe(stall)
            self.recorder.record(
                "engine.swap_commit", version=s["version"], previous=prev,
                mode=s["mode"], stall_s=stall,
            )
            if self.trace_sink is not None:
                # Version-pin attribution: every request still queued
                # here will (re-)admit under the NEW version — the pin
                # lands on its trace, so a swap-preempt recompute's
                # before/after legs are tell-apart-able by version.
                for r in self._queue:
                    self.trace_sink.instant(
                        r.rid, "swap_pin", replica=self.trace_replica,
                        version=s["version"], previous=prev,
                        stall_s=stall,
                    )
        return True

    def add_request(
        self, prompt, *, rid: int | None = None,
        deadline_s: float | None = None,
        arrival_t: float | None = None,
        adapter: str | None = None,
        tenant: str | None = None,
    ) -> int:
        """Enqueue one request (the arrival process). Returns its id —
        the key ``pop_finished()`` will report it under, and (at
        ``temperature > 0``) the identity its sampling streams are keyed
        by. Admission happens inside a later ``step()``.

        ``deadline_s`` overrides the engine's default TTL for this
        request (arrival-to-retirement; exceeded → failed with status
        ``"deadline"``). Raises :class:`AdmissionError` when admission
        control sheds the arrival (queue at ``max_queue``, or the
        degradation ladder at its shedding level) — nothing is
        enqueued, so the caller can back off.

        ``arrival_t`` (a ``time.perf_counter`` stamp) preserves the
        ORIGINAL arrival clock when re-queuing after a failover drain
        (``drain_requests``) — deadlines and queue-wait telemetry then
        measure the request's true age, not its age on this replica.

        ``adapter`` names an :class:`~learning_jax_sharding_tpu.tenancy.
        AdapterPool` tenant (engine built with ``adapter_pool=``): every
        token of this request is then generated against the BASE +
        tenant-adapter merged weights inside the fused multi-LoRA step.
        The adapter is ACQUIRED here (refcounted — it cannot be evicted
        while this request is live) and released at retirement.

        ``tenant`` labels the request for per-tenant cost attribution
        and SLO burn accounting (round 20): the retirement's SLO
        observations carry it, and the fleet's TraceStore record is
        minted with it — purely observational, never a routing input.
        """
        p = np.asarray(prompt, np.int32).reshape(-1)
        self._validate_prompt(p)
        if adapter is not None and self._adapter_pool is None:
            raise ValueError(
                "adapter= requires an engine built with adapter_pool="
            )
        if self._shed_all or (
            self._max_queue is not None
            and len(self._queue) >= self._max_queue
        ):
            self._c_shed.inc()
            why = (
                "degradation ladder is shedding"
                if self._shed_all
                else f"queue full ({self._max_queue})"
            )
            self.recorder.record(
                "engine.shed", reason=why, queue_depth=len(self._queue),
            )
            raise AdmissionError(f"request shed: {why}")
        if deadline_s is not None:
            if deadline_s <= 0:
                raise ValueError(
                    f"deadline_s must be > 0, got {deadline_s}"
                )
            self._any_req_deadline = True
        if rid is None:
            rid = self._next_rid
            self._next_rid += 1
        else:
            # An explicit id must be unique among everything live NOW
            # (silent result overwrite in _finished otherwise) and must
            # not collide with later auto-assigned ones.
            if (
                rid in self._finished
                or rid in self._req
                or any(r.rid == rid for r in self._queue)
            ):
                raise ValueError(f"request id {rid} already in use")
            self._next_rid = max(self._next_rid, rid + 1)
        if adapter is not None:
            # Acquire BEFORE enqueueing: an unknown tenant raises here
            # (nothing enqueued), and the refcount pins the adapter's
            # pool slot for the request's whole lifetime.
            self._adapter_pool.acquire(adapter)
        now = time.perf_counter()
        self._queue.append(
            _Request(
                rid=rid, prompt=p,
                arrival_t=now if arrival_t is None else arrival_t,
                deadline_s=deadline_s,
                version=self.weights_version,
                adapter=adapter,
                enqueue_t=now,
                tenant=tenant,
            )
        )
        self._c_requests.inc()
        self._g_queue.set(len(self._queue))
        if self.trace_sink is not None:
            # Solo engines mint here; under a fleet router the id was
            # minted at ROUTER admission and this is an idempotent
            # lookup (reroutes re-enqueue under the same rid → same
            # trace id, the continuity the tracecontext tests pin).
            self.trace_sink.mint(
                rid, arrival_t=self._queue[-1].arrival_t, tenant=tenant,
            )
        self.tracer.instant(
            "request.arrival", rid=rid, prompt_len=int(p.size)
        )
        self.recorder.record(
            "engine.arrival", rid=rid, prompt_len=int(p.size),
            queue_depth=len(self._queue),
        )
        return rid

    def has_work(self) -> bool:
        # A staged-but-uncommitted weight swap is work: it takes one
        # more step() to commit, and a driver that stops stepping at
        # "no requests left" must not strand the engine mid-swap.
        return (
            bool(self._queue)
            or any(r >= 0 for r in self._req)
            or self._staged_swap is not None
        )

    @property
    def swap_pending(self) -> bool:
        """True while a staged weight swap awaits its commit (drivers
        that pace their own swap cadence poll this instead of staging
        on top of a pending one, which raises)."""
        return self._staged_swap is not None

    def queue_depth(self) -> int:
        """Requests waiting for a slot — the fleet router's load probe."""
        return len(self._queue)

    def active_slots(self) -> int:
        """Slots actively decoding right now."""
        return int(self._active.sum())

    def occupied_slots(self) -> int:
        """Slots holding a request — decoding OR mid-prefill (a slot is
        occupied from admission, before its first decode token; the
        fleet placement score must see that load too)."""
        return sum(1 for r in self._req if r >= 0)

    def free_slots(self) -> int:
        """Idle slots available for admission or external KV ingestion."""
        return sum(1 for r in self._req if r < 0)

    def pop_finished(self) -> dict[int, Any]:
        """Collect every request RETIRED since the last pop. Completed
        requests map to their ``[prompt, generated...]`` token array;
        requests that hit a recovery policy (deadline TTL, poison
        quarantine, malformed admission, ``close()``) map to a
        :class:`RequestFailure` carrying the terminal status and any
        partial tokens — an error is a result, never a silent drop."""
        fin = {
            rid: (
                r.tokens if r.status == "ok"
                else RequestFailure(
                    rid=rid, status=r.status, error=r.error, tokens=r.tokens,
                )
            )
            for rid, r in self._finished.items()
        }
        self._finished = {}
        return fin

    # --- disaggregated prefill/decode handoff (round 11) -------------------

    def _check_handoff_supported(self, what: str):
        if self._speculative:
            raise ValueError(
                f"{what}: speculative engines are not supported — the "
                "draft cache would have to ride the handoff in lockstep"
            )
        if self._paged:
            raise ValueError(
                f"{what}: paged engines are not supported — rows live "
                "behind host-owned block tables, not contiguous cache rows"
            )
        if self._adapter_pool is not None:
            raise ValueError(
                f"{what}: multi-LoRA engines are not supported — a handed-"
                "off row's K/V was computed under a tenant adapter the "
                "receiving engine may not hold"
            )

    def ensure_cache(self, params, draft_params=None):
        """Create the engine's (zeroed) KV cache WITHOUT admitting work —
        the disaggregated-decode bring-up hook: ``ingest_kv`` and
        ``kv_row_shardings`` need the cache arrays (and the shardings the
        compiler gave them) to exist before the first external row lands.
        Runs the one-shot cache-creating program with an all-zero-length
        chunk (no writes, no advances — the same trick the paged path
        uses), so ``cache_creations`` counts it like any other creation.
        No-op when the cache already exists."""
        self._check_draft_args(draft_params)
        params, d_params = self._cast_params(params, draft_params)
        if self._cache is not None:
            return
        with activate(self._mesh, self._rules):
            first_args = (
                params, d_params,
                jnp.zeros((self._b, self._refill_chunk), jnp.int32),
                jnp.zeros((self._b,), jnp.int32), self._rid_arr(),
                self.rng,
            )
            _, self._cache = self._first_refill_fn(*first_args)
            if self._paged:
                self._cache = self._set_tables(self._cache)
        self.cache_creations += 1
        self._c_creations.inc()
        self.recorder.record("engine.cache_create", n=self.cache_creations)
        self._last_first_refill_args = lambda: first_args

    def kv_row_shardings(self):
        """Per-leaf :class:`~jax.sharding.NamedSharding` of ONE cache row
        (the batch dim dropped) — the destination layout a KV transfer
        plan reshards into (``fleet.kv_transfer.transfer_tree``). Rows
        delivered in this layout make ``kv_ingest`` a purely local
        update, which is exactly what its golden contract pins."""
        if self._cache is None:
            raise RuntimeError(
                "kv_row_shardings: the engine holds no cache yet — call "
                "ensure_cache(params) first"
            )
        from jax.sharding import NamedSharding, PartitionSpec

        def leaf(x):
            spec = getattr(x.sharding, "spec", None)
            if spec is None or len(tuple(spec)) == 0:
                return NamedSharding(self._mesh, PartitionSpec())
            return NamedSharding(self._mesh, PartitionSpec(*tuple(spec)[1:]))

        return jax.tree.map(leaf, self._cache)

    def kv_row_seq_dims(self):
        """Per-leaf SEQUENCE dim of one cache row (``-1`` = no sequence
        dim — transfer the leaf whole; a plain int, not None, so the
        map stays a well-formed pytree), for the transfer plan's
        valid-length clipping. Derived from the row SHAPES, not assumed:
        the dense decode backend caches rows sequence-major
        ``(S, n_kv, h)`` but the blocked backend (the TPU ``auto``
        default) is HEAD-major ``(n_kv, S, h)`` — a hard-coded dim 0
        would clip the KV-heads dim there and hand the decode replica
        zeroed heads. A row dim is the sequence dim iff it is the ONE
        dim sized ``max_seq_len``; ambiguous shapes fall back to -1
        (whole-leaf transfer: always correct, just unclipped)."""
        if self._cache is None:
            raise RuntimeError(
                "kv_row_seq_dims: the engine holds no cache yet — call "
                "ensure_cache(params) first"
            )
        s = self._cfg.max_seq_len

        def leaf(x):
            if x.ndim < 2:
                return -1
            row_shape = tuple(x.shape[1:])
            hits = [d for d, n in enumerate(row_shape) if n == s]
            return hits[0] if len(hits) == 1 else -1

        return jax.tree.map(leaf, self._cache)

    def export_kv(self, rid: int):
        """DISAGGREGATED-PREFILL hook: ``(rows, length)`` for a request
        that RETIRED here — every cache leaf's row for the slot it
        occupied (counters included; one fixed-shape executable), plus
        the row's valid length (``prompt + generated − 1``: the last
        emitted token was never written back). Valid until a later
        admission reuses the slot, so export immediately after the
        ``step()`` that retired the request — the fleet router does.
        ``length`` bounds the transfer plan: bytes past it are invisible
        to the causal-at-index masks and never cross the wire."""
        self._check_handoff_supported("export_kv")
        slot = self._export_ok.get(rid)
        if slot is None:
            raise KeyError(
                f"request {rid} is not exportable: it never retired here, "
                "or its slot was already reused by a later admission"
            )
        if self._cache is None:
            raise RuntimeError("export_kv: the engine holds no cache")
        with self.ledger.measure("kv_handoff"):
            slot_j = jnp.int32(slot)
            with activate(self._mesh, self._rules):
                rows = self._kv_export_fn(self._cache, slot_j)
            # Read the LIVE cache at relower time (like _last_decode_args
            # et al.) — capturing the tuple would pin this moment's cache
            # tree in HBM after later dispatches replace it.
            self._last_kv_export_args = lambda: (self._cache, slot_j)
            length = max(0, self._plen[slot] + self._emitted[slot] - 1)
            self._c_kv_exports.inc()
            self.recorder.record(
                "engine.kv_export", rid=rid, slot=slot, length=length,
            )
        return rows, length

    def ingest_kv(
        self, params, prompt, first_token, rows, *, rid: int,
        deadline_s: float | None = None,
        arrival_t: float | None = None,
        admit_t: float | None = None,
        first_token_t: float | None = None,
        tenant: str | None = None,
    ) -> int:
        """EXTERNAL KV INGESTION: occupy a free slot with a request whose
        PREFILL RAN ON ANOTHER ENGINE — write its transferred cache
        ``rows`` (an ``export_kv`` tree, resharded to this mesh by the
        fleet transfer plan), set the row's counters to the prompt
        length, and mark it decoding with ``first_token`` pending. The
        request then advances through the normal ``step()`` path; greedy
        AND sampled streams are bit-identical to serving the whole
        request on one engine of the same mesh shape (the rows hold
        exactly the bytes this engine's own prefill would have written,
        and every sampling draw is keyed by (request id, generated
        position) — test-pinned). The ``*_t`` stamps carry the request's
        ORIGINAL clock across the handoff so deadlines and latency
        percentiles stay honest. Returns the slot taken; raises
        ``RuntimeError`` when no slot is free (the router holds the
        handoff until one is)."""
        self._check_handoff_supported("ingest_kv")
        with self.ledger.measure("kv_handoff"):
            p = np.asarray(prompt, np.int32).reshape(-1)
            self._validate_prompt(p)
            if (
                rid in self._finished
                or rid in self._req
                or any(r.rid == rid for r in self._queue)
            ):
                raise ValueError(f"request id {rid} already in use")
            self._next_rid = max(self._next_rid, rid + 1)
            slot = next(
                (s for s in range(self._b) if self._req[s] < 0), None
            )
            if slot is None:
                raise RuntimeError(
                    "ingest_kv: no free slot — poll free_slots() before "
                    "transferring"
                )
            self.ensure_cache(params)
            slot_j, idx_j = jnp.int32(slot), jnp.int32(int(p.size))
            with activate(self._mesh, self._rules):
                self._cache = self._kv_ingest_fn(
                    self._cache, rows, slot_j, idx_j
                )
            # Live-cache closure (see export_kv): only the one transferred
            # row tree stays retained for relowering, never a stale copy of
            # the whole pre-ingest cache.
            self._last_kv_ingest_args = lambda: (
                self._cache, rows, slot_j, idx_j,
            )
            now = time.perf_counter()
            r = _Request(
                rid=rid, prompt=p,
                arrival_t=now if arrival_t is None else arrival_t,
                deadline_s=deadline_s,
                version=self.weights_version,
                tenant=tenant,
            )
            r.admit_t = now if admit_t is None else admit_t
            r.first_token_t = now if first_token_t is None else first_token_t
            r.enqueue_t = now
            # Prefill ran on ANOTHER engine: this engine's trace legs
            # must cover only its own decode work (the handoff leg is the
            # router's to record — it saw both ends of the transfer).
            r.ingested = True
            if deadline_s is not None:
                self._any_req_deadline = True
            self._export_ok = {
                k: v for k, v in self._export_ok.items() if v != slot
            }
            self._slot_req[slot] = r
            self._req[slot] = rid
            self._plen[slot] = int(p.size)
            self._pending[slot] = np.zeros((0,), np.int32)
            self._emitted[slot] = 1
            self._out[slot] = list(p) + [int(first_token)]
            self._ttimes[slot] = [r.first_token_t]
            self._tok[slot] = int(first_token)
            self._needs_reset[slot] = False
            self._reset_to[slot] = 0
            self._c_requests.inc()
            self._c_kv_ingests.inc()
            self.tracer.async_begin(
                "request", rid, prompt_len=int(p.size), slot=slot,
            )
            self.recorder.record(
                "engine.kv_ingest", rid=rid, slot=slot, length=int(p.size),
            )
            if (
                self._eos is not None and int(first_token) == self._eos
            ) or self._max_new <= 1:
                # The handed-off first token already ends the request.
                self._retire(slot, now, [])
            else:
                self._active[slot] = True
                self._g_active.set(int(self._active.sum()))
        return slot

    # --- KV tier ladder (round 15): prefix digest + page spill/fill --------

    @staticmethod
    def prefix_hash(key: bytes) -> bytes:
        """The 8-byte digest hash of one registry key (page-aligned
        token-prefix bytes) — the unit :meth:`prefix_digest` exports and
        the router matches prompt chains against."""
        return hashlib.blake2b(key, digest_size=8).digest()

    def prefix_digest(self) -> tuple[int, frozenset]:
        """``(epoch, hashes)`` — a compact, queryable digest of the
        prefix registry for PREFIX-AWARE FLEET PLACEMENT: one
        :meth:`prefix_hash` per registered page-aligned token prefix.
        The router hashes an arriving prompt's page chain and walks it
        against each replica's digest to predict the longest cached
        prefix BEFORE placing the request. ``epoch`` bumps on any
        registry key change (register / evict / spill / fill /
        swap-commit flush), so a cached digest is valid exactly while
        its epoch matches; the memo makes steady-state queries O(1)."""
        if not (self._paged and self._prefix):
            return (self.prefix_epoch, frozenset())
        if (
            self._digest_cache is None
            or self._digest_cache[0] != self.prefix_epoch
        ):
            self._digest_cache = (
                self.prefix_epoch,
                frozenset(
                    self.prefix_hash(k) for k in self._prefix_registry
                ),
            )
        return self._digest_cache

    def retained_prefixes(self) -> list[bytes]:
        """Registry keys of the REFERENCE-FREE retained pages, oldest
        (LRU-eviction order) first — the tier ladder's demotion
        candidates. Pages shared by live slots are excluded: they cannot
        leave HBM mid-request."""
        if not (self._paged and self._prefix):
            return []
        return [
            self._key_of_page[pid]
            for pid in self._cached_lru
            if pid in self._key_of_page
        ]

    def touch_prefix(self, key: bytes) -> bool:
        """LRU-refresh a resident reference-free prefix page. The tier
        ladder touches a chain's RESIDENT ancestors before promoting its
        missing descendants, so the promotion's own ``_take_page`` calls
        cannot evict the chain out from under itself. No-op (``False``)
        if the key is unregistered or the page is shared by a live
        slot."""
        if not (self._paged and self._prefix):
            return False
        pid = self._prefix_registry.get(key)
        if pid is None or pid not in self._cached_lru:
            return False
        self._cached_lru.pop(pid)
        self._cached_lru[pid] = None
        return True

    def _check_tier_supported(self, what: str):
        if not (self._paged and self._prefix):
            raise RuntimeError(
                f"{what} requires a paged engine with prefix_cache=True"
            )
        if self._speculative:
            # A spec engine's retained pages hold target AND draft K/V
            # under one page id; spilling only the target leaves would
            # hand a promoted page garbage draft state. Tier the plain
            # engines; spec replicas serve prefix hits from HBM only.
            raise RuntimeError(f"{what}: speculative engines are not tiered")

    def _page_row_shardings(self) -> list:
        """Per-leaf :class:`~jax.sharding.NamedSharding` of ONE page row
        (the pool dim dropped), flatten-ordered like ``kv_page_spill``'s
        output list — the destination layout host→HBM promotion reshards
        into, making ``kv_page_fill`` a purely local update (what its
        golden pins)."""
        from jax.sharding import NamedSharding, PartitionSpec

        rows = []
        for path, x in jax.tree_util.tree_flatten_with_path(self._cache)[0]:
            if getattr(path[-1], "key", None) not in _PAGE_LEAF_KEYS:
                continue
            spec = getattr(x.sharding, "spec", None)
            if spec is None or len(tuple(spec)) == 0:
                rows.append(NamedSharding(self._mesh, PartitionSpec()))
            else:
                rows.append(
                    NamedSharding(
                        self._mesh, PartitionSpec(*tuple(spec)[1:])
                    )
                )
        return rows

    def spill_page(self, key: bytes, *, drop: bool = True, base_rows=None):
        """DEMOTE one retained prefix page out of HBM: gather its K/V
        rows (``kv_page_spill``, one fixed-shape executable) and move
        them to host numpy through the counted
        ``parallel.resharding`` segment plan — every spilled byte is
        priced and booked to the ledger's ``kv_handoff`` bucket. With
        ``drop=True`` (demotion) the page leaves the registry and
        returns to the free pool; ``drop=False`` is a NON-DESTRUCTIVE
        read — the peer-tier path, where another replica copies this
        replica's warm page without disturbing it. Returns
        ``(rows, stats)``: flatten-ordered host page rows (the
        ``fill_page`` input) and ``{"bytes", "raw_bytes", "segments"}``
        — ``bytes`` is WIRE bytes: with a ``comm_compression`` KV codec
        attached the rows ship as block-scaled int8 through the plan's
        codec seam and land decoded (on the int8 grid) host-side, so a
        later re-spill of the same rows is bit-identical (quantization
        is a fixed point on its own image). ``base_rows`` (same
        flatten order, or ``None``) is the delta codec's
        version-stamped base: with ``kv_codec="int8_delta"`` only
        blocks that changed since the base version ship, so a tier
        re-demotion after a version bump pays for the novel suffix,
        not the whole page."""
        self._check_tier_supported("spill_page")
        pid = self._prefix_registry.get(key)
        if pid is None:
            raise KeyError("spill_page: key not in the prefix registry")
        if drop and pid not in self._cached_lru:
            raise RuntimeError(
                "spill_page(drop=True): page is shared by live slots — "
                "it cannot leave HBM mid-request"
            )
        if self._cache is None:
            raise RuntimeError("spill_page: the engine holds no cache")
        from learning_jax_sharding_tpu.parallel.resharding import (
            HostBuffer,
            execute_transfer,
            plan_transfer,
        )

        with self.ledger.measure("kv_handoff"):
            pid_j = jnp.int32(pid)
            with activate(self._mesh, self._rules):
                dev_rows = self._kv_page_spill_fn(self._cache, pid_j)
            # Live-cache closure (see export_kv): relowering reads the
            # engine's CURRENT cache, never a pinned stale copy.
            self._last_kv_page_spill_args = lambda: (self._cache, pid_j)
            codec = self._kv_codec
            ckey = (
                (codec.name, getattr(codec, "block", 0))
                if codec is not None else None
            )
            host = HostBuffer()
            rows, nbytes, raw_bytes, nsegs = [], 0, 0, 0
            for i, x in enumerate(dev_rows):
                base = base_rows[i] if base_rows is not None else None
                pkey = (
                    tuple(x.shape), str(x.dtype), x.sharding, "spill", ckey,
                )
                plan = self._page_plan_cache.get(pkey)
                if plan is None:
                    plan = plan_transfer(
                        x.shape, x.dtype.itemsize, x.sharding, host,
                        seq_dim=None, page_tokens=None, codec=codec,
                    )
                    self._page_plan_cache[pkey] = plan
                buf, stats = execute_transfer(plan, x, base=base)
                rows.append(buf)
                nbytes += stats["bytes"]
                raw_bytes += stats.get("raw_bytes", stats["bytes"])
                nsegs += stats["segments"]
            if drop:
                del self._cached_lru[pid]
                del self._prefix_registry[self._key_of_page.pop(pid)]
                del self._refcnt[pid]
                self._free_pages.append(pid)
                self.prefix_epoch += 1
                self._update_high_water()
            self._c_pg_spills.inc()
            self._c_pg_bytes_out.inc(nbytes)
            self._c_kv_raw_bytes.inc(raw_bytes)
            if nbytes:
                self._g_comp_ratio.set(raw_bytes / nbytes)
            self.recorder.record(
                "engine.kv_page_spill", pid=pid, bytes=nbytes,
                raw_bytes=raw_bytes, segments=nsegs, dropped=drop,
            )
        return rows, {
            "bytes": nbytes, "raw_bytes": raw_bytes, "segments": nsegs,
        }

    def fill_page(self, key: bytes, rows) -> dict:
        """PROMOTE a spilled page back into HBM: take a physical page
        (may LRU-evict a colder retained page), commit the host rows
        under this cache's page-row layout through the counted host
        plan, write them in with ``kv_page_fill``, and register ``key``
        as a reference-free retained page (LRU-newest). The next
        admission whose prompt chain reaches ``key`` maps it like any
        HBM-resident prefix page. Returns ``{"bytes", "raw_bytes",
        "segments", "pid"}`` (``bytes`` is wire bytes — the same codec
        seam as :meth:`spill_page`, and re-encoding already-quantized
        spill output is exact, so a spill → fill → spill round trip is
        bit-stable at page boundaries); raises if ``key`` is already
        resident (promotion is not idempotent — check the digest
        first)."""
        self._check_tier_supported("fill_page")
        if key in self._prefix_registry:
            raise ValueError("fill_page: key is already resident")
        if self._cache is None:
            raise RuntimeError(
                "fill_page: the engine holds no cache — ensure_cache() "
                "or serve a request first"
            )
        from learning_jax_sharding_tpu.parallel.resharding import (
            HostBuffer,
            execute_transfer,
            plan_transfer,
        )

        with self.ledger.measure("kv_handoff"):
            with self.ledger.measure("page_alloc"):
                pid = self._take_page()
            codec = self._kv_codec
            ckey = (
                (codec.name, getattr(codec, "block", 0))
                if codec is not None else None
            )
            host = HostBuffer()
            dev_rows, nbytes, raw_bytes, nsegs = [], 0, 0, 0
            for x, dst in zip(rows, self._page_row_shardings()):
                buf = np.asarray(x)
                pkey = (tuple(buf.shape), str(buf.dtype), dst, "fill", ckey)
                plan = self._page_plan_cache.get(pkey)
                if plan is None:
                    plan = plan_transfer(
                        buf.shape, buf.dtype.itemsize, host, dst,
                        seq_dim=None, page_tokens=None, codec=codec,
                    )
                    self._page_plan_cache[pkey] = plan
                out, stats = execute_transfer(plan, buf)
                dev_rows.append(out)
                nbytes += stats["bytes"]
                raw_bytes += stats.get("raw_bytes", stats["bytes"])
                nsegs += stats["segments"]
            pid_j = jnp.int32(pid)
            with activate(self._mesh, self._rules):
                self._cache = self._kv_page_fill_fn(
                    self._cache, dev_rows, pid_j
                )
            # Only the one promoted row list stays retained for
            # relowering, never a stale copy of the whole cache.
            self._last_kv_page_fill_args = lambda: (
                self._cache, dev_rows, pid_j,
            )
            self._prefix_registry[key] = pid
            self._key_of_page[pid] = key
            self._refcnt[pid] = 0
            self._cached_lru[pid] = None
            self.prefix_epoch += 1
            self._update_high_water()
            self._c_pg_fills.inc()
            self._c_pg_bytes_in.inc(nbytes)
            self._c_kv_raw_bytes.inc(raw_bytes)
            if nbytes:
                self._g_comp_ratio.set(raw_bytes / nbytes)
            self.recorder.record(
                "engine.kv_page_fill", pid=pid, bytes=nbytes,
                raw_bytes=raw_bytes, segments=nsegs,
            )
        return {
            "bytes": nbytes, "raw_bytes": raw_bytes, "segments": nsegs,
            "pid": pid,
        }

    def _retire(self, slot, now, retired):
        r = self._slot_req[slot]
        r.tokens = np.asarray(self._out[slot], np.int32)
        r.finish_t = now
        n = self._emitted[slot]
        times = self._ttimes[slot]
        gaps = [b - a for a, b in zip(times, times[1:])]
        self._itl.extend(gaps)
        for g in gaps:
            self._h_itl.observe(g)
        rec = dict(
            rid=r.rid,
            prompt_len=int(r.prompt.size),
            generated=n,
            queue_wait=r.admit_t - r.arrival_t,
            ttft=(
                r.first_token_t - r.arrival_t
                if r.first_token_t is not None else None
            ),
            e2e=now - r.arrival_t,
            tpot=(
                (now - r.first_token_t) / (n - 1) if n > 1 else None
            ),
        )
        self._completed.append(rec)
        # Histograms carry the same observations for export; the exact
        # percentiles in latency_stats() stay sample-based (pinned). All
        # of this booking is the observability tax — it lands in the
        # ledger's telemetry bucket so perf_goodput.py can pin it.
        with self.ledger.measure("telemetry"):
            self._c_finished.inc()
            self._c_tokens.inc(n)
            self._h_wait.observe(rec["queue_wait"])
            self._h_e2e.observe(rec["e2e"])
            if rec["ttft"] is not None:
                self._h_ttft.observe(rec["ttft"])
            if rec["tpot"] is not None:
                self._h_tpot.observe(rec["tpot"])
            self.tracer.async_end("request", r.rid, generated=n)
            self.recorder.record(
                "engine.retire", rid=r.rid, slot=slot, generated=n,
                ttft=rec["ttft"], e2e=rec["e2e"], version=r.version,
            )
            if self.slo is not None:
                ten = r.tenant
                self.slo.observe(
                    "queue_wait", rec["queue_wait"], tenant=ten
                )
                self.slo.observe("e2e", rec["e2e"], tenant=ten)
                if rec["ttft"] is not None:
                    self.slo.observe("ttft", rec["ttft"], tenant=ten)
                if rec["tpot"] is not None:
                    self.slo.observe("tpot", rec["tpot"], tenant=ten)
                for g in gaps:
                    self.slo.observe("itl", g, tenant=ten)
            if self.trace_sink is not None:
                self._record_trace_legs(r, now, generated=n)
                if self.trace_sink.auto_complete:
                    self.trace_sink.complete(
                        r.rid, status="ok", finish_t=now,
                    )
        self._finished[r.rid] = r
        # Version attribution (round 12): every response is traceable to
        # exactly ONE weights version — the one pinned at its (last)
        # admission. The zero-downtime swap oracle audits this log.
        self.finished_versions[r.rid] = r.version
        retired.append(r.rid)
        if r.adapter is not None and self._adapter_pool is not None:
            self._adapter_pool.release(r.adapter)
        # Open the export window (disaggregated handoff): the row's KV
        # stays intact until a later admission reuses this slot.
        self._export_ok[r.rid] = slot
        self._slot_req[slot] = None
        self._req[slot] = -1
        self._active[slot] = False
        self._aidx[slot] = 0
        if self._paged:
            self._release(slot)

    def _record_trace_legs(
        self, r, now, *, generated=0, wasted=False, status="ok",
    ):
        """Append THIS engine's spans of ``r``'s journey to the trace
        sink, from the request's own stamps. The queue leg opens at
        ``enqueue_t`` (not the fleet ``arrival_t``): a rerouted request
        keeps its original arrival for deadlines and latency honesty,
        but it only waited HERE from its re-enqueue — the requeue gap
        shows up as the trace's ``stall``, which is the truth.
        ``wasted=True`` marks compute legs thrown away by a failover
        (they sum separately in the critical path). Ingested rows emit
        only a decode leg — their queue/prefill ran on the prefill
        replica and the handoff leg is the router's to record (it alone
        saw both ends of the transfer)."""
        ts = self.trace_sink
        rep = self.trace_replica
        q0 = r.enqueue_t if r.enqueue_t is not None else r.arrival_t
        if r.admit_t is None:
            # Never admitted here: all wait, no compute to waste.
            ts.leg(r.rid, "queue", q0, now, replica=rep, status=status)
            return
        if r.ingested:
            ts.leg(
                r.rid, "decode", q0, now, replica=rep,
                generated=generated, version=r.version,
                wasted=wasted, status=status,
            )
            return
        ts.leg(r.rid, "queue", q0, r.admit_t, replica=rep)
        ft = r.first_token_t
        if ft is None:
            # Died mid-prefill (chaos kill before the first token).
            ts.leg(
                r.rid, "prefill", r.admit_t, now, replica=rep,
                version=r.version, wasted=wasted, status=status,
            )
            return
        ts.leg(
            r.rid, "prefill", r.admit_t, ft, replica=rep,
            first_token_t=ft, version=r.version, wasted=wasted,
        )
        if now > ft:
            ts.leg(
                r.rid, "decode", ft, now, replica=rep,
                generated=generated, version=r.version,
                wasted=wasted, status=status,
            )

    def _fail_request(self, r, status, error, *, now=None, tokens=None):
        """Retire ``r`` with a terminal non-ok status: surfaced through
        ``pop_finished`` as a :class:`RequestFailure` — the recovery
        policies' one exit path (deadline, quarantine, malformed,
        shutdown)."""
        now = time.perf_counter() if now is None else now
        r.status = status
        r.error = error
        r.finish_t = now
        if tokens is not None:
            r.tokens = tokens
        with self.ledger.measure("telemetry"):
            self._c_req_failed.inc()
            if status == "rerouted":
                self._c_rerouted.inc()
            self.recorder.record(
                "engine.request_failed", rid=r.rid, status=status,
                error=error,
            )
            if r.admit_t is not None:
                # async_begin was issued at first admission; close the
                # span so the trace shows the failed request's full
                # lifetime.
                self.tracer.async_end("request", r.rid, status=status)
            if self.trace_sink is not None:
                # A reroute throws this engine's partial compute away —
                # the next engine recomputes it. Mark those legs wasted
                # so the fleet critical path separates real progress
                # from failover churn.
                self._record_trace_legs(
                    r, now,
                    wasted=(status == "rerouted"), status=status,
                )
                if self.trace_sink.auto_complete and status != "rerouted":
                    self.trace_sink.complete(
                        r.rid, status=status, finish_t=now,
                    )
        if r.adapter is not None and self._adapter_pool is not None:
            self._adapter_pool.release(r.adapter)
        self._finished[r.rid] = r
        self.finished_versions[r.rid] = r.version

    def _fail_slot(self, slot, status, error, now=None):
        """Fail the request occupying ``slot`` and free the slot — the
        in-flight arm of :meth:`_fail_request` (partial output kept:
        the caller sees how far the request got)."""
        r = self._slot_req[slot]
        self._fail_request(
            r, status, error, now=now,
            tokens=np.asarray(self._out[slot], np.int32),
        )
        if self._paged:
            # Never register a failed request's pages: a deadline/poison
            # eviction can land mid-prefill, with pages partially written.
            self._release(slot, register=False)
        self._slot_req[slot] = None
        self._req[slot] = -1
        self._active[slot] = False
        self._aidx[slot] = 0
        self._pending[slot] = np.zeros((0,), np.int32)
        self._needs_reset[slot] = False
        self._reset_to[slot] = 0

    def _sweep_deadlines(self):
        """TTL eviction: fail every queued or in-flight request whose
        age exceeds its deadline (per-request ``deadline_s`` override,
        else the engine default). Skipped in O(1) when no deadline is
        configured anywhere."""
        if self._deadline_s is None and not self._any_req_deadline:
            return
        if self._deadline_s is None:
            # Engine-level TTL off: the sweep exists only for per-request
            # deadlines. Re-arm the O(1) skip once none remain live —
            # one early request with a TTL must not tax every later step
            # of the engine's lifetime.
            if not any(
                r.deadline_s is not None for r in self._queue
            ) and not any(
                r is not None and r.deadline_s is not None
                for r in self._slot_req
            ):
                self._any_req_deadline = False
                return
        with self.ledger.measure("admission"):
            now = time.perf_counter()

            def expired(r):
                dl = (
                    r.deadline_s if r.deadline_s is not None
                    else self._deadline_s
                )
                return dl is not None and now - r.arrival_t > dl

            if any(expired(r) for r in self._queue):
                keep = deque()
                for r in self._queue:
                    if expired(r):
                        self._c_deadline.inc()
                        self._fail_request(
                            r, "deadline", "deadline exceeded in queue",
                            now=now,
                        )
                    else:
                        keep.append(r)
                self._queue = keep
                self._g_queue.set(len(self._queue))
            for slot in range(self._b):
                r = self._slot_req[slot]
                if r is not None and expired(r):
                    self._c_deadline.inc()
                    self._fail_slot(
                        slot, "deadline", "deadline exceeded in flight",
                        now,
                    )

    def _on_dispatch_fault(self, e):
        """A dispatch raised a RECOVERABLE fault (injected NaN-trap /
        hang-watchdog abort). Every involved request earns a strike;
        requests at ``max_dispatch_strikes`` are FAILED as poison, the
        rest are requeued (recompute preemption — exact, see
        ``_unadmit``) and re-admitted ONE AT A TIME (probation, see
        ``_admit``) so the poison trips alone instead of striking its
        batchmates to death. The engine's device state needs no repair:
        re-admission resets every per-row counter."""
        with self.ledger.measure("recovery"):
            self._c_dispatch_faults.inc()
            self.recorder.record(
                "engine.dispatch_fault",
                error=type(e).__name__, message=str(e),
                rids=[r for r in self._req if r >= 0],
            )
            now = time.perf_counter()
            for slot in range(self._b):
                r = self._slot_req[slot]
                if r is None:
                    continue
                r.strikes += 1
                if r.strikes >= self._max_strikes:
                    self._c_quarantined.inc()
                    self.recorder.record(
                        "engine.quarantine", rid=r.rid, strikes=r.strikes,
                    )
                    self._fail_slot(slot, "poisoned", str(e), now)
                else:
                    self._unadmit(slot)

    def _consume(self, slot, tokens, now, retired):
        # Append a decode dispatch's tokens for one slot; retire at
        # EOS or budget — ONE copy of the retirement rule for both
        # engine modes.
        for t in tokens:
            self._out[slot].append(int(t))
            self._emitted[slot] += 1
            self._tok[slot] = int(t)
            self._ttimes[slot].append(now)
            if (self._eos is not None and t == self._eos) or (
                self._emitted[slot] >= self._max_new
            ):
                self._retire(slot, now, retired)
                break

    def _rid_arr(self):
        return jnp.asarray(np.maximum(self._req, 0), jnp.int32)

    # --- the scheduler -----------------------------------------------------

    def _unadmit(self, slot):
        """Backpressure/preemption: push an in-flight request back to the
        queue head and free its slot — taken when the page pool cannot
        cover its next dispatch but OTHER slots still hold pages that
        will free as they retire. The request restarts from scratch on a
        later admission (RECOMPUTE preemption): any consumed chunks and
        emitted tokens are discarded and re-derived — EXACTLY, because
        greedy decoding is deterministic and every sampling draw is
        keyed by (request id, generated position), not by schedule. So
        preemption, like every other scheduling decision, cannot change
        results (test-pinned)."""
        r = self._slot_req[slot]
        self._queue.appendleft(r)
        self.tracer.instant("request.preempted", rid=r.rid, slot=slot)
        self.recorder.record("engine.preempt", rid=r.rid, slot=slot)
        if self._paged:
            self._release(slot, register=False)
        self._slot_req[slot] = None
        self._req[slot] = -1
        self._active[slot] = False
        self._aidx[slot] = 0
        self._pending[slot] = np.zeros((0,), np.int32)
        self._needs_reset[slot] = False
        self._reset_to[slot] = 0

    def _admission_ok(self, p: np.ndarray) -> str | None:
        """Cheap admission-time re-validation: the queue is not trusted
        between ``add_request`` and admission — a frontend race (or the
        chaos harness) can corrupt a queued prompt, and a malformed
        prompt must FAIL THE REQUEST, not wedge the slot or crash the
        scheduler. Shape/dtype here; sequence budgets via THE validator
        (``_validate_prompt`` — target AND draft configs), so the two
        paths cannot drift."""
        if p.ndim != 1 or p.dtype.kind not in "iu":
            return f"malformed prompt (shape {p.shape}, dtype {p.dtype})"
        try:
            self._validate_prompt(p)
        except ValueError as e:
            return str(e)
        return None

    def _pop_admittable(self):
        """The next request to admit, honoring PROBATION: while any
        request carries dispatch strikes, suspects are re-admitted ONE
        AT A TIME into an otherwise idle engine (so a poison request
        trips its fault alone and its former batchmates are never
        struck to quarantine alongside it), and nothing else admits
        until the suspects are cleared (completed or failed)."""
        if any(
            r is not None and r.strikes > 0 for r in self._slot_req
        ):
            return None             # a suspect is live: solo probation
        si = next(
            (i for i, r in enumerate(self._queue) if r.strikes > 0), None
        )
        if si is None:
            return self._queue.popleft()
        if any(q >= 0 for q in self._req):
            return None             # wait for idle before the next suspect
        r = self._queue[si]
        del self._queue[si]
        return r

    def _admit(self):
        if self._staged_swap is not None:
            # A staged swap DRAINS the engine: no new admissions until
            # occupancy hits zero and the commit flips versions — an
            # admission now would pin the OLD version onto a request that
            # outlives it. Queued requests keep their place; the very
            # step that commits re-runs admission under the new version.
            self._g_queue.set(len(self._queue))
            return
        b = self._b
        with self.ledger.measure("admission"):
            now = time.perf_counter()
            for slot in range(b):
                if self._req[slot] < 0 and self._queue:
                    r = self._pop_admittable()
                    if r is None:
                        break
                    r.prompt = np.asarray(
                        chaos_hook("engine.admit", value=r.prompt, rid=r.rid)
                    )
                    bad = self._admission_ok(r.prompt)
                    if bad is not None:
                        self.recorder.record(
                            "engine.malformed", rid=r.rid, error=bad,
                        )
                        self._fail_request(r, "malformed", bad, now=now)
                        continue
                    # A preempted request keeps its first admission time
                    # (and counts its prefix hit once — re-admission
                    # re-maps the same pages, not new savings).
                    first_admission = r.admit_t is None
                    if first_admission:
                        r.admit_t = now
                        self.tracer.async_begin(
                            "request", r.rid,
                            prompt_len=int(r.prompt.size), slot=slot,
                        )
                    self.tracer.instant(
                        "request.admit", rid=r.rid, slot=slot
                    )
                    self.recorder.record(
                        "engine.admit", rid=r.rid, slot=slot,
                        prompt_len=int(r.prompt.size),
                        readmission=not first_admission,
                    )
                    prompt = r.prompt
                    # (Re-)pin the weights version at EVERY admission: a
                    # preempted/requeued request recomputes from scratch,
                    # so it recomputes UNDER — and is attributed to —
                    # whatever version is serving when it readmits.
                    r.version = self.weights_version
                    # The slot is being reused: any retired request whose
                    # KV row lived here is no longer exportable.
                    self._export_ok = {
                        k: v for k, v in self._export_ok.items()
                        if v != slot
                    }
                    self._slot_req[slot] = r
                    self._req[slot] = r.rid
                    self._aidx[slot] = (
                        self._adapter_pool.slot_of(r.adapter)
                        if r.adapter is not None else 0
                    )
                    self._plen[slot] = prompt.size
                    self._pending[slot] = prompt
                    self._emitted[slot] = 0
                    self._out[slot] = list(prompt)
                    self._ttimes[slot] = []
                    self._needs_reset[slot] = True
                    self._reset_to[slot] = 0
                    if self._paged and self._prefix:
                        # Longest chain of retained pages whose token
                        # prefix matches; the last prompt token always
                        # recomputes (its logits seed generation).
                        shared = []
                        for k in range(
                            1, (prompt.size - 1) // self._page_size + 1
                        ):
                            pid = self._prefix_registry.get(
                                prompt[: k * self._page_size].tobytes()
                            )
                            if pid is None:
                                break
                            shared.append(pid)
                        for j, pid in enumerate(shared):
                            self._refcnt[pid] = self._refcnt.get(pid, 0) + 1
                            self._cached_lru.pop(pid, None)
                            self._table_np[slot, j] = pid
                            self._held[slot].append(pid)
                            self._tables_dirty = True
                        self._shared_count[slot] = len(shared)
                        if shared:
                            s_len = len(shared) * self._page_size
                            self._pending[slot] = prompt[s_len:]
                            self._reset_to[slot] = s_len
                            if first_admission:
                                self._c_pfx_hits.inc()
                                self._c_pfx_pages.inc(len(shared))
                            self._update_high_water()
                        if first_admission:
                            # Predicted-vs-realized (round 15): the router
                            # records its digest-based prediction under
                            # the rid before placement; admission is the
                            # moment of truth. A shortfall means the page
                            # was evicted/spilled between scoring and
                            # admission — the request just re-prefills
                            # the missing tokens (graceful miss), and the
                            # counter makes the race visible.
                            realized = len(shared) * self._page_size
                            self.prefix_realized[r.rid] = realized
                            exp = self.expected_prefix.pop(r.rid, None)
                            if exp is not None and exp > 0:
                                self._c_pfx_expected.inc()
                                if realized < exp:
                                    self._c_tier_miss.inc()
                                    self.recorder.record(
                                        "engine.tier_miss", rid=r.rid,
                                        expected=int(exp),
                                        realized=realized,
                                    )
            self._g_queue.set(len(self._queue))

    def _refill_dispatch(self, params, d_params, retired):
        # One refill chunk for every slot with pending prompt tokens
        # (fresh or continuing); decoding rows ride along with length 0.
        # With ``decode_chain > 1`` up to that many CHUNKS are dispatched
        # back-to-back with a single host sync at the end — chunk
        # contents are host-known (the pending prompt), so nothing in a
        # later chunk depends on an earlier chunk's readback; a long
        # prompt pays one round trip per CHAIN instead of per chunk.
        b = self._b
        segs = []            # (lengths, tok_new_device) per chained chunk
        for _ in range(self.decode_chain):
            lengths = np.zeros((b,), np.int32)
            chunk = np.zeros((b, self._refill_chunk), np.int32)
            for slot in range(b):
                n = min(self._pending[slot].size, self._refill_chunk)
                if n:
                    chunk[slot, :n] = self._pending[slot][:n]
                    lengths[slot] = n
            if not lengths.any():
                break
            with self.ledger.measure("recovery"):
                # An armed chaos seam spends its injected delay (hang,
                # slow) HERE — fault time is recovery, never device.
                chaos_hook(
                    "engine.dispatch", phase="refill",
                    rids=[r for r in self._req if r >= 0],
                )
            if self._paged:
                for slot in range(b):
                    if lengths[slot]:
                        consumed = (
                            self._plen[slot] - self._pending[slot].size
                        )
                        try:
                            self._ensure(
                                slot, consumed + int(lengths[slot])
                            )
                        except RuntimeError:
                            # Backpressure instead of a wedge: if any
                            # OTHER slot is mid-flight, its retirement
                            # will free pages — requeue this request and
                            # serve the rest. Raise only when this
                            # request is alone (it can never fit).
                            if not any(
                                self._req[s] >= 0
                                for s in range(b) if s != slot
                            ):
                                raise
                            self._unadmit(slot)
                            self._c_preempt.inc()
                            lengths[slot] = 0
                            chunk[slot, :] = 0
                if not lengths.any():
                    break
                if self._cache is None:
                    # Create faithful zero caches with a NO-OP refill
                    # (every length 0 — no writes, no advances), so the
                    # real first chunk runs through the steady-state path
                    # with the block tables already installed.
                    first_args = (
                        params, d_params,
                        jnp.zeros_like(jnp.asarray(chunk)),
                        jnp.zeros((b,), jnp.int32), self._rid_arr(),
                        self.rng,
                    )
                    with self._led_device(self._first_refill_fn):
                        _, self._cache = self._first_refill_fn(*first_args)
                    self.cache_creations += 1
                    self._c_creations.inc()
                    self.recorder.record(
                        "engine.cache_create", n=self.cache_creations
                    )
                    self._last_first_refill_args = lambda: first_args
                self._cache = self._set_tables(self._cache)
            if self._cache is None:
                first_args = (
                    params, d_params, jnp.asarray(chunk),
                    jnp.asarray(lengths), self._rid_arr(), self.rng,
                )
                with self._led_device(self._first_refill_fn), annotate(
                    "engine.first_refill"
                ):
                    tok_new, self._cache = self._first_refill_fn(*first_args)
                seg_fam = "first_refill"
                self.cache_creations += 1
                self._c_creations.inc()
                self.recorder.record(
                    "engine.cache_create", n=self.cache_creations
                )
                self._last_first_refill_args = lambda: first_args
            else:
                # COPIES, not the live arrays: jnp.asarray of a numpy
                # array can be zero-copy (the jax.Array aliases the host
                # buffer), and the flags are cleared in place below while
                # the dispatch may still be executing asynchronously — an
                # aliased clear would erase the admission resets
                # mid-flight (observed as flaky stale-counter corruption
                # on CPU).
                chunk_d = jnp.asarray(chunk)
                lengths_d = jnp.asarray(lengths)
                reset_d = jnp.asarray(self._needs_reset.copy())
                reset_to_d = jnp.asarray(self._reset_to.copy())
                rid_d = self._rid_arr()
                with self._led_device(self._refill_step_fn), annotate(
                    "engine.refill_step"
                ):
                    tok_new, self._cache = self._refill_step_fn(
                        params, d_params, self._cache, chunk_d, lengths_d,
                        reset_d, reset_to_d, rid_d, self.rng,
                    )
                seg_fam = "refill_step"
                self._last_refill_args = lambda: (
                    params, d_params, self._cache, chunk_d, lengths_d,
                    reset_d, reset_to_d, rid_d, self.rng,
                )
            # The dispatch has its own copy of the admission resets, so
            # consume the flags (every flagged row had pending tokens and
            # therefore rode this chunk).
            self._needs_reset[:] = False
            self._reset_to[:] = 0
            # Advance the host-side pending views NOW (later chunks in
            # the chain read them); completions are processed after the
            # single sync, per segment, in order.
            seg_completes = []
            for slot in range(b):
                if lengths[slot]:
                    self._pending[slot] = (
                        self._pending[slot][lengths[slot]:]
                    )
                    if (
                        self._pending[slot].size == 0
                        and self._req[slot] >= 0
                    ):
                        seg_completes.append(slot)
            segs.append((tok_new, seg_completes, seg_fam))
        if not segs:
            return False
        for tok_new, seg_completes, seg_fam in segs:
            with self._led_device(family=seg_fam):
                tok_new = np.asarray(tok_new)   # each segment's own sync
            now = time.perf_counter()       # its host-visibility time
            for slot in seg_completes:
                # Prompt complete: its first token came from this
                # chunk's last valid position.
                t = int(tok_new[slot])
                self._out[slot].append(t)
                self._emitted[slot] = 1
                self._tok[slot] = t
                self._slot_req[slot].first_token_t = now
                self._ttimes[slot].append(now)
                self.tracer.instant(
                    "request.first_token", rid=self._req[slot]
                )
                if (self._eos is not None and t == self._eos) or (
                    self._max_new == 1
                ):
                    self._retire(slot, now, retired)
                else:
                    self._active[slot] = True
        return True

    def _decode_dispatch(self, params, d_params, retired):
        # Up to ``decode_chain`` decode BLOCKS dispatched back-to-back —
        # the carries (tok/active/remaining[/pos]) flow device-to-device
        # and the host syncs ONCE at the end. Rows freeze in-scan at
        # EOS/budget exactly as within one block, so chaining cannot
        # change results (test-pinned). Scheduling tradeoff, not
        # correctness: a slot retiring mid-chain idles until the chain's
        # one sync, so admission (and queued-request TTFT) coarsens by
        # up to chain-1 blocks — decode_chain is an explicit opt-in
        # (default 1). NOTE the measured first-order decode lever on the
        # tunneled chip is decode_block_steps (dispatch cost ~120 ms is
        # paid per CALL; see perf_block_ladder.py) — chaining stacks a
        # further gain and is the main lever for refill. Returns whether
        # a dispatch actually ran (idle polling must not accrue time).
        if not self._active.any():
            return False
        b = self._b
        # Degradation level 1 turns the draft-verify rounds off: the
        # SPEC engine decodes through the plain decode_block (its own
        # target apply — the same program a non-spec engine runs, so it
        # checks against the plain ``decode_step`` golden). The draft
        # cache sits idle; on re-enable its stale K/V only costs
        # acceptance rate, never correctness — the verifier decides
        # every emitted token.
        spec = self._speculative and not self._spec_disabled
        remaining = np.asarray(
            [max(0, self._max_new - e) for e in self._emitted], np.int32
        )
        # Never dispatch blocks that CANNOT emit: the host knows every
        # row's remaining budget, so the chain is capped at the blocks
        # the longest-running active row can still use — with
        # K = max_new_tokens an entire wave retires in block 1 and an
        # uncapped chain would run chain-1 fully-frozen (but fully
        # priced) no-op blocks.
        worst = int(remaining[self._active].max())
        per_block = self._block_steps * (
            (self._num_draft + 1) if spec else 1
        )
        chain = min(self.decode_chain, -(-worst // per_block))
        with self.ledger.measure("recovery"):
            # Armed chaos delay (hang/slow) books as recovery, not
            # device — the attribution the chaos tests pin.
            chaos_hook(
                "engine.dispatch", phase="decode",
                rids=[r for r in self._req if r >= 0],
            )
        if self._paged:
            # Cover every position this chain can write: chain·K new
            # tokens per row (plain), or chain·K rounds of up to
            # num_draft+1 plus the verify chunk's headroom (speculative)
            # — capped by the row's remaining budget either way.
            for slot in range(b):
                if not self._active[slot]:
                    continue
                pos_s = self._plen[slot] + self._emitted[slot] - 1
                if spec:
                    span = (
                        min(
                            int(remaining[slot]),
                            chain * self._block_steps
                            * (self._num_draft + 1),
                        )
                        + self._num_draft + 1
                    )
                else:
                    span = min(
                        int(remaining[slot]), chain * self._block_steps
                    )
                try:
                    self._ensure(slot, pos_s + span)
                except RuntimeError:
                    # Decode-time RECOMPUTE preemption (exact — see
                    # _unadmit): requeue this row unless it is the only
                    # request left holding pages (then it can never fit).
                    if not any(
                        self._req[s] >= 0 for s in range(b) if s != slot
                    ):
                        raise
                    self._unadmit(slot)
                    self._c_preempt.inc()
            if not self._active.any():
                return False
            self._cache = self._set_tables(self._cache)
            # Re-cap the chain from the SURVIVING rows: if backpressure
            # just un-admitted the longest-running row, the chain sized
            # to it would dispatch fully-frozen no-op blocks.
            worst = int(remaining[self._active].max())
            chain = min(self.decode_chain, -(-worst // per_block))
        tok_d = jnp.asarray(self._tok)
        active_d = jnp.asarray(self._active.astype(np.int32))
        remaining_d = jnp.asarray(remaining)
        rid = self._rid_arr()
        if spec:
            # Each row's current cache index: prompt + emitted - 1 (its
            # pending token is not yet in the cache).
            pos_d = jnp.asarray(
                np.asarray(
                    [
                        max(0, p + e - 1)
                        for p, e in zip(self._plen, self._emitted)
                    ],
                    np.int32,
                )
            )
            t_cache, d_cache = self._cache
            segs = []
            for _ in range(chain):
                with self._led_device(self._decode_block_spec_fn), annotate(
                    "engine.decode_block_spec"
                ):
                    (buffer, counts, acc, prop, tok_d, pos_d, active_d,
                     remaining_d, t_cache, d_cache) = (
                        self._decode_block_spec_fn(
                            params, d_params, t_cache, d_cache, tok_d,
                            active_d, pos_d, remaining_d, rid, self.rng,
                        )
                    )
                segs.append((buffer, counts, acc, prop))
            self._cache = (t_cache, d_cache)
            self._last_decode_args = lambda: (
                params, d_params, self._cache[0], self._cache[1], tok_d,
                active_d, pos_d, remaining_d, rid, self.rng,
            )
            # ONE sync for the whole chain.
            with self._led_device(family="decode_block_spec"):
                segs = [
                    tuple(np.asarray(x) for x in seg) for seg in segs
                ]
            now = time.perf_counter()
            was_active = self._active.copy()
            for buffer, counts, acc, prop in segs:
                self._c_spec_acc.inc(int(acc.sum()))
                self._c_spec_prop.inc(int(prop.sum()))
                for slot in range(b):
                    # Consume segments chronologically; a slot retired in
                    # an earlier segment (req < 0) emits nothing real in
                    # later ones — its lane froze on device.
                    if was_active[slot] and self._req[slot] >= 0:
                        self._consume(
                            slot, buffer[slot, : counts[slot]].tolist(),
                            now, retired,
                        )
        else:
            if self._speculative:
                # Degraded: advance the TARGET cache only; the idle
                # draft cache rides along untouched.
                cache, d_cache = self._cache
            else:
                cache, d_cache = self._cache, None
            segs = []
            for _ in range(chain):
                with self._led_device(self._decode_block_fn), annotate(
                    "engine.decode_block"
                ):
                    toks, active_d, remaining_d, cache = (
                        self._decode_block_fn(
                            params, cache, tok_d, active_d,
                            remaining_d, rid, self.rng,
                        )
                    )
                # Next block's pending token: each row's last emitted
                # (frozen rows repeat their token — correct carry).
                tok_d = toks[:, -1]
                segs.append(toks)
            if self._speculative:
                self._cache = (cache, d_cache)
                self._last_decode_plain_args = lambda: (
                    params, self._cache[0], tok_d, active_d, remaining_d,
                    rid, self.rng,
                )
            else:
                self._cache = cache
                self._last_decode_args = lambda: (
                    params, self._cache, tok_d, active_d, remaining_d,
                    rid, self.rng,
                )
            with self._led_device(family="decode_block"):
                segs = [np.asarray(t) for t in segs]   # ONE sync
            now = time.perf_counter()
            was_active = self._active.copy()
            for toks in segs:
                for slot in range(b):
                    if was_active[slot] and self._req[slot] >= 0:
                        self._consume(
                            slot, toks[slot].tolist(), now, retired
                        )
        return True

    def _schedule_refill(self, budget):
        """The token-budget refill schedule for ONE mixed link: FCFS over
        slots with pending prompt tokens (admission order — the oldest
        request's prompt streams first), each taking
        ``min(pending, refill_chunk, budget left)``. Returns
        ``(chunk, lengths, starved)`` — ``starved`` counts slots that held
        pending tokens but got none this link (the scheduler decision the
        flight recorder logs)."""
        b = self._b
        lengths = np.zeros((b,), np.int32)
        chunk = np.zeros((b, self._refill_chunk), np.int32)
        starved = 0
        order = sorted(
            (s for s in range(b) if self._pending[s].size),
            # Admission order, not request id: callers may pass arbitrary
            # rids to add_request. Same-pass admissions share admit_t, so
            # arrival breaks the tie; a preempted request keeps its first
            # admission time and so its place in line.
            key=lambda s: (
                self._slot_req[s].admit_t, self._slot_req[s].arrival_t
            ),
        )
        for slot in order:
            if budget <= 0:
                starved += 1
                continue
            n = min(self._pending[slot].size, self._refill_chunk, budget)
            if self._paged:
                consumed = self._plen[slot] - self._pending[slot].size
                try:
                    self._ensure(slot, consumed + n)
                except RuntimeError:
                    # Backpressure, exactly as in _refill_dispatch: requeue
                    # unless this request is the only one holding pages.
                    if not any(
                        self._req[s] >= 0
                        for s in range(b) if s != slot
                    ):
                        raise
                    self._unadmit(slot)
                    self._c_preempt.inc()
                    continue
            chunk[slot, :n] = self._pending[slot][:n]
            lengths[slot] = n
            budget -= n
        return chunk, lengths, starved

    def _mixed_dispatch(self, params, d_params, retired):
        # The FUSED scheduler iteration (``mixed=True``): up to
        # ``decode_chain`` mixed links dispatched back-to-back, each
        # advancing every decoding row by one token (speculative: one
        # draft-verify round) AND pushing budgeted refill chunks for
        # admitting/streaming rows — decode rows are funded first out of
        # ``token_budget``, refill takes the remainder (uncapped when no
        # row is decoding: with no one to protect, refill runs at the
        # split engine's full width). Carries flow device-to-device; ONE
        # host sync at the end. Cache creation still routes through the
        # refill path (the one-shot ``first_refill`` program). Returns
        # the program class that actually ran ("mixed" / "refill" /
        # "decode" — step() books wall time per class) or False when
        # nothing dispatched.
        if self._cache is None:
            if self._adapter_pool is None:
                return (
                    "refill"
                    if self._refill_dispatch(params, d_params, retired)
                    else False
                )
            # Adapter engines must never stream prompt CONTENT through
            # the base-weights refill programs: create the cache with a
            # ZERO-LENGTH first refill (no writes, no advances) and fall
            # through to the fused adapter step below, which prefills
            # every row through its own tenant's merged weights.
            first_args = (
                params, d_params,
                jnp.zeros((self._b, self._refill_chunk), jnp.int32),
                jnp.zeros((self._b,), jnp.int32), self._rid_arr(),
                self.rng,
            )
            with self._led_device(self._first_refill_fn):
                _, self._cache = self._first_refill_fn(*first_args)
            self.cache_creations += 1
            self._c_creations.inc()
            self.recorder.record(
                "engine.cache_create", n=self.cache_creations
            )
            self._last_first_refill_args = lambda: first_args
        b = self._b
        if self._speculative and self._spec_disabled:
            # Degradation level >= 1 on a speculative MIXED engine: run
            # the SPLIT programs (refill_step still prefills the draft
            # cache, so re-enabling speculation stays sound; decode runs
            # the plain decode_block via _decode_dispatch's degraded
            # path). Everything dispatched here is an already-known
            # program family — an overload incident must not trigger
            # fresh compiles of a one-off fused variant.
            if any(p.size for p in self._pending):
                return (
                    "refill"
                    if self._refill_dispatch(params, d_params, retired)
                    else False
                )
            return (
                "decode"
                if self._decode_dispatch(params, d_params, retired)
                else False
            )
        if (
            not any(p.size for p in self._pending)
            and self._adapter_pool is None
        ):
            # PURE-DECODE phase: nothing to fuse — run the K-token decode
            # block (full decode throughput; a fused link costs one
            # dispatch per token and exists to overlap refill, absent
            # here). Admission is unaffected: _admit ran before this
            # dispatch, and a queued request only waits on a block when
            # every slot is busy — in which case it could not have been
            # admitted under any granularity. (Adapter-pool engines skip
            # this: the split decode block applies BASE weights, so
            # their pure-decode phase runs fused adapter links instead.)
            return (
                "decode"
                if self._decode_dispatch(params, d_params, retired)
                else False
            )
        if (
            self._speculative and not self._active.any()
            and self._adapter_pool is None
        ):
            # PURE-REFILL phase in speculative mode: a fused link would
            # pay a full draft-verify round with every row frozen (draft
            # applies, a verify apply, two rollback broadcasts — zero
            # tokens out) on top of the refill. Outputs are
            # schedule-independent, so run the split refill path until a
            # row starts decoding. (A non-speculative refill-only link
            # costs what refill_step costs; no fallback needed there.)
            return (
                "refill"
                if self._refill_dispatch(params, d_params, retired)
                else False
            )
        per_link = (self._num_draft + 1) if self._speculative else 1
        # The fused-link count ONE host iteration covers: the multi-step
        # horizon when engaged, else the decode chain (horizon=1 IS
        # today's loop — same programs, byte-for-byte).
        horizon = int(self.horizon)
        n_links = horizon if horizon > 1 else max(1, self.decode_chain)

        def chain_cap(remaining, active):
            # Links the longest-running decoding row can still use
            # (optimistic for speculative — same convention as
            # _decode_dispatch's per-block cap).
            if not active.any():
                return 0
            return -(-int(remaining[active].max()) // per_link)

        remaining = np.asarray(
            [max(0, self._max_new - e) for e in self._emitted], np.int32
        )
        chain_dec = chain_cap(remaining, self._active)
        if self._paged and self._active.any():
            # Cover every decode position this chain can write, with the
            # decode path's recompute-preemption fallback.
            links_hint = min(n_links, max(chain_dec, 1))
            for slot in range(b):
                if not self._active[slot]:
                    continue
                pos_s = self._plen[slot] + self._emitted[slot] - 1
                span = min(int(remaining[slot]), links_hint * per_link)
                if self._speculative:
                    span += self._num_draft + 1
                try:
                    self._ensure(slot, pos_s + span)
                except RuntimeError:
                    if not any(
                        self._req[s] >= 0 for s in range(b) if s != slot
                    ):
                        raise
                    self._unadmit(slot)
                    self._c_preempt.inc()
            remaining = np.asarray(
                [max(0, self._max_new - e) for e in self._emitted],
                np.int32,
            )
            chain_dec = chain_cap(remaining, self._active)
        was_active = self._active.copy()
        n_active = int(was_active.sum())
        tok_d = jnp.asarray(self._tok)
        active_d = jnp.asarray(was_active.astype(np.int32))
        remaining_d = jnp.asarray(remaining)
        rid = self._rid_arr()
        if self._speculative:
            # Every row's CURRENT cache index: decoding rows at
            # prompt + emitted - 1, refilling rows at their consumed
            # count (the round's rollback broadcast must re-assert, never
            # rewind, a refill advance — the device adds each link's
            # chunk lengths on top of this).
            pos_d = jnp.asarray(
                np.asarray(
                    [
                        max(0, self._plen[s] + self._emitted[s] - 1)
                        if was_active[s]
                        else (
                            self._plen[s] - self._pending[s].size
                            if self._req[s] >= 0 else 0
                        )
                        for s in range(b)
                    ],
                    np.int32,
                )
            )
            t_cache, d_cache = self._cache
        with self.ledger.measure("recovery"):
            # Armed chaos delay books as recovery, never device.
            chaos_hook(
                "engine.dispatch", phase="mixed",
                rids=[r for r in self._req if r >= 0],
            )
        if self._adapter_pool is not None:
            # One fused program serves every tenant in the batch: the
            # stacked pool rides in as an argument (stable treedef →
            # stable compile) and the per-row adapter index gathers each
            # row's slice on device. _aidx is fixed for the whole chain:
            # admission ran before this dispatch and nothing re-admits
            # mid-chain.
            pool_t = self._adapter_pool.tree
            aidx_d = jnp.asarray(self._aidx)
        if horizon > 1:
            # Device-resident multi-step path: the horizon's plan is
            # staged host-side and ONE scanned program advances all of
            # it — same preamble (chaos seam, paged pre-ensure, chain
            # caps) as the link loop below, so the two paths cannot
            # drift on scheduling policy.
            return self._multi_dispatch(
                params, d_params, retired, n_links=n_links,
                per_link=per_link, chain_dec=chain_dec,
                was_active=was_active, n_active=n_active, tok_d=tok_d,
                active_d=active_d, remaining_d=remaining_d, rid=rid,
                pos_d=pos_d if self._speculative else None,
                t_cache=t_cache if self._speculative else None,
                d_cache=d_cache if self._speculative else None,
                pool_t=(
                    pool_t if self._adapter_pool is not None else None
                ),
                aidx_d=(
                    aidx_d if self._adapter_pool is not None else None
                ),
            )
        segs = []
        starved_total = 0
        refill_scheduled = 0
        for link in range(max(1, self.decode_chain)):
            # Decode rows are funded at their true per-link consumption:
            # 1 token plain, num_draft + 1 verify-chunk positions
            # speculative — otherwise a spec dispatch overruns the
            # documented per-dispatch ceiling by n_active * num_draft.
            budget = (
                max(0, self.token_budget - n_active * per_link)
                if n_active else b * self._refill_chunk
            )
            chunk, lengths, starved = self._schedule_refill(budget)
            has_decode = n_active > 0 and link < chain_dec
            if not lengths.any() and not has_decode:
                break
            starved_total += starved
            refill_scheduled += int(lengths.sum())
            if self._paged:
                self._cache = (
                    (t_cache, d_cache) if self._speculative else self._cache
                )
                self._cache = self._set_tables(self._cache)
                if self._speculative:
                    t_cache, d_cache = self._cache
            # COPIES of the admission resets (see _refill_dispatch: the
            # dispatch is async; an aliased in-place clear would corrupt
            # it). Link 0 carries every pending reset — including rows the
            # budget starved this link: the on-device counter reset is
            # idempotent and nothing advances a row before its first
            # chunk, so resetting early is safe and the flags can clear.
            chunk_d = jnp.asarray(chunk)
            lengths_d = jnp.asarray(lengths)
            reset_d = jnp.asarray(self._needs_reset.copy())
            reset_to_d = jnp.asarray(self._reset_to.copy())
            if self._speculative and self._adapter_pool is not None:
                with self._led_device(
                    self._adapter_spec_mixed_step_fn
                ), annotate("engine.adapter_spec_mixed_step"):
                    (first_tok, buffer, counts, acc, prop, tok_d, pos_d,
                     active_d, remaining_d, t_cache, d_cache) = (
                        self._adapter_spec_mixed_step_fn(
                            params, pool_t, aidx_d, d_params, t_cache,
                            d_cache, chunk_d, lengths_d, reset_d,
                            reset_to_d, tok_d, active_d, pos_d,
                            remaining_d, rid, self.rng,
                        )
                    )
                args = (
                    params, pool_t, aidx_d, d_params, t_cache, d_cache,
                    chunk_d, lengths_d, reset_d, reset_to_d, tok_d,
                    active_d, pos_d, remaining_d, rid, self.rng,
                )
                link_fam = "adapter_mixed_step"
            elif self._speculative:
                with self._led_device(
                    self._spec_mixed_step_fn
                ), annotate("engine.spec_mixed_step"):
                    (first_tok, buffer, counts, acc, prop, tok_d, pos_d,
                     active_d, remaining_d, t_cache, d_cache) = (
                        self._spec_mixed_step_fn(
                            params, d_params, t_cache, d_cache, chunk_d,
                            lengths_d, reset_d, reset_to_d, tok_d,
                            active_d, pos_d, remaining_d, rid, self.rng,
                        )
                    )
                args = (
                    params, d_params, t_cache, d_cache, chunk_d,
                    lengths_d, reset_d, reset_to_d, tok_d, active_d,
                    pos_d, remaining_d, rid, self.rng,
                )
                link_fam = "mixed_step"
            elif self._adapter_pool is not None:
                with self._led_device(
                    self._adapter_mixed_step_fn
                ), annotate("engine.adapter_mixed_step"):
                    first_tok, tok_d, active_d, remaining_d, self._cache = (
                        self._adapter_mixed_step_fn(
                            params, pool_t, aidx_d, self._cache, chunk_d,
                            lengths_d, reset_d, reset_to_d, tok_d,
                            active_d, remaining_d, rid, self.rng,
                        )
                    )
                buffer = counts = acc = prop = None
                args = (
                    params, pool_t, aidx_d, self._cache, chunk_d,
                    lengths_d, reset_d, reset_to_d, tok_d, active_d,
                    remaining_d, rid, self.rng,
                )
                link_fam = "adapter_mixed_step"
            else:
                with self._led_device(
                    self._mixed_step_fn
                ), annotate("engine.mixed_step"):
                    first_tok, tok_d, active_d, remaining_d, self._cache = (
                        self._mixed_step_fn(
                            params, self._cache, chunk_d, lengths_d,
                            reset_d, reset_to_d, tok_d, active_d,
                            remaining_d, rid, self.rng,
                        )
                    )
                buffer = counts = acc = prop = None
                args = (
                    params, self._cache, chunk_d, lengths_d, reset_d,
                    reset_to_d, tok_d, active_d, remaining_d, rid,
                    self.rng,
                )
                link_fam = "mixed_step"
            self._last_mixed_args = lambda a=args: a
            self._needs_reset[:] = False
            self._reset_to[:] = 0
            # Advance the host-side pending views NOW (later links read
            # them); completions are processed after the single sync.
            seg_completes = []
            for slot in range(b):
                if lengths[slot]:
                    self._pending[slot] = (
                        self._pending[slot][lengths[slot]:]
                    )
                    if (
                        self._pending[slot].size == 0
                        and self._req[slot] >= 0
                    ):
                        seg_completes.append(slot)
            segs.append(
                (first_tok, buffer, counts, acc, prop, seg_completes)
            )
        if not segs:
            return False
        if self._speculative:
            self._cache = (t_cache, d_cache)
        self.recorder.record(
            "engine.mixed_schedule", links=len(segs),
            decode_rows=n_active, refill_tokens=refill_scheduled,
            starved=starved_total, budget=self.token_budget,
            queue_depth=len(self._queue),
        )
        if self._adapter_pool is not None:
            self._c_adapter_n.inc(len(segs))
            occ = np.asarray([q >= 0 for q in self._req])
            self._c_adapter_rows.inc(
                int(((self._aidx > 0) & occ).sum()) * len(segs)
            )
        for first_tok, buffer, counts, acc, prop, seg_completes in segs:
            with self._led_device(family=link_fam):
                first_np = np.asarray(first_tok)   # each link's own sync
            now = time.perf_counter()
            for slot in seg_completes:
                # Prompt complete: its first token came from this link's
                # refill pick (same rule as _refill_dispatch).
                t = int(first_np[slot])
                self._out[slot].append(t)
                self._emitted[slot] = 1
                self._tok[slot] = t
                self._slot_req[slot].first_token_t = now
                self._ttimes[slot].append(now)
                self.tracer.instant(
                    "request.first_token", rid=self._req[slot]
                )
                if (self._eos is not None and t == self._eos) or (
                    self._max_new == 1
                ):
                    self._retire(slot, now, retired)
                else:
                    self._active[slot] = True
            if self._speculative:
                with self._led_device(family=link_fam):
                    counts_np = np.asarray(counts)
                    buffer_np = np.asarray(buffer)
                    acc_np = np.asarray(acc)
                    prop_np = np.asarray(prop)
                self._c_spec_acc.inc(int(acc_np.sum()))
                self._c_spec_prop.inc(int(prop_np.sum()))
            for slot in range(b):
                # Decode consumption: rows decoding at CHAIN START that
                # are still live (a row retired while processing an
                # earlier link froze on device — its later-link lanes
                # carry no real tokens).
                if was_active[slot] and self._req[slot] >= 0:
                    if self._speculative:
                        toks = buffer_np[slot, : counts_np[slot]].tolist()
                    else:
                        toks = [int(first_np[slot])]
                    self._consume(slot, toks, now, retired)
        return "mixed"

    def _plan_horizon_links(
        self, n_links, n_active, per_link, chain_dec, *, allow_preempt,
    ):
        """The HOST half of the multi-step scheduler: the per-link refill
        plan for up to ``n_links`` fused links — ``_schedule_refill``'s
        policy (FCFS by admission order, decode funded first out of
        ``token_budget``) applied over a VIRTUAL pending advance: reads
        ``self._pending`` through per-slot offsets and never consumes it;
        the caller commits the advance when (and only when) the plan
        dispatches. Returns ``(links, offs)`` where each link is
        ``(chunk, lengths, starved, completes)``, or ``None`` when
        ``allow_preempt=False`` (the in-flight planner) and the page pool
        cannot cover the plan — preemption is a BOUNDARY decision, so
        speculative staging aborts instead of un-admitting anyone."""
        b = self._b
        with self.ledger.measure("sched"):
            offs = [0] * b
            links = []
            for link in range(n_links):
                budget = (
                    max(0, self.token_budget - n_active * per_link)
                    if n_active else b * self._refill_chunk
                )
                lengths = np.zeros((b,), np.int32)
                chunk = np.zeros((b, self._refill_chunk), np.int32)
                starved = 0
                completes = []
                order = sorted(
                    (
                        s for s in range(b)
                        if self._pending[s].size - offs[s] > 0
                    ),
                    key=lambda s: (
                        self._slot_req[s].admit_t,
                        self._slot_req[s].arrival_t,
                    ),
                )
                for slot in order:
                    if budget <= 0:
                        starved += 1
                        continue
                    n = min(
                        self._pending[slot].size - offs[slot],
                        self._refill_chunk, budget,
                    )
                    if self._paged:
                        consumed = (
                            self._plen[slot] - self._pending[slot].size
                            + offs[slot]
                        )
                        try:
                            self._ensure(slot, consumed + n)
                        except RuntimeError:
                            if not allow_preempt:
                                return None
                            # Backpressure, exactly as _schedule_refill:
                            # requeue unless this request is the only one
                            # holding pages. Scrub the un-admitted slot
                            # from the earlier planned links — nothing
                            # dispatched yet, so the plan must not
                            # stream a requeued request's chunks.
                            if not any(
                                self._req[s] >= 0
                                for s in range(b) if s != slot
                            ):
                                raise
                            self._unadmit(slot)
                            self._c_preempt.inc()
                            offs[slot] = 0
                            for ch2, ln2, _s2, comp2 in links:
                                ln2[slot] = 0
                                ch2[slot, :] = 0
                                if slot in comp2:
                                    comp2.remove(slot)
                            continue
                    chunk[slot, :n] = (
                        self._pending[slot][offs[slot]: offs[slot] + n]
                    )
                    lengths[slot] = n
                    offs[slot] += n
                    budget -= n
                    if (
                        offs[slot] == self._pending[slot].size
                        and self._req[slot] >= 0
                    ):
                        completes.append(slot)
                has_decode = n_active > 0 and link < chain_dec
                if not lengths.any() and not has_decode:
                    break
                links.append((chunk, lengths, starved, completes))
            return links, offs

    def _boundary_fingerprint(self, n_links, n_active, per_link, chain_dec):
        # Everything _plan_horizon_links reads: the slot occupancy, the
        # pending sizes (contents are immutable between admissions, so
        # sizes + request ids pin them), and the budget/cap inputs.
        return (
            tuple(self._req),
            tuple(int(p.size) for p in self._pending),
            int(n_active), int(chain_dec), int(n_links), int(per_link),
            int(self.token_budget),
        )

    def _take_staged_plan(self, n_links, n_active, per_link, chain_dec):
        """Consume the async planner's staged plan iff the boundary state
        matches its prediction exactly — an EOS retirement, an admission,
        a deadline eviction, a preemption, or a runtime knob change all
        miss the fingerprint and fall back to live planning, so the
        staged plan can only move host work off the boundary, never
        change what dispatches."""
        staged, self._staged_plan = self._staged_plan, None
        if staged is None:
            return None
        fp, plan = staged
        if fp != self._boundary_fingerprint(
            n_links, n_active, per_link, chain_dec
        ):
            return None
        self._c_plan_reused.inc()
        return plan

    def _multi_dispatch(
        self, params, d_params, retired, *, n_links, per_link, chain_dec,
        was_active, n_active, tok_d, active_d, remaining_d, rid,
        pos_d=None, t_cache=None, d_cache=None, pool_t=None, aidx_d=None,
    ):
        # The DEVICE-RESIDENT steady-state loop (horizon > 1): plan the
        # whole horizon's refill schedule host-side, dispatch ONE scanned
        # ``multi_step`` program covering up to ``n_links`` fused
        # iterations, overlap the NEXT horizon's planning with the
        # in-flight device work (``_plan_next_horizon``), then sync ONCE
        # and process every link's completions/consumption exactly as the
        # per-link loop does. Reached from _mixed_dispatch AFTER its
        # fallthroughs and preamble, so cache creation, degradation,
        # pure-decode/pure-refill phases, the chaos seam, and the paged
        # decode pre-ensure behave identically at every horizon.
        b = self._b
        plan = self._take_staged_plan(n_links, n_active, per_link, chain_dec)
        reused = plan is not None
        if plan is None:
            plan = self._plan_horizon_links(
                n_links, n_active, per_link, chain_dec, allow_preempt=True,
            )
        links, offs = plan
        if not links:
            return False
        n_live = len(links)
        # Commit the virtual pending advance NOW: the plan is final and
        # the dispatch below is async — completions are processed after
        # the one sync, from the per-link ``completes`` the plan carries.
        for slot in range(b):
            if offs[slot]:
                self._pending[slot] = self._pending[slot][offs[slot]:]
        starved_total = sum(link[2] for link in links)
        refill_scheduled = sum(int(link[1].sum()) for link in links)
        # Stack the plan into fixed-shape (N, B, ...) scan inputs — ONE
        # executable per (horizon, program family); trailing padded
        # steps ride the scan's cond skip. Link 0 carries every pending
        # admission reset (idempotent on device, same as the link loop).
        chunks = np.zeros((n_links, b, self._refill_chunk), np.int32)
        lens = np.zeros((n_links, b), np.int32)
        resets = np.zeros((n_links, b), bool)
        reset_tos = np.zeros((n_links, b), np.int32)
        for i, (chunk, lengths, _starved, _completes) in enumerate(links):
            chunks[i] = chunk
            lens[i] = lengths
        resets[0] = self._needs_reset
        reset_tos[0] = self._reset_to
        if self._paged:
            # All page allocation for the horizon happened in the plan
            # (refill) and the preamble's pre-ensure (decode): push the
            # final tables once for the whole horizon.
            self._cache = (
                (t_cache, d_cache) if self._speculative else self._cache
            )
            self._cache = self._set_tables(self._cache)
            if self._speculative:
                t_cache, d_cache = self._cache
        live = np.zeros((n_links,), np.int32)
        live[:n_live] = 1
        chunks_d = jnp.asarray(chunks)
        lens_d = jnp.asarray(lens)
        resets_d = jnp.asarray(resets)
        reset_tos_d = jnp.asarray(reset_tos)
        live_d = jnp.asarray(live)
        if self._speculative and self._adapter_pool is not None:
            with self._led_device(
                self._adapter_spec_multi_step_fn
            ), annotate("engine.adapter_spec_multi_step"):
                (first_toks, buffers, counts, accs, props, tok_d, pos_d,
                 active_d, remaining_d, t_cache, d_cache) = (
                    self._adapter_spec_multi_step_fn(
                        params, pool_t, aidx_d, d_params, t_cache,
                        d_cache, chunks_d, lens_d, resets_d, reset_tos_d,
                        live_d, tok_d, active_d, pos_d, remaining_d, rid,
                        self.rng,
                    )
                )
            args = (
                params, pool_t, aidx_d, d_params, t_cache, d_cache,
                chunks_d, lens_d, resets_d, reset_tos_d, live_d, tok_d,
                active_d, pos_d, remaining_d, rid, self.rng,
            )
            fused_fam = "adapter_multi_step"
        elif self._speculative:
            with self._led_device(
                self._spec_multi_step_fn
            ), annotate("engine.spec_multi_step"):
                (first_toks, buffers, counts, accs, props, tok_d, pos_d,
                 active_d, remaining_d, t_cache, d_cache) = (
                    self._spec_multi_step_fn(
                        params, d_params, t_cache, d_cache, chunks_d,
                        lens_d, resets_d, reset_tos_d, live_d, tok_d,
                        active_d, pos_d, remaining_d, rid, self.rng,
                    )
                )
            args = (
                params, d_params, t_cache, d_cache, chunks_d, lens_d,
                resets_d, reset_tos_d, live_d, tok_d, active_d, pos_d,
                remaining_d, rid, self.rng,
            )
            fused_fam = "multi_step"
        elif self._adapter_pool is not None:
            with self._led_device(
                self._adapter_multi_step_fn
            ), annotate("engine.adapter_multi_step"):
                first_toks, tok_d, active_d, remaining_d, self._cache = (
                    self._adapter_multi_step_fn(
                        params, pool_t, aidx_d, self._cache, chunks_d,
                        lens_d, resets_d, reset_tos_d, live_d, tok_d,
                        active_d, remaining_d, rid, self.rng,
                    )
                )
            buffers = counts = accs = props = None
            args = (
                params, pool_t, aidx_d, self._cache, chunks_d, lens_d,
                resets_d, reset_tos_d, live_d, tok_d, active_d,
                remaining_d, rid, self.rng,
            )
            fused_fam = "adapter_multi_step"
        else:
            with self._led_device(
                self._multi_step_fn
            ), annotate("engine.multi_step"):
                first_toks, tok_d, active_d, remaining_d, self._cache = (
                    self._multi_step_fn(
                        params, self._cache, chunks_d, lens_d, resets_d,
                        reset_tos_d, live_d, tok_d, active_d,
                        remaining_d, rid, self.rng,
                    )
                )
            buffers = counts = accs = props = None
            args = (
                params, self._cache, chunks_d, lens_d, resets_d,
                reset_tos_d, live_d, tok_d, active_d, remaining_d, rid,
                self.rng,
            )
            fused_fam = "multi_step"
        self._last_multi_args = lambda a=args: a
        if self._speculative:
            self._cache = (t_cache, d_cache)
        self._needs_reset[:] = False
        self._reset_to[:] = 0
        self.recorder.record(
            "engine.mixed_schedule", links=n_live,
            decode_rows=n_active, refill_tokens=refill_scheduled,
            starved=starved_total, budget=self.token_budget,
            queue_depth=len(self._queue), horizon=n_links,
            plan_reused=reused,
        )
        self._c_multi_n.inc()
        self._c_multi_links.inc(n_live)
        if self._adapter_pool is not None:
            self._c_adapter_n.inc(n_live)
            self._c_adapter_rows.inc(
                sum(
                    1 for s in range(self._b)
                    if self._req[s] >= 0 and self._aidx[s] > 0
                ) * n_live
            )
        # THE async-planner window: the fused program is in flight and
        # nothing below needs its results yet — stage the next horizon.
        self._plan_next_horizon(n_links, per_link, chain_dec, links)
        # ONE blocking readback for the whole horizon (the host's single
        # touch per N iterations — books as in-flight device time).
        with self._led_device(family=fused_fam):
            toks_np = np.asarray(first_toks)
            if self._speculative:
                counts_np = np.asarray(counts)
                buffers_np = np.asarray(buffers)
                acc_np = np.asarray(accs)
                props_np = np.asarray(props)
        if self._speculative:
            self._c_spec_acc.inc(int(acc_np[:n_live].sum()))
            self._c_spec_prop.inc(int(props_np[:n_live].sum()))
        now = time.perf_counter()
        for i in range(n_live):
            first_np = toks_np[i]
            for slot in links[i][3]:
                # Prompt complete at link i: its first token came from
                # that link's refill pick (same rule as the link loop).
                t = int(first_np[slot])
                self._out[slot].append(t)
                self._emitted[slot] = 1
                self._tok[slot] = t
                self._slot_req[slot].first_token_t = now
                self._ttimes[slot].append(now)
                self.tracer.instant(
                    "request.first_token", rid=self._req[slot]
                )
                if (self._eos is not None and t == self._eos) or (
                    self._max_new == 1
                ):
                    self._retire(slot, now, retired)
                else:
                    self._active[slot] = True
            for slot in range(b):
                # Decode consumption: rows decoding at HORIZON START
                # that are still live (a row that retired at an earlier
                # link froze on device — its later lanes carry no real
                # tokens). Same rule as the link loop's per-seg pass.
                if was_active[slot] and self._req[slot] >= 0:
                    if self._speculative:
                        toks = (
                            buffers_np[i, slot, : counts_np[i, slot]]
                            .tolist()
                        )
                    else:
                        toks = [int(first_np[slot])]
                    self._consume(slot, toks, now, retired)
        return "mixed"

    def _plan_next_horizon(self, n_links, per_link, chain_dec, links):
        """The ASYNC PLANNER: runs while the fused multi-step program is
        in flight (between its dispatch and the one blocking sync) and
        stages the NEXT horizon's refill plan — including its page-run
        reservations — against a PREDICTED boundary state. Reads only
        host state the in-flight program never writes (pending prompt
        views, the host page allocator) and performs NO device readback:
        a planner sync would re-serialize the host onto the device clock
        (lint-pinned, ``host-sync-in-hot-loop``). The staged plan
        carries a fingerprint of the predicted state; the next dispatch
        consumes it only on an exact match (``_take_staged_plan``), so a
        wrong prediction costs a re-plan at the boundary, never a wrong
        dispatch. Prediction is conservative: every active row advances
        its MINIMUM (one token/round per decode link) and nobody emits
        EOS — any faster drain or retirement misses the fingerprint."""
        self._staged_plan = None
        b = self._b
        with self.ledger.measure("sched"):
            n_dec = min(len(links), max(0, chain_dec))
            rem = np.asarray(
                [max(0, self._max_new - e) for e in self._emitted],
                np.int32,
            )
            act = self._active.copy()      # horizon-start active rows
            surv = act & (rem > n_dec)
            rem_pred = rem.copy()
            rem_pred[act] = np.maximum(rem_pred[act] - n_dec, 0)
            req_pred = list(self._req)
            for s in range(b):
                if act[s] and not surv[s]:
                    req_pred[s] = -1
            for _c, _l, _st, comp in links:
                for s in comp:
                    # A prompt completing this horizon becomes an active
                    # decode row at the boundary (unless it retires at
                    # its first token — max_new == 1 here; EOS misses
                    # the fingerprint).
                    if self._max_new > 1:
                        surv[s] = True
                        rem_pred[s] = self._max_new - 1
                    else:
                        req_pred[s] = -1
            n_active_pred = int(surv.sum())
            chain_pred = (
                -(-int(rem_pred[surv].max()) // per_link)
                if surv.any() else 0
            )
            plan = self._plan_horizon_links(
                n_links, n_active_pred, per_link, chain_pred,
                allow_preempt=False,
            )
            if plan is None or not plan[0]:
                return
            fp = (
                tuple(req_pred),
                tuple(int(p.size) for p in self._pending),
                n_active_pred, chain_pred, int(n_links), int(per_link),
                int(self.token_budget),
            )
            self._staged_plan = (fp, plan)
            self._c_plan_staged.inc()
            self.recorder.record(
                "engine.plan_staged", links=len(plan[0]),
                predicted_active=n_active_pred,
            )

    @property
    def comm_compression_active(self) -> bool:
        """True while the quantized serving collectives are compiled in
        (False when never enabled, or after a drift-budget trip)."""
        return self._comp is not None and self._comp.active

    def _comp_maintain(self, params):
        """Drift governor for the compressed serving collectives: every
        ``drift_check_every``-th dispatched step with active decode rows,
        run one greedy decode step under BOTH applies (compressed and
        plain oracle) on the live cache and count diverging rows. The
        drift rate over the budget feeds a dedicated one-level
        :class:`~learning_jax_sharding_tpu.robustness.policies.
        DegradationLadder`; a trip disables compression and clears every
        apply-family executable cache, so the NEXT dispatch retraces to
        the plain — bit-identical — contraction. Probe caches are
        discarded; the served stream never observes the probe."""
        comp = self._comp
        if (
            comp is None or not comp.active
            or self._comp_probe_fn is None or self._comp_ladder is None
            or self._cache is None or not self._active.any()
        ):
            return
        self._comp_n += 1
        if self._comp_n % comp.drift_check_every:
            return
        # Observability tax, like _retire's booking: the probe is an
        # extra (cached) program dispatch, not serving work.
        with self.ledger.measure("telemetry"):
            cache = self._cache[0] if self._speculative else self._cache
            tok = jnp.asarray(self._tok, jnp.int32)
            act = jnp.asarray(self._active.astype(np.int32))
            with activate(self._mesh, self._rules):
                n_live, n_diff = self._comp_probe_fn(
                    params, cache, tok, act
                )
            n_live, n_diff = int(n_live), int(n_diff)
            self._c_comp_probes.inc()
            self._c_comp_disagree.inc(n_diff)
            frac = (n_diff / n_live) if n_live else 0.0
            # drift_budget <= 0 is the deterministic test hook: every
            # probe reads as breached, so the first probe trips.
            burn = (
                frac / comp.drift_budget if comp.drift_budget > 0
                else float("inf")
            )
            self.recorder.record(
                "engine.comp_drift_probe", active=n_live,
                disagreements=n_diff, drift=frac,
            )
            if self._comp_ladder.update(burn) >= 1:
                self._trip_compression(frac)

    def _trip_compression(self, frac: float):
        comp = self._comp
        if comp is None or not comp.enabled:
            return
        comp.enabled = False
        cleared = 0
        for attr, _ in self._FN_FAMILY_ATTRS:
            if attr.startswith("_kv_"):
                continue  # handoff/page programs never embed the apply
            fn = getattr(self, attr, None)
            if fn is not None and hasattr(fn, "clear_cache"):
                fn.clear_cache()
                cleared += 1
        if self._comp_probe_fn is not None and hasattr(
            self._comp_probe_fn, "clear_cache"
        ):
            self._comp_probe_fn.clear_cache()
        self._c_comp_trips.inc()
        self._g_comp_on.set(0)
        self.recorder.record(
            "engine.comp_drift_trip", drift=frac,
            budget=comp.drift_budget, programs_cleared=cleared,
        )

    @property
    def degradation_level(self) -> int:
        """Current graceful-degradation level (0 when no ladder is
        attached): 0 normal, 1 speculation off, 2 reduced
        ``token_budget``, 3 shedding new admits."""
        return self._ladder.level if self._ladder is not None else 0

    def _apply_degradation(self):
        """Feed the SLO burn rate into the attached ladder and apply a
        level change to the engine's runtime knobs. The levers are the
        SAME public knobs an operator can turn (``token_budget``), so
        de-escalation restores the value captured when the ladder took
        it over, not a constructor constant."""
        if self._ladder is None or self.slo is None:
            return
        burn = max(
            (self.slo.burn_rate(t.name) for t in self.slo.targets),
            default=0.0,
        )
        prev = self._ladder.level
        level = self._ladder.update(burn)
        if level == prev:
            return
        if self._speculative:
            self._spec_disabled = level >= 1
        if self._mixed:
            if level >= 2 and self._base_budget is None:
                self._base_budget = self.token_budget
                self.token_budget = max(self._b, self.token_budget // 2)
            elif level < 2 and self._base_budget is not None:
                self.token_budget = self._base_budget
                self._base_budget = None
        self._shed_all = level >= 3
        self._g_degraded.set(level)
        self.recorder.record(
            "engine.degrade", level=level, name=self._ladder.name,
            burn_rate=burn, spec_disabled=self._spec_disabled,
            token_budget=self.token_budget, shedding=self._shed_all,
        )

    def step(self, params=None, draft_params=None) -> list[int]:
        """ONE scheduler iteration: admit queued requests into idle
        slots, then run exactly one dispatch — a refill chunk if any slot
        has pending prompt tokens, else a decode block if any row is
        active, else nothing. With ``mixed=True`` the one dispatch is the
        FUSED program instead: every decoding row advances (one token per
        link, or one draft-verify round) AND pending prompts push
        budgeted refill chunks, so decode never stalls behind refill and
        admission lands at every dispatch. Returns the ids of requests
        that finished during this step (their outputs await
        ``pop_finished()``).

        A staged ``swap_weights`` commits HERE, at the top of the step,
        before this step's admissions — so the backlog re-admitted in
        the committing step is pinned to (and served by) the NEW
        version. Once a swap has committed, the engine owns its weights:
        the installed tree overrides whatever ``params`` the caller
        still passes (a driver mid-rollout keeps handing in its stale
        copy), and ``step()`` may be called with no params at all."""
        # GOODPUT LEDGER: step() is the top-level frame — the whole
        # iteration is COVERED wall, bucketed "sched" by default, and
        # every specialized region inside (dispatch → device/compile,
        # admission, page_alloc, kv_handoff, swap, recovery, telemetry)
        # claims its own exclusive slice via nested frames. Time between
        # step() calls is nobody's and derives as "idle". That is the
        # whole reconciliation argument: Σ buckets == wall, gated.
        with self.ledger.measure("sched"):
            if self._staged_swap is not None:
                self._try_commit_swap()
            if self._installed is not None:
                params, draft_params = self._installed
            elif params is None:
                raise TypeError(
                    "step() without params: no swapped-in weights "
                    "installed — pass params, or swap_weights() first"
                )
            self._check_draft_args(draft_params)
            params, d_params = self._cast_params(params, draft_params)
            retired: list[int] = []
            with activate(self._mesh, self._rules):
                # TTL eviction before admission: an expired queued request
                # must not take a slot, and an expired in-flight one frees
                # its slot for this step's admission.
                self._sweep_deadlines()
                self._admit()
                # Decode-stall accounting: a dispatch "stalls decode" when
                # rows were actively decoding but the dispatch advanced
                # none of them (the split engine's refill). The SLO feed
                # sees a 0/1 stall indicator per dispatch-with-active-
                # rows, so a ``decode_stall_share`` target reads as the
                # fraction of such dispatches that parked decode behind
                # refill.
                had_active = bool(self._active.any())
                t0 = time.perf_counter()
                try:
                    if self._mixed:
                        # Wall time accrues to the program class that
                        # actually ran: _mixed_dispatch's fallthroughs
                        # (cache creation and speculative pure-refill →
                        # "refill", pure-decode block → "decode") must
                        # land in refill_s/decode_s, not mixed_s, or
                        # refill_frac understates refill serialization. A
                        # "refill" here CAN hold active decode rows in
                        # exactly one regime — the degradation ladder's
                        # split fallback on a speculative engine — and
                        # then it stalls decode like the split engine's
                        # refill does, so it books stall time and the SLO
                        # stream sees it: the ladder is driven by that
                        # monitor, and a degraded engine must not blind
                        # the very telemetry that degraded it.
                        kind = self._mixed_dispatch(params, d_params, retired)
                        if kind:
                            dt = time.perf_counter() - t0
                            with self.ledger.measure("telemetry"):
                                if kind == "refill":
                                    self._c_refill_s.inc(dt)
                                    self._c_refill_n.inc()
                                    if had_active:
                                        self._c_stall_s.inc(dt)
                                        if self.slo is not None:
                                            self.slo.observe(
                                                "decode_stall_share", 1.0
                                            )
                                    self.tracer.complete(
                                        "engine.refill", t0, dt,
                                        retired=len(retired),
                                    )
                                elif kind == "decode":
                                    self._c_decode_s.inc(dt)
                                    self._c_decode_n.inc()
                                    self.tracer.complete(
                                        "engine.decode", t0, dt,
                                        retired=len(retired),
                                    )
                                    if had_active and self.slo is not None:
                                        self.slo.observe(
                                            "decode_stall_share", 0.0
                                        )
                                else:
                                    self._c_mixed_s.inc(dt)
                                    self._c_mixed_n.inc()
                                    self.tracer.complete(
                                        "engine.mixed", t0, dt,
                                        retired=len(retired),
                                    )
                                    if had_active and self.slo is not None:
                                        self.slo.observe(
                                            "decode_stall_share", 0.0
                                        )
                    elif self._refill_dispatch(params, d_params, retired):
                        dt = time.perf_counter() - t0
                        with self.ledger.measure("telemetry"):
                            self._c_refill_s.inc(dt)
                            self._c_refill_n.inc()
                            if had_active:
                                self._c_stall_s.inc(dt)
                                if self.slo is not None:
                                    self.slo.observe(
                                        "decode_stall_share", 1.0
                                    )
                            self.tracer.complete(
                                "engine.refill", t0, dt,
                                retired=len(retired),
                            )
                    elif self._decode_dispatch(params, d_params, retired):
                        # Only DISPATCHED time accrues: an idle poll
                        # (streaming drivers spin step() between
                        # arrivals) must not drown the refill/decode
                        # split.
                        dt = time.perf_counter() - t0
                        with self.ledger.measure("telemetry"):
                            self._c_decode_s.inc(dt)
                            self._c_decode_n.inc()
                            if had_active and self.slo is not None:
                                self.slo.observe("decode_stall_share", 0.0)
                            self.tracer.complete(
                                "engine.decode", t0, dt,
                                retired=len(retired),
                            )
                except _RECOVERABLE_DISPATCH as e:
                    # Poison-request quarantine: strike every involved
                    # request, fail the repeat offenders, requeue the rest
                    # for probationary (solo) recompute — see
                    # _on_dispatch_fault. Infrastructure errors propagate.
                    self._on_dispatch_fault(e)
                self._apply_degradation()
                self._comp_maintain(params)
            self._g_active.set(int(self._active.sum()))
            self._g_queue.set(len(self._queue))
        return retired

    # --- stats -------------------------------------------------------------

    def latency_stats(self) -> dict | None:
        """Latency percentiles over the requests completed in the current
        stats window (see class docstring for the field meanings)."""
        comp = self._completed
        if not comp:
            return None

        def pcts(values, name):
            a = np.asarray([v for v in values if v is not None], np.float64)
            if not a.size:
                return {}
            return {
                f"{name}_p50": float(np.percentile(a, 50)),
                f"{name}_p99": float(np.percentile(a, 99)),
            }

        out = {"requests": len(comp)}
        out.update(pcts([c["queue_wait"] for c in comp], "queue_wait"))
        out.update(pcts([c["ttft"] for c in comp], "ttft"))
        out.update(pcts([c["tpot"] for c in comp], "tpot"))
        out.update(pcts(self._itl, "itl"))
        out.update(pcts([c["e2e"] for c in comp], "e2e"))
        refill_s = self._win_delta(self._c_refill_s)
        decode_s = self._win_delta(self._c_decode_s)
        mixed_s = self._win_delta(self._c_mixed_s)
        stall_s = self._win_delta(self._c_stall_s)
        busy = refill_s + decode_s + mixed_s
        out.update(
            refill_s=refill_s, decode_s=decode_s, mixed_s=mixed_s,
            refill_frac=(refill_s / busy) if busy else None,
            # Decode-stall share: the fraction of dispatched engine time
            # that parked decoding rows behind another slot's refill —
            # the number the mixed engine exists to drive to ~0.
            decode_stall_s=stall_s,
            decode_stall_share=(stall_s / busy) if busy else None,
        )
        # Multi-step scheduler (round 16): engine iterations fused per
        # host dispatch this window. 1.0 means the host round-tripped
        # every token (horizon=1); the gate in scripts/bench_compare.py
        # tracks it direction-aware (up = fewer host touches per token).
        multi_n = self._win_delta(self._c_multi_n)
        if multi_n:
            out.update(
                multi_dispatches=int(multi_n),
                steps_per_dispatch=(
                    self._win_delta(self._c_multi_links) / multi_n
                ),
                plan_reuse_rate=(
                    self._win_delta(self._c_plan_reused)
                    / max(1.0, self._win_delta(self._c_plan_staged))
                ),
            )
        # Recovery-policy telemetry (round 10), window-derived like the
        # rest: shed_rate is the fraction of ARRIVALS admission control
        # rejected; deadline_miss_rate the fraction of RETIREMENTS that
        # were TTL evictions — both gated direction-aware by
        # scripts/bench_compare.py so robustness hooks can't silently
        # regress the serving trajectory.
        shed = self._win_delta(self._c_shed)
        offered = self._win_delta(self._c_requests) + shed
        done = (
            self._win_delta(self._c_finished)
            + self._win_delta(self._c_req_failed)
        )
        dl = self._win_delta(self._c_deadline)
        out.update(
            shed_rate=(shed / offered) if offered else 0.0,
            deadline_miss_rate=(dl / done) if done else 0.0,
            failed=int(self._win_delta(self._c_req_failed)),
            # Failover visibility (round 11): requests drained to another
            # replica are counted apart from true failures, so a router
            # kill shows up as rerouted work, not as fresh admissions.
            rerouted=int(self._win_delta(self._c_rerouted)),
        )
        if self._paged and self._prefix:
            # KV economy (round 15): the fraction of this window's
            # admissions that reused retained prefix pages, and the
            # fraction of router-predicted hits that admission could not
            # realize (evicted/spilled mid-route — the tier race).
            hits = self._win_delta(self._c_pfx_hits)
            admitted = self._win_delta(self._c_requests)
            exp = self._win_delta(self._c_pfx_expected)
            miss = self._win_delta(self._c_tier_miss)
            out.update(
                prefix_hit_rate=(hits / admitted) if admitted else 0.0,
                tier_miss_rate=(miss / exp) if exp else 0.0,
            )
        return out

    def _snapshot_stats(self):
        # Mode stats keep the pre-persistence contract exactly (None when
        # no mode is on — test-pinned); the VALUES are window deltas over
        # the cumulative registry counters, so last_stats is re-derived
        # from the same metrics a Prometheus scrape would see.
        stats = {}
        if self._paged:
            stats.update(
                page_high_water=int(self._g_pages.high_water),
                pages_total=self._paged_pages - 1,
                page_size=self._page_size,
                preemptions=int(self._win_delta(self._c_preempt)),
            )
            if self._prefix:
                stats.update(
                    prefix_hits=int(self._win_delta(self._c_pfx_hits)),
                    prefix_pages_reused=int(
                        self._win_delta(self._c_pfx_pages)
                    ),
                    prefix_pages_retained=len(self._cached_lru),
                )
        if self._speculative:
            acc = self._win_delta(self._c_spec_acc)
            prop = self._win_delta(self._c_spec_prop)
            stats.update(
                spec_accepted=int(acc),
                spec_proposed=int(prop),
                spec_accept_rate=(acc / prop) if prop else None,
            )
        self.last_stats = stats or None
        self.last_latency = self.latency_stats()

    def compile_counts(self) -> dict[str, int | None]:
        """Executable-cache size per compiled engine program — each is
        that program's lifetime compile count (one executable per
        distinct shape/static combination), the "did serving recompile
        mid-flight?" probe. The steady-state engine holds these at 1."""
        fns = {
            "first_refill": self._first_refill_fn,
            "refill_step": self._refill_step_fn,
        }
        if self._speculative:
            fns["decode_block_spec"] = self._decode_block_spec_fn
            if self._last_decode_plain_args is not None:
                # The degradation ladder's plain decode path has
                # dispatched: its executable cache is a live program too.
                fns["decode_block"] = self._decode_block_fn
        else:
            fns["decode_block"] = self._decode_block_fn
        if self._mixed and self._adapter_pool is not None:
            fns["adapter_mixed_step"] = (
                self._adapter_spec_mixed_step_fn if self._speculative
                else self._adapter_mixed_step_fn
            )
        elif self._mixed:
            fns["mixed_step"] = (
                self._spec_mixed_step_fn if self._speculative
                else self._mixed_step_fn
            )
        if self._mixed and self._last_multi_args is not None:
            # The fused horizon program (horizon > 1): ONE additional
            # steady-state executable per engaged program family — held
            # at 1 per (horizon, family) by the same fixed-shape plan
            # arrays that hold mixed_step at 1.
            if self._adapter_pool is not None:
                fns["adapter_multi_step"] = (
                    self._adapter_spec_multi_step_fn if self._speculative
                    else self._adapter_multi_step_fn
                )
            else:
                fns["multi_step"] = (
                    self._spec_multi_step_fn if self._speculative
                    else self._multi_step_fn
                )
        if self._last_kv_export_args is not None:
            fns["kv_export"] = self._kv_export_fn
        if self._last_kv_ingest_args is not None:
            fns["kv_ingest"] = self._kv_ingest_fn
        if self._last_kv_page_spill_args is not None:
            fns["kv_page_spill"] = self._kv_page_spill_fn
        if self._last_kv_page_fill_args is not None:
            fns["kv_page_fill"] = self._kv_page_fill_fn
        return {k: cache_size(f) for k, f in fns.items()}

    def _dispatched_programs(self):
        """``(program_name, jitted_fn, args)`` for every engine program
        that has dispatched at least once — THE one list of relowerable
        programs, shared by the runtime reports and the static contract
        pass so a new program cannot be visible to one and invisible to
        the other. ``first_refill`` is included so single-chunk prefills
        are not silently missing."""
        out = []
        if self._last_first_refill_args is not None:
            out.append((
                "first_refill", self._first_refill_fn,
                self._last_first_refill_args(),
            ))
        if self._last_refill_args is not None:
            out.append((
                "refill_step", self._refill_step_fn,
                self._last_refill_args(),
            ))
        if self._last_decode_args is not None:
            if self._speculative:
                fn, name = self._decode_block_spec_fn, "decode_block_spec"
            else:
                fn, name = self._decode_block_fn, "decode_block"
            out.append((name, fn, self._last_decode_args()))
        if self._last_decode_plain_args is not None:
            # The degradation ladder's target-only decode on a SPEC
            # engine — the same program a plain engine runs, visible to
            # the contract pass under the plain ``decode_step`` golden.
            out.append((
                "decode_block", self._decode_block_fn,
                self._last_decode_plain_args(),
            ))
        if self._last_mixed_args is not None:
            if self._adapter_pool is not None:
                fn = (
                    self._adapter_spec_mixed_step_fn if self._speculative
                    else self._adapter_mixed_step_fn
                )
                name = "adapter_mixed_step"
            else:
                fn = (
                    self._spec_mixed_step_fn if self._speculative
                    else self._mixed_step_fn
                )
                name = "mixed_step"
            out.append((name, fn, self._last_mixed_args()))
        if self._last_multi_args is not None:
            if self._adapter_pool is not None:
                fn = (
                    self._adapter_spec_multi_step_fn if self._speculative
                    else self._adapter_multi_step_fn
                )
                name = "adapter_multi_step"
            else:
                fn = (
                    self._spec_multi_step_fn if self._speculative
                    else self._multi_step_fn
                )
                name = "multi_step"
            out.append((name, fn, self._last_multi_args()))
        if self._last_kv_export_args is not None:
            out.append((
                "kv_export", self._kv_export_fn,
                self._last_kv_export_args(),
            ))
        if self._last_kv_ingest_args is not None:
            out.append((
                "kv_ingest", self._kv_ingest_fn,
                self._last_kv_ingest_args(),
            ))
        if self._last_kv_page_spill_args is not None:
            out.append((
                "kv_page_spill", self._kv_page_spill_fn,
                self._last_kv_page_spill_args(),
            ))
        if self._last_kv_page_fill_args is not None:
            out.append((
                "kv_page_fill", self._kv_page_fill_fn,
                self._last_kv_page_fill_args(),
            ))
        return out

    def _program_reports(self) -> dict[str, dict]:
        """Full ``executable_report`` per dispatched engine program,
        re-lowered AOT with its most recent dispatch arguments (costs a
        compile per program — diagnostics, not hot path; coverage per
        :meth:`_dispatched_programs`)."""
        from learning_jax_sharding_tpu.telemetry.compile_watch import (
            executable_report,
        )

        with activate(self._mesh, self._rules):
            return {
                name: executable_report(fn, *args)
                for name, fn, args in self._dispatched_programs()
            }

    def collective_inventory(self) -> dict[str, dict[str, int]]:
        """Per-dispatch collective counts read off the engine's OWN
        compiled programs — ``parallel.hlo.collective_counts`` over each
        program (see :meth:`_program_reports` for cost and coverage)."""
        return {
            name: rep["collectives"]
            for name, rep in self._program_reports().items()
        }

    def program_hlo(self) -> dict[str, str]:
        """Optimized HLO text per dispatched engine program — the static
        contract pass's view of the serving path (``analysis.contracts``).
        Same AOT-relower cost and coverage as :meth:`_program_reports`
        (both map over :meth:`_dispatched_programs`)."""
        from learning_jax_sharding_tpu.parallel.hlo import compiled_hlo

        with activate(self._mesh, self._rules):
            return {
                name: compiled_hlo(fn, *args)
                for name, fn, args in self._dispatched_programs()
            }

    #: Engine program → golden contract name (``analysis/golden/<name>.json``)
    #: — the names ``analysis.entrypoints`` generates under. A SPECULATIVE
    #: engine's programs get a ``spec_`` prefix on top (its refill also
    #: prefills the draft cache — a different program family with its own
    #: goldens): spec_first_prefill / spec_prefill / spec_decode_step.
    CONTRACT_NAMES = {
        "first_refill": "first_prefill",
        "refill_step": "prefill",
        "decode_block": "decode_step",
        "decode_block_spec": "decode_step",
        "mixed_step": "mixed_step",
        "adapter_mixed_step": "adapter_mixed_step",
        "multi_step": "multi_step",
        "adapter_multi_step": "adapter_multi_step",
        "kv_export": "kv_export",
        "kv_ingest": "kv_ingest",
        "kv_page_spill": "kv_page_spill",
        "kv_page_fill": "kv_page_fill",
    }

    def contract_name(self, program: str) -> str:
        base = self.CONTRACT_NAMES.get(program, program)
        comp = self._comp
        if program in (
            "kv_export", "kv_ingest", "kv_page_spill", "kv_page_fill"
        ):
            # The handoff programs are only dispatchable on non-spec
            # engines (export/ingest raise otherwise) — one golden each.
            # A KV codec does not change the DEVICE program (the codec
            # runs in the host transfer plan), but a compression engine
            # contracts under ``*_q8`` names anyway: the golden set must
            # say, checkably, which byte-movement regime it was pinned
            # under.
            if comp is not None and comp.kv_codec is not None:
                return f"{base}_q8"
            return base
        if comp is not None and comp.active:
            # Apply-family programs compile the quantized TP matmul in:
            # a DIFFERENT steady-state program with its own golden. A
            # drift trip flips ``comp.enabled`` off and the retraced
            # programs contract under the plain names again.
            base = f"{base}_q8"
        if program == "decode_block":
            # The plain decode program keeps its plain golden even on a
            # speculative engine: the degradation ladder dispatches it
            # with the target cache only, and it compiles to the same
            # HLO a non-speculative engine's decode_block does — no new
            # steady-state program beyond the documented set.
            return base
        return f"spec_{base}" if self._speculative else base

    def check_contracts(self, golden_dir):
        """Check every dispatched engine program against its golden SPMD
        contract in ``golden_dir`` (:meth:`contract_name` maps programs
        to golden files) and return the findings — the serving-side
        enforcement hook for ``scripts/shardcheck.py``. Findings also
        land in this engine's flight recorder and registry, so a contract
        drift shows up in the same diagnosis bundle as the runtime events
        it explains."""
        from learning_jax_sharding_tpu.analysis.contracts import (
            check_against_golden,
            contract_of,
        )
        from learning_jax_sharding_tpu.analysis.findings import (
            report_findings,
        )

        findings = []
        for prog, text in self.program_hlo().items():
            observed = contract_of(
                self.contract_name(prog), text, mesh=self._mesh
            )
            findings.extend(check_against_golden(golden_dir, observed))
        report_findings(
            findings, recorder=self.recorder, registry=self.registry
        )
        return findings

    def explain_collectives(
        self, *, measured: bool = False, profile=None
    ) -> dict[str, "object"]:
        """Pre-compile collective attribution for every dispatched engine
        program: run the GSPMD propagation simulator
        (``analysis.shardflow``) over each program's jaxpr and return a
        :class:`~learning_jax_sharding_tpu.analysis.shardflow.
        ShardflowReport` per contract name — each predicted collective
        carries the SOURCE LINE that causes it, which the compiled-HLO
        inventory (:meth:`collective_inventory`) can never recover.
        Trace-only (``jax.make_jaxpr``): no compiles, so this is cheap
        enough to run on a live engine. Decode-family programs advance
        ``decode_block_steps`` tokens per dispatch inside their device
        loop; that trip count prices the in-loop collectives.

        With ``measured=True`` each contract name instead maps to
        ``{"report", "measured_comm_s", "lines"}``: the same report plus
        the ledger window's measured collective seconds for that program
        family (exposed + overlapped from :meth:`overlap_report`),
        attributed per SOURCE LINE proportionally to the costmodel's
        per-line prediction (``telemetry.commscope``) — the
        predicted-vs-measured table ``shardcheck --explain`` prints."""
        from learning_jax_sharding_tpu.analysis.shardflow import (
            trace_shardflow,
        )

        out = {}
        with activate(self._mesh, self._rules):
            for name, fn, args in self._dispatched_programs():
                cname = self.contract_name(name)
                # The fused horizon program scans its body ``horizon``
                # times, not ``decode_block_steps``: price its in-loop
                # collectives at the horizon trip count so the reconciled
                # total caps at N× the single-step multiset.
                hint = (
                    int(self.horizon)
                    if name in ("multi_step", "adapter_multi_step")
                    else int(self._block_steps)
                )
                out[cname] = trace_shardflow(
                    cname, fn, *args, mesh=self._mesh,
                    while_trip_hint=hint,
                )
        if not measured:
            return out

        from learning_jax_sharding_tpu.analysis import costmodel
        from learning_jax_sharding_tpu.telemetry import commscope

        if profile is None:
            profile = costmodel.current_profile()
        overlap = self.ledger.overlap_report(
            predicted=self._comm_predictions(profile, out)
        )
        res = {}
        for name, _fn, _args in self._dispatched_programs():
            cname = self.contract_name(name)
            rep = out.get(cname)
            if rep is None:
                continue
            fam = overlap["families"].get(name)
            meas = (
                fam["exposed_comm_s"] + fam["overlapped_comm_s"]
                if fam else 0.0
            )
            res[cname] = {
                "report": rep,
                "measured_comm_s": meas,
                "lines": commscope.line_report(rep, profile, meas),
            }
        return res

    def _comm_predictions(self, profile, reports) -> dict[str, dict]:
        """Per-dispatch ``{"compute_s", "comm_s"}`` costmodel prediction
        per program family (keys = :meth:`_dispatched_programs` names,
        matching the ledger's device-family tags). ``compute_s`` is the
        non-collective roofline (max of compute/memory terms) — the
        serial lens :func:`~.commscope.decompose_overlap` needs."""
        from learning_jax_sharding_tpu.analysis import costmodel

        preds = {}
        for name, _fn, _args in self._dispatched_programs():
            rep = reports.get(self.contract_name(name))
            if rep is None:
                continue
            cost = costmodel.price(rep, profile)
            preds[name] = {
                "compute_s": max(cost.compute_s, cost.memory_s),
                "comm_s": cost.collective_s,
            }
        return preds

    def overlap_report(self, profile=None) -> dict:
        """Decompose the ledger window's device seconds into compute /
        exposed-comm / overlapped-comm per program family
        (``GoodputLedger.overlap_report``), with per-dispatch costmodel
        predictions derived from this engine's own shardflow reports.
        The decomposition sums back to the device bucket exactly, so
        ``reconcile()`` is untouched."""
        from learning_jax_sharding_tpu.analysis import costmodel

        if profile is None:
            profile = costmodel.current_profile()
        reports = self.explain_collectives()
        return self.ledger.overlap_report(
            predicted=self._comm_predictions(profile, reports)
        )

    def comm_report(
        self, profile=None, comm_profile=None, *, export_gauges=True,
    ) -> dict:
        """The comm-observatory verdict for the current ledger window.

        Combines the overlap decomposition with per-source-line
        predicted-vs-measured attribution for every program family, and
        (by default) publishes the ``comm_axis_bandwidth_bytes_per_s``
        and ``comm_exposed_seconds_total{family,axis}`` gauges into this
        engine's registry — the Prometheus/fleet-merge path.

        ``comm_profile`` is a measured ``telemetry.commscope.CommProfile``
        (calibration ladder output); when given, pricing uses its
        per-axis α–β models via ``costmodel.calibrate_axis_profiles``
        with the pinned table as fallback."""
        from learning_jax_sharding_tpu.analysis import costmodel
        from learning_jax_sharding_tpu.telemetry import commscope

        if profile is None:
            profile = costmodel.current_profile()
        if comm_profile is not None:
            profile = costmodel.calibrate_axis_profiles(
                comm_profile, base=profile)
            if export_gauges:
                commscope.export_profile_gauges(self.registry, comm_profile)
        reports = self.explain_collectives()
        overlap = self.ledger.overlap_report(
            predicted=self._comm_predictions(profile, reports)
        )
        families = {}
        for name, fam in overlap["families"].items():
            rep = reports.get(self.contract_name(name))
            meas = fam["exposed_comm_s"] + fam["overlapped_comm_s"]
            shares = (
                commscope.axis_comm_shares(rep, profile)
                if rep is not None else {}
            )
            if export_gauges:
                commscope.export_exposed_gauges(
                    self.registry, name, fam["exposed_comm_s"], shares)
            families[name] = {
                **fam,
                "measured_comm_s": meas,
                "axis_shares": shares,
                "lines": (
                    commscope.line_report(rep, profile, meas)
                    if rep is not None else []
                ),
            }
        return {
            "profile": profile.to_dict(),
            "overlap": overlap,
            "families": families,
        }

    def collective_axis_volume(self) -> dict[str, dict]:
        """Per-MESH-AXIS collective byte volume for each engine program:
        what one refill/decode dispatch puts on the wire, attributed to
        the mesh axis whose device groups carry it
        (``telemetry.devview.axis_collective_volume``). Same AOT-relower
        cost and coverage as :meth:`collective_inventory`."""
        from learning_jax_sharding_tpu.telemetry.devview import (
            axis_collective_volume,
        )

        return {
            name: axis_collective_volume(
                rep["collective_instructions"], self._mesh
            )
            for name, rep in self._program_reports().items()
        }

    def dump_diagnostics(self, outdir=None):
        """Write the engine's post-mortem bundle (flight-recorder events +
        registry snapshot + Chrome trace + device memory stats) and return
        its directory — the on-demand form of what
        ``recorder.capture()`` dumps on exception."""
        return self.recorder.dump(
            outdir, registry=self.registry, tracer=self.tracer
        )

    # --- one-shot entry ----------------------------------------------------

    def serve(self, params, prompts, rng=None, draft_params=None):
        """Drain a whole queue: outputs in queue order, requests numbered
        by queue index (the sampling-stream identity). Requires an idle
        engine (streaming work must finish first); persistent state —
        cache, pool, prefix registry — carries over BETWEEN calls."""
        self._check_draft_args(draft_params)
        if self.has_work():
            raise RuntimeError(
                "serve() requires an idle engine: drain streaming work "
                "(step() until not has_work()) first"
            )
        prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
        # Validate EVERYTHING before touching any state: a bad prompt
        # must raise without costing the engine its persistent registry
        # (the failure path below resets the pool).
        for p in prompts:
            self._validate_prompt(p)
        self.rng = jax.random.key(0) if rng is None else rng
        self.reset_stats()
        # The per-call rid namespace (0..n-1) must not collide with
        # un-popped streaming results: stash them, restore after — a
        # failed call's partial outputs are dropped with its state.
        stash = self._finished
        self._finished = {}
        ok = False
        try:
            for i, p in enumerate(prompts):
                self.add_request(p, rid=i)
            with self.tracer.span("engine.serve", requests=len(prompts)):
                while self.has_work():
                    self.step(params, draft_params)
            ok = True
        finally:
            # Stats must reflect THIS call even when it raises — pool
            # exhaustion is exactly when the measured footprint matters.
            self._snapshot_stats()
            if not ok:
                # Leave the engine reusable: drop the wedged in-flight
                # state (and the registry — partial writes may alias it).
                self.reset()
                self._finished = stash
        results = []
        for i in range(len(prompts)):
            r = self._finished.pop(i)
            if r.status == "ok":
                results.append(np.asarray(r.tokens, np.int32))
            else:
                # Recovery policies can retire a request WITHOUT
                # completing it (deadline TTL, poison quarantine,
                # malformed) — its queue-order slot carries the terminal
                # status instead of tokens, never a silent gap.
                results.append(RequestFailure(
                    rid=r.rid, status=r.status, error=r.error,
                    tokens=r.tokens,
                ))
        self._finished = stash
        return results


def make_continuous_engine(
    config: TransformerConfig, mesh: Mesh, rules: Rules, **kwargs
):
    """Build a persistent :class:`ContinuousEngine` and return its
    one-shot entry ``serve(params, prompts, rng, draft_params) ->
    list[np.ndarray]`` (the original engine API — every oracle pinned on
    it holds unchanged). The wrapped engine is reachable at
    ``serve.engine`` for streaming admission and telemetry; after each
    call ``serve.last_stats`` / ``serve.last_latency`` mirror the
    engine's. Because the engine persists, repeated calls share the KV
    cache, page pool, and prefix registry — see
    :class:`ContinuousEngine` for the full contract."""
    engine = ContinuousEngine(config, mesh, rules, **kwargs)

    def serve(params, prompts, rng=None, draft_params=None):
        try:
            return engine.serve(
                params, prompts, rng=rng, draft_params=draft_params
            )
        finally:
            serve.last_stats = engine.last_stats
            serve.last_latency = engine.last_latency

    serve.engine = engine
    serve.last_stats = None
    serve.last_latency = None
    return serve
