"""HuggingFace GPT-2 interop: load transformer weights into this framework.

"A user of the reference should be able to switch and find everything they
need" — including their existing checkpoints. GPT-2's architecture is a
pre-LN transformer with learned positions, biased projections, and tanh
GELU: exactly :class:`models.transformer.Transformer` at
``use_bias=True, norm_eps=1e-5`` (the reference has no model zoo or
checkpoint interop at all, SURVEY.md §5). This module maps a
``transformers`` GPT-2 state dict onto this framework's param tree, after
which the ENTIRE stack applies unchanged: sharded apply under any rule set,
KV-cached generation, beam search, int8/int4 serving, LoRA fine-tuning.

Parity is exact, not approximate: ``tests/test_convert.py`` checks logits
against the torch model to float tolerance. Works offline — the tests build
randomly initialized ``GPT2LMHeadModel``s (no downloads); real checkpoints
convert the same way.

Layout notes (verified against ``transformers`` GPT-2):

* HF ``Conv1D`` stores weights ``(in, out)`` — the same orientation as our
  Dense kernels, so no transposes except the tied LM head;
* ``c_attn`` packs q/k/v as one ``(E, 3E)`` kernel → split into three;
  the per-head layout after reshaping ``E → (heads, head_dim)`` matches our
  ``(B, S, N, H)`` reshape, so no head permutation is needed;
* the LM head is tied to the token embedding: ``lm_head.kernel = wteᵀ``.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from learning_jax_sharding_tpu.models.transformer import TransformerConfig


def config_from_hf_gpt2(hf_config: Any, **overrides) -> TransformerConfig:
    """TransformerConfig matching a ``transformers.GPT2Config``.

    ``overrides`` pass through to the dataclass (e.g. ``dtype=jnp.bfloat16``
    for TPU serving of a converted checkpoint).
    """
    if hf_config.activation_function not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(
            f"unsupported activation {hf_config.activation_function!r}: the "
            "FeedForward uses tanh GELU (gelu_new)"
        )
    # GPT-2 attention variants this attention stack does not implement —
    # converting them would produce silently wrong logits, breaking the
    # module's exact-parity contract.
    for flag in ("scale_attn_by_inverse_layer_idx", "reorder_and_upcast_attn"):
        if getattr(hf_config, flag, False):
            raise ValueError(f"unsupported GPT-2 attention variant: {flag}=True")
    if not getattr(hf_config, "scale_attn_weights", True):
        # This attention stack always scales scores by head_dim**-0.5.
        raise ValueError(
            "unsupported GPT-2 attention variant: scale_attn_weights=False"
        )
    if hf_config.n_embd % hf_config.n_head:
        # HF only catches this at model init; fail at config conversion with
        # the same loudness as the unsupported-variant guards above.
        raise ValueError(
            f"n_embd {hf_config.n_embd} not divisible by n_head "
            f"{hf_config.n_head}: head_dim would be fractional"
        )
    import jax.numpy as jnp

    defaults = dict(
        vocab_size=hf_config.vocab_size,
        num_layers=hf_config.n_layer,
        features=hf_config.n_embd,
        num_heads=hf_config.n_head,
        head_dim=hf_config.n_embd // hf_config.n_head,
        # n_inner=None means the GPT-2 default of 4*n_embd.
        hidden=hf_config.n_inner or 4 * hf_config.n_embd,
        max_seq_len=hf_config.n_positions,
        use_bias=True,
        norm_eps=hf_config.layer_norm_epsilon,
        norm="layernorm",
        rope=False,
        causal=True,
        dtype=jnp.float32,
        param_dtype=jnp.float32,
    )
    defaults.update(overrides)
    return TransformerConfig(**defaults)


def params_from_hf_gpt2(hf_model: Any) -> dict:
    """Map a ``transformers.GPT2LMHeadModel`` state dict onto this
    framework's ``Transformer`` param tree (plain numpy leaves — shard with
    ``jax.device_put`` / the sharded-init pipeline as usual)."""
    sd = {k: v.detach().cpu().numpy() for k, v in hf_model.state_dict().items()}
    n_layer = hf_model.config.n_layer
    e = hf_model.config.n_embd

    def t(name):
        return sd[f"transformer.{name}"].astype(np.float32)

    # GPT-2 usually ties the LM head to wte; reading "lm_head.weight" is
    # correct for tied AND untied checkpoints (tied state dicts alias it).
    head = sd.get("lm_head.weight", sd["transformer.wte.weight"])
    params: dict = {
        "tok_embed": {"embedding": t("wte.weight")},
        "pos_embed": t("wpe.weight"),
        "ln_out": {"scale": t("ln_f.weight"), "bias": t("ln_f.bias")},
        "lm_head": {"kernel": head.astype(np.float32).T},
    }
    for i in range(n_layer):
        p = f"h.{i}"
        qkv_w = t(f"{p}.attn.c_attn.weight")  # (E, 3E), Conv1D = (in, out)
        qkv_b = t(f"{p}.attn.c_attn.bias")
        params[f"block_{i}"] = {
            "ln_attn": {
                "scale": t(f"{p}.ln_1.weight"), "bias": t(f"{p}.ln_1.bias")
            },
            "attn": {
                "query": {"kernel": qkv_w[:, :e], "bias": qkv_b[:e]},
                "key": {"kernel": qkv_w[:, e : 2 * e], "bias": qkv_b[e : 2 * e]},
                "value": {"kernel": qkv_w[:, 2 * e :], "bias": qkv_b[2 * e :]},
                "out": {
                    "kernel": t(f"{p}.attn.c_proj.weight"),
                    "bias": t(f"{p}.attn.c_proj.bias"),
                },
            },
            "ln_ff": {
                "scale": t(f"{p}.ln_2.weight"), "bias": t(f"{p}.ln_2.bias")
            },
            "ff": {
                "up": {
                    "kernel": t(f"{p}.mlp.c_fc.weight"),
                    "bias": t(f"{p}.mlp.c_fc.bias"),
                },
                "down": {
                    "kernel": t(f"{p}.mlp.c_proj.weight"),
                    "bias": t(f"{p}.mlp.c_proj.bias"),
                },
            },
        }
    return params


def state_dict_from_params(params: dict, *, tie_head: bool = True) -> dict:
    """Inverse of :func:`params_from_hf_gpt2`: framework params → a
    ``transformers`` GPT-2 state dict (torch tensors), so models trained or
    fine-tuned here (e.g. LoRA-merged) export back to the HF ecosystem.

    ``tie_head`` drops the separate ``lm_head.weight`` entry and lets HF tie
    it to ``wte`` (set False for params whose head was trained untied).
    Load with ``hf_model.load_state_dict(sd, strict=False)`` (HF carries
    non-weight buffers like attention bias masks that this does not emit).
    Trees trained with ``scan_layers`` are unstacked automatically.
    """
    import torch

    params = unstack_scan_params(params)

    def tt(x):
        return torch.tensor(np.asarray(x, np.float32))

    sd = {
        "transformer.wte.weight": tt(params["tok_embed"]["embedding"]),
        "transformer.wpe.weight": tt(params["pos_embed"]),
        "transformer.ln_f.weight": tt(params["ln_out"]["scale"]),
        "transformer.ln_f.bias": tt(params["ln_out"]["bias"]),
    }
    head_t = np.asarray(params["lm_head"]["kernel"], np.float32).T
    if tie_head:
        # Guard the default: exporting a DIVERGED head as "tied" would
        # silently drop trained weights (HF re-ties lm_head to wte on load).
        wte = np.asarray(params["tok_embed"]["embedding"], np.float32)
        if not np.allclose(head_t, wte, atol=1e-6):
            raise ValueError(
                "lm_head is not tied to tok_embed (they differ); export with "
                "tie_head=False to keep the trained head"
            )
    else:
        sd["lm_head.weight"] = tt(head_t)
    n_layer = sum(1 for k in params if k.startswith("block_"))
    if n_layer == 0:
        raise ValueError("no block_i subtrees found — not a Transformer param tree")
    for i in range(n_layer):
        blk = params[f"block_{i}"]
        p = f"transformer.h.{i}"
        attn = blk["attn"]
        qkv_w = np.concatenate(
            [np.asarray(attn[k]["kernel"], np.float32) for k in ("query", "key", "value")],
            axis=1,
        )
        qkv_b = np.concatenate(
            [np.asarray(attn[k]["bias"], np.float32) for k in ("query", "key", "value")]
        )
        sd.update({
            f"{p}.ln_1.weight": tt(blk["ln_attn"]["scale"]),
            f"{p}.ln_1.bias": tt(blk["ln_attn"]["bias"]),
            f"{p}.attn.c_attn.weight": tt(qkv_w),
            f"{p}.attn.c_attn.bias": tt(qkv_b),
            f"{p}.attn.c_proj.weight": tt(attn["out"]["kernel"]),
            f"{p}.attn.c_proj.bias": tt(attn["out"]["bias"]),
            f"{p}.ln_2.weight": tt(blk["ln_ff"]["scale"]),
            f"{p}.ln_2.bias": tt(blk["ln_ff"]["bias"]),
            f"{p}.mlp.c_fc.weight": tt(blk["ff"]["up"]["kernel"]),
            f"{p}.mlp.c_fc.bias": tt(blk["ff"]["up"]["bias"]),
            f"{p}.mlp.c_proj.weight": tt(blk["ff"]["down"]["kernel"]),
            f"{p}.mlp.c_proj.bias": tt(blk["ff"]["down"]["bias"]),
        })
    return sd


def unstack_scan_params(params: dict) -> dict:
    """``scan_layers`` stacked params → the unrolled per-layer layout.

    A model trained with ``scan_layers=True`` (O(1) compile time in depth)
    keeps its block params as one ``"blocks"`` subtree whose leaves carry a
    leading ``(LAYERS,)`` dim. Serving and export run the unrolled stack
    (``block_0..block_{L-1}``) — this splits each stacked leaf along that
    dim so the SAME trained weights drive decode / HF export. Inverse of
    :func:`stack_scan_params`; a tree already in the unrolled layout passes
    through unchanged. Math is identical either way (test-pinned logit
    parity, ``tests/test_scan_layers.py``).
    """
    if "blocks" not in params:
        return params
    if "embed" in params or "head" in params:
        # PipelinedTransformer trees also keep a "blocks" subtree, but its
        # leading dims are (stages, ...) — splitting those as layers would
        # silently produce wrong-rank per-layer tensors. Fail loudly instead.
        raise ValueError(
            "params look like a PipelinedTransformer stage-stacked tree "
            "(embed/blocks/head); unstack_scan_params handles only "
            "Transformer scan_layers trees"
        )
    import jax

    blocks = params["blocks"]
    num_layers = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    out = {k: v for k, v in params.items() if k != "blocks"}
    for i in range(num_layers):
        out[f"block_{i}"] = jax.tree.map(lambda x, i=i: x[i], blocks)
    return out


def stack_scan_params(params: dict) -> dict:
    """Unrolled ``block_i`` params → the ``scan_layers`` stacked layout
    (leaves gain a leading layer dim). Inverse of
    :func:`unstack_scan_params`; a tree already stacked passes through."""
    import jax
    import jax.numpy as jnp

    n_layer = sum(1 for k in params if k.startswith("block_"))
    if n_layer == 0:
        return params
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[params[f"block_{i}"] for i in range(n_layer)],
    )
    rest = {k: v for k, v in params.items() if not k.startswith("block_")}
    return {**rest, "blocks": stacked}
