"""Pipeline-parallel transformer: the case-7 model over a ``pipe`` mesh axis.

The reference runs every layer on every device (SURVEY.md §2.4: pipeline
parallelism absent). This module splits the case-7 transformer's block stack
into contiguous stages carried by a ``pipe`` mesh axis and streams
microbatches through them with :func:`parallel.pipeline.spmd_pipeline` —
while the embedding, the stage-internal math, and the logits head keep their
data/tensor shardings under GSPMD (partial-manual ``shard_map``: only the
pipe axis is manual). One jitted train step therefore composes dp x tp x pp.

Design: this is an orchestrator over pure functions, not an ``nn.Module`` —
the per-layer parameters must live in ONE stacked pytree (leading dims
``(stages, layers_per_stage)``) so a single ``ppermute`` ring and a single
``lax.scan`` serve every stage, which is incompatible with Flax's
one-submodule-per-layer parameter naming. The blocks themselves ARE the
ordinary :class:`models.transformer.TransformerBlock`; their params are
created by ``jax.vmap`` of the block's init over per-layer PRNG keys.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax.linen import partitioning as nn_partitioning
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from learning_jax_sharding_tpu.models.transformer import (
    TransformerBlock,
    TransformerConfig,
    make_norm,
)
from learning_jax_sharding_tpu.parallel.logical import (
    BATCH,
    EMBED,
    Rules,
    SEQ,
    VOCAB,
    activate,
)
from learning_jax_sharding_tpu.parallel.pipeline import (
    PIPE_AXIS,
    spmd_pipeline,
    stack_stage_params,
)


class _EmbedIn(nn.Module):
    """Token + position embedding (the case-7 model's input layer, run
    outside the pipeline: it is one cheap gather, not worth a stage)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        cfg = self.config
        s = tokens.shape[1]
        x = nn.Embed(
            cfg.vocab_size,
            cfg.features,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), (VOCAB, EMBED)
            ),
            name="tok_embed",
        )(tokens)
        pos = self.param(
            "pos_embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), (SEQ, EMBED)
            ),
            (cfg.max_seq_len, cfg.features),
            cfg.param_dtype,
        )
        x = x + pos[None, :s].astype(cfg.dtype)
        return nn.with_logical_constraint(x, (BATCH, SEQ, EMBED))


class _Head(nn.Module):
    """Final LayerNorm + logits projection (run outside the pipeline)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        x = make_norm(
            cfg.norm, cfg.dtype, cfg.param_dtype, "ln_out", cfg.norm_eps
        )(x)
        logits = nn.Dense(
            cfg.vocab_size,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), (EMBED, VOCAB)
            ),
            name="lm_head",
        )(x)
        return nn.with_logical_constraint(logits, (BATCH, SEQ, VOCAB))


@dataclasses.dataclass
class PipelinedTransformer:
    """The case-7 transformer with its block stack pipelined over ``pipe``.

    Parameters are a plain dict pytree::

        {"embed": <_EmbedIn params>,
         "blocks": <TransformerBlock params, leaves (P, L/P, ...)>,
         "head":  <_Head params>}

    ``init_sharded`` births it already sharded (the reference's born-sharded
    init pattern, `/root/reference/case6_attention.py:189-196`, extended with
    the stage dim on the pipe axis).
    """

    config: TransformerConfig
    mesh: Mesh
    rules: Rules
    num_stages: int
    num_microbatches: Optional[int] = None
    interleave: int = 1
    # >1 = interleaved circular schedule: each device owns `interleave`
    # round-robin layer chunks and microbatches circulate the ring that many
    # times — the GPipe bubble shrinks ~interleave-fold
    # (parallel/pipeline.py module docstring has the measured tick counts).
    pipe_axis: str = PIPE_AXIS

    def __post_init__(self):
        cfg = self.config
        if cfg.num_layers % (self.num_stages * self.interleave):
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by "
                f"num_stages {self.num_stages} × interleave {self.interleave}"
            )
        if self.mesh.shape[self.pipe_axis] != self.num_stages:
            raise ValueError(
                f"mesh axis {self.pipe_axis!r} has size "
                f"{self.mesh.shape[self.pipe_axis]}, want {self.num_stages}"
            )
        # Unsupported-config guard: silently training a different model than
        # the config asks for would be worse than refusing.
        if cfg.num_experts > 0:
            raise ValueError(
                "PipelinedTransformer does not support MoE blocks yet "
                "(num_experts > 0); use Transformer with RULES_DP_TP_EP"
            )
        if cfg.dropout_rate > 0:
            raise ValueError(
                "PipelinedTransformer does not support dropout yet "
                "(the pipelined stage_fn runs deterministically)"
            )
        self._embed = _EmbedIn(cfg)
        self._head = _Head(cfg)
        self._block = TransformerBlock(
            features=cfg.features,
            num_heads=cfg.num_heads,
            head_dim=cfg.head_dim,
            num_kv_heads=cfg.num_kv_heads,
            rope=cfg.rope,
            rope_theta=cfg.rope_theta,
            window=cfg.window,
            hidden=cfg.hidden,
            dropout_rate=0.0,
            causal=cfg.causal,
            use_bias=cfg.use_bias,
            norm_eps=cfg.norm_eps,
            norm=cfg.norm,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            attn_fn=cfg.attn_fn,
        )

    # -- init ---------------------------------------------------------------

    def _init_boxed(self, rng: jax.Array, tokens: jax.Array) -> dict:
        cfg = self.config
        k_embed, k_blocks, k_head = jax.random.split(rng, 3)
        embed_p = self._embed.init({"params": k_embed}, tokens)["params"]
        x = jax.eval_shape(
            lambda p, t: self._embed.apply({"params": p}, t),
            nn.meta.unbox(embed_p),
            tokens,
        )
        x = jnp.zeros(x.shape, x.dtype)
        # One init per layer, vmapped over keys → every leaf gains a leading
        # layer dim; the boxed logical names stay those of a single block.
        layer_keys = jax.random.split(k_blocks, cfg.num_layers)
        block_p = jax.vmap(
            lambda k: self._block.init({"params": k}, x)["params"]
        )(layer_keys)
        head_p = self._head.init({"params": k_head}, x)["params"]
        return {"embed": embed_p, "blocks": block_p, "head": head_p}

    def _shardings(self, abstract_boxed: dict) -> dict:
        """Map logical specs to shardings; block leaves get
        ``(pipe, None, *logical)`` for their ``(P, L/P, ...)`` layout."""
        rules = tuple(self.rules)

        def leaf_sharding(box: Any, extra: tuple) -> NamedSharding:
            axes = (
                nn_partitioning.logical_to_mesh_axes(tuple(box.names), rules)
                if isinstance(box, nn.LogicallyPartitioned)
                else PartitionSpec()
            )
            return NamedSharding(self.mesh, PartitionSpec(*extra, *axes))

        embed_sh = jax.tree.map(
            lambda b: leaf_sharding(b, ()),
            abstract_boxed["embed"],
            is_leaf=lambda b: isinstance(b, nn.LogicallyPartitioned),
        )
        head_sh = jax.tree.map(
            lambda b: leaf_sharding(b, ()),
            abstract_boxed["head"],
            is_leaf=lambda b: isinstance(b, nn.LogicallyPartitioned),
        )
        # Block leaves are (P, L/P, *weight_dims) — or (P, V, c, *weight_dims)
        # when interleaved: stage dim over pipe, chunk/layer dims replicated,
        # weight dims per their logical names (TP rides here).
        lead = (self.pipe_axis, None) + (None,) * (self.interleave > 1)
        blocks_sh = jax.tree.map(
            lambda b: leaf_sharding(b, lead),
            abstract_boxed["blocks"],
            is_leaf=lambda b: isinstance(b, nn.LogicallyPartitioned),
        )
        return {"embed": embed_sh, "blocks": blocks_sh, "head": head_sh}

    def init_sharded(self, rng: jax.Array, tokens: jax.Array) -> tuple[dict, dict]:
        """Born-sharded params: ``(params, shardings)``.

        The stacked per-layer block params are reshaped to
        ``(num_stages, layers_per_stage, ...)`` inside the jitted init so no
        replicated copy ever materializes.
        """

        def init_fn(rng, tokens):
            boxed = self._init_boxed(rng, tokens)
            params = nn.meta.unbox(boxed)
            params["blocks"] = stack_stage_params(
                params["blocks"], self.num_stages, self.interleave
            )
            return params

        def restack(box: Any) -> Any:
            # Abstract leaves are ShapeDtypeStructs, possibly inside
            # LogicallyPartitioned boxes (whose names cover only the weight
            # dims): rewrite (L, ...) shapes to (P, L/P, ...) in place.
            value = box.value if isinstance(box, nn.LogicallyPartitioned) else box
            chunks = self.num_stages * self.interleave
            lead = (
                (self.num_stages, value.shape[0] // self.num_stages)
                if self.interleave == 1
                else (self.num_stages, self.interleave, value.shape[0] // chunks)
            )
            value = jax.ShapeDtypeStruct(
                lead + tuple(value.shape[1:]), value.dtype
            )
            if isinstance(box, nn.LogicallyPartitioned):
                return box.replace_boxed(value)
            return value

        with activate(self.mesh, self.rules):
            abstract_boxed = jax.eval_shape(self._init_boxed, rng, tokens)
            # eval_shape sees the (L, ...) layout; reshape to (P, L/P, ...)
            # before computing shardings so specs line up with init_fn output.
            abstract_boxed["blocks"] = jax.tree.map(
                restack,
                abstract_boxed["blocks"],
                is_leaf=lambda b: isinstance(b, nn.LogicallyPartitioned),
            )
            shardings = self._shardings(abstract_boxed)
            params = jax.jit(init_fn, out_shardings=shardings)(rng, tokens)
        return params, shardings

    # -- forward ------------------------------------------------------------

    def apply(self, params: dict, tokens: jax.Array) -> jax.Array:
        """Forward pass: embed → pipelined block stack → head → logits."""

        def stage_fn(stage_params, h):
            def apply_layer(layer_params, h):
                return self._block.apply({"params": layer_params}, h)

            if self.config.remat:
                # Recompute each layer's activations in the backward pipeline
                # instead of holding M microbatches' worth of them live.
                apply_layer = jax.checkpoint(apply_layer)

            def body(h, layer_params):
                return apply_layer(layer_params, h), None

            h, _ = lax.scan(body, h, stage_params)
            return h

        x = self._embed.apply({"params": params["embed"]}, tokens)
        x = spmd_pipeline(
            stage_fn,
            params["blocks"],
            x,
            mesh=self.mesh,
            axis=self.pipe_axis,
            num_microbatches=self.num_microbatches,
            interleave=self.interleave,
        )
        return self._head.apply({"params": params["head"]}, x)

    # -- training -----------------------------------------------------------

    def init_optimizer(
        self, params: dict, optimizer: optax.GradientTransformation
    ) -> Any:
        """Optimizer state born sharded like the params: ``optimizer.init``
        is jitted with the sharded params as input, so XLA propagates the
        parameter shardings onto the (shape-mirroring) moment buffers."""
        with activate(self.mesh, self.rules):
            return jax.jit(optimizer.init)(params)

    def make_train_step(
        self,
        optimizer: optax.GradientTransformation,
        loss_fn: Callable[[jax.Array, Any], jax.Array],
    ) -> Callable:
        """Jitted ``step((params, opt_state), batch) -> ((params, opt_state),
        loss)`` with the carry donated — the pp analogue of
        ``training.pipeline.make_train_step``. Pass sharded params and the
        state from :meth:`init_optimizer`; shardings flow from the inputs."""

        def step(carry, batch):
            params, opt_state = carry

            def loss_of(p):
                logits = self.apply(p, batch["inputs"])
                return loss_fn(logits, batch)

            loss, grads = jax.value_and_grad(loss_of)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        jitted = jax.jit(step, donate_argnums=(0,))

        def run(carry, batch):
            with activate(self.mesh, self.rules):
                return jitted(carry, batch)

        run.jitted = jitted
        return run
