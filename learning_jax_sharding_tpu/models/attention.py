"""Logically partitioned multi-head attention (the case-5/6 model, L4).

Rebuilds the reference's ``FlaxAttention``
(`/root/reference/case6_attention.py:42-143`, minimal form
`/root/reference/case5_attention_dense.py:41-71`) as a framework module:

* Q/K/V projections with logical kernel axes ``(EMBED, HEADS)`` and output
  projection ``(HEADS, EMBED)`` — matching `case6_attention.py:56-90`, so the
  case-6 parity oracles hold (Wq (640,512) → shard (320,512) under the
  reference rules on a 2×2 mesh, SURVEY.md §8);
* activation sharding constraints between every stage
  (`case6_attention.py:105-116,137,141`), expressed with honest axis names
  (``SEQ`` for the sequence dim — see logical.py's design note);
* fp32 softmax upcast (`case6_attention.py:121-130`) via ``ops.attention``;
* selectable attention backend: dense einsum attention (reference semantics),
  or the Pallas flash kernel for long sequences.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from learning_jax_sharding_tpu.ops.attention import (
    causal_mask,
    dot_product_attention,
    sliding_window_mask,
)
from learning_jax_sharding_tpu.ops.rope import apply_rope
from learning_jax_sharding_tpu.parallel.logical import BATCH, EMBED, HEADS, KV, SEQ


def resolve_decode_backend(mode: str) -> str:
    """``"auto"`` → the blocked Pallas cache kernel on TPU, the dense cached
    path elsewhere (the kernel runs off-TPU only under the slow interpreter).
    Explicit ``"dense"`` / ``"blocked"`` force a backend."""
    if mode == "auto":
        return "blocked" if jax.default_backend() == "tpu" else "dense"
    if mode not in ("dense", "blocked"):
        raise ValueError(
            f"unknown decode_attention {mode!r}: expected 'auto', 'dense', "
            f"or 'blocked'"
        )
    return mode


def _dense_attention(q, k, v, mask, *, num_heads):
    """Positional-array-args wrapper so ``jax.checkpoint`` can wrap the dense
    op. The GQA head expansion happens INSIDE: a checkpoint always saves its
    arguments, so expanding before it would store group-factor-times-larger
    k/v residuals — on exactly the long-context path ``remat_attention``
    exists to shrink."""
    return dot_product_attention(
        q, repeat_kv(k, num_heads), repeat_kv(v, num_heads), mask=mask
    )


def quantize_kv_chunk(chunk: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization of a K/V chunk along its last (head-dim)
    axis: per-(token, head) fp32 scales + clipped integer values. THE single
    definition of the cache quantization step — both cached-attention
    backends (dense and blocked) write with it, so the stored values cannot
    drift between layouts."""
    absmax = jnp.max(jnp.abs(chunk.astype(jnp.float32)), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(chunk.astype(jnp.float32) / scale[..., None]), -127, 127
    )
    return scale, q


def row_update(buf: jax.Array, chunk: jax.Array, idx: jax.Array, *, seq_dim: int) -> jax.Array:
    """Write ``chunk`` into ``buf`` at a PER-ROW offset along ``seq_dim``
    (both batch-leading): row ``b``'s chunk lands at ``idx[b]`` — the ragged
    cache write, where every sequence in the batch sits at its own length.
    A vmapped ``dynamic_update_slice`` (lowers to one scatter); the scalar
    path keeps its plain ``dynamic_update_slice``."""

    def one(b_buf, b_chunk, i):
        starts = [0] * b_buf.ndim
        starts[seq_dim - 1] = i
        return jax.lax.dynamic_update_slice(b_buf, b_chunk, tuple(starts))

    return jax.vmap(one)(buf, chunk, idx)


def row_update_masked(
    buf: jax.Array, chunk: jax.Array, idx: jax.Array, lengths: jax.Array,
    *, seq_dim: int,
) -> jax.Array:
    """Length-aware :func:`row_update`: row ``b`` writes only its first
    ``lengths[b]`` chunk positions at ``idx[b]``; the rest of the window
    writes back the buffer's OWN values.

    Why this exists (continuous batching): a refill chunk runs for EVERY
    row, and a zero-length row near the buffer end would have its
    ``dynamic_update_slice`` start CLAMPED below its index — overwriting
    valid attended history with chunk padding. The masked read-modify-write
    makes any clamped or zero-length window a no-op on existing data (and
    aligns a clamped partial chunk to its true offset), so mixed
    refill/decode batches can never corrupt a row's cache.
    """
    s = chunk.shape[seq_dim]
    cap = buf.shape[seq_dim]

    def one(b_buf, b_chunk, i, n):
        start_v = jnp.minimum(i, cap - s)
        starts = [jnp.zeros((), jnp.int32)] * b_buf.ndim
        starts[seq_dim - 1] = start_v
        win = jax.lax.dynamic_slice(b_buf, tuple(starts), b_chunk.shape)
        off = i - start_v          # 0 unless the window start clamped
        pos = jnp.arange(s)
        shape = [1] * b_buf.ndim
        shape[seq_dim - 1] = s
        mask = ((pos >= off) & (pos < off + n)).reshape(shape)
        rolled = jnp.roll(b_chunk, off, axis=seq_dim - 1)
        merged = jnp.where(mask, rolled, win)
        return jax.lax.dynamic_update_slice(b_buf, merged, tuple(starts))

    return jax.vmap(one)(buf, chunk, idx, lengths)


def repeat_kv(kv: jax.Array, num_heads: int) -> jax.Array:
    """Broadcast grouped k/v heads ``(B, S, N_kv, H)`` to ``num_heads``.

    Grouped-query attention shares each k/v head across a group of query
    heads. Parameters, gradients, and (crucially) the decode KV cache stay at
    ``N_kv`` heads — the repeat happens only at attention-compute time so the
    score einsums see matching head counts and every backend (dense, flash,
    ring) works unchanged.
    """
    n_kv = kv.shape[2]
    if n_kv == num_heads:
        return kv
    if num_heads % n_kv:
        raise ValueError(f"num_heads {num_heads} not a multiple of kv heads {n_kv}")
    return jnp.repeat(kv, num_heads // n_kv, axis=2)


class MultiHeadAttention(nn.Module):
    """Multi-head self-attention with logical partitioning.

    Attributes:
        features: residual-stream width M (the reference's M=640,
            `/root/reference/case6_attention.py:151`).
        num_heads: attention heads N (reference: 8, `case6_attention.py:44`).
        head_dim: per-head width H (reference: 64, `case6_attention.py:45`).
        dropout_rate: output dropout (reference: 0.1, `case6_attention.py:91`).
        causal: apply a causal mask (reference attention is bidirectional;
            the case-7 transformer sets this True).
        dtype: computation dtype (bf16 on TPU for MXU throughput; softmax
            still runs fp32 via the op).
        param_dtype: parameter storage dtype.
        attn_fn: attention backend taking ``(q, k, v, *, causal: bool)`` with
            (B, S, N, H) operands (see ops.flash_attention.make_flash_attn_fn
            / ops.ring_attention.make_ring_attn_fn); None (default) uses the
            dense einsum op, which also supports arbitrary masks.
        remat_attention: recompute the O(S²) score/softmax tensors in the
            backward pass instead of saving them (``jax.checkpoint`` around
            the dense attention op). Costs ~one extra score einsum per layer
            (a few % of step FLOPs) and removes the (B, N, S, S) arrays from
            saved activations — the dominant activation-memory term, and what
            otherwise caps batch size (flash-attention memory behavior
            without the kernel). Dense backend only.
    """

    features: int
    num_heads: int = 8
    head_dim: int = 64
    num_kv_heads: Optional[int] = None   # < num_heads → GQA; 1 → MQA
    rope: bool = False                   # rotary positions on q/k
    rope_theta: float = 10_000.0
    window: Optional[int] = None         # causal sliding-window size (SWA)
    dropout_rate: float = 0.0
    causal: bool = False
    use_bias: bool = False               # biases on q/k/v/out (GPT-2 style)
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    attn_fn: Optional[Callable] = None
    remat_attention: bool = False
    decode: bool = False
    max_decode_len: int = 0
    kv_cache_dtype: Optional[jnp.dtype] = None
    # Decode-cache storage format. None stores at compute dtype (default).
    # jnp.int8 quantizes K/V on write with a per-(token, head) fp32 scale —
    # the cache is usually what caps batch x context at serving time, and
    # int8 roughly halves it vs bf16 (fp32 scales add 4/head_dim of the int8
    # bytes: 6% at head_dim=64). Any other dtype (e.g. bf16 under fp32
    # compute) is a plain storage cast.
    decode_attention: str = "auto"
    # Decode-attention backend: "dense" attends the WHOLE max_decode_len
    # buffer every step (reference-style, O(max_len) HBM traffic per token);
    # "blocked" uses the length-aware Pallas cache kernel
    # (ops/decode_attention.py) whose traffic scales with the VALID cache
    # length and which reads GQA caches at N_kv heads with no repeat_kv
    # expansion. "auto" (default) picks blocked on TPU, dense elsewhere.
    # The backends differ in cache layout: dense stores (B, L, N_kv, H),
    # blocked stores (B, N_kv, L, H) (sequence-major per head, so each cache
    # block is one contiguous DMA).
    decode_block_k: Optional[int] = None   # blocked-backend cache block size
    quantization: Optional[str] = None
    # "int4": projections consume quantize_tree(bits=4) params VERBATIM via
    # the fused dequant-matmul kernel (ops/int4_matmul.py) — packed nibbles
    # stream into the dot, no dequantized weights in HBM. None = nn.Dense.
    quantization_group: int = 128
    quantized_matmul_fn: Optional[Callable] = None  # mesh-aware fused-int4
                                         # matmul (make_int4_matmul_fn)
    decode_attn_fn: Optional[Callable] = None
    # Mesh-aware override for the blocked backend (shard_map-wrapped kernel
    # from ops.decode_attention.make_decode_attn_fn); None calls the kernel
    # directly (single-device, or GSPMD-replicated).
    decode_ragged: bool = False
    # Per-ROW cache positions: ``cache_index`` is (B,), writes scatter each
    # row's chunk at its own offset, and masks/rope use per-row positions —
    # mixed-length prompt batches (the normal serving case) become
    # expressible, and rows advance independently (a finished row passes
    # chunk_lengths 0 and stops consuming cache). False keeps the scalar
    # rectangular machinery (no scatter on the hot path).
    decode_paged: bool = False
    # PAGED cache (blocked backend + ragged only): K/V live in per-layer
    # physical page POOLS of ``decode_page_count`` pages ×
    # ``decode_block_k`` tokens, indirected through a per-row
    # ``block_table`` cache variable that the HOST allocator owns
    # (models/serving.py) — cache HBM scales with pages allocated, not
    # B × max_decode_len. Page 0 is a reserved scratch target for masked
    # writes; this module never touches the table.
    decode_page_count: int = 0

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_heads(self) -> int:
        """K/V head count: ``num_kv_heads`` (GQA/MQA) or all heads (MHA).

        Grouped heads shrink k/v projection params, gradients, and the decode
        KV cache by ``num_heads / num_kv_heads`` — the cache is usually what
        caps batch×context at serving time. Query heads are unchanged. Under
        TP rules (HEADS→model) the mesh axis size must divide this count.
        """
        n = self.num_kv_heads if self.num_kv_heads is not None else self.num_heads
        if self.num_heads % n:
            raise ValueError(
                f"num_kv_heads {n} must divide num_heads {self.num_heads}"
            )
        return n

    def _dense(self, features: int, kernel_axes, name: str):
        """nn.Dense, or the fused-int4 drop-in under quantization="int4"
        (one shared dispatch, models/quantize.py::projection_dense)."""
        from learning_jax_sharding_tpu.models.quantize import projection_dense

        return projection_dense(
            quantization=self.quantization,
            features=features,
            kernel_axes=kernel_axes,
            use_bias=self.use_bias,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=self.kernel_init,
            group_size=self.quantization_group,
            quantized_matmul_fn=self.quantized_matmul_fn,
            name=name,
        )

    def _fused_qkv(self, m: int) -> bool:
        """Route q/k/v through one ``int4_matmul3`` launch: int4 serving,
        single-device (no TP shard_map injection), MHA (equal projection
        widths; GQA's narrower k/v keep per-projection calls), no biases,
        and a group layout the kernel can tile."""
        if (
            self.quantization != "int4"
            or self.quantized_matmul_fn is not None
            or self.use_bias
            or self.kv_heads != self.num_heads
            or m % 2
        ):
            return False
        g = min(self.quantization_group, m)
        return g == m or (m // 2) % g == 0

    def _proj(self, name: str, heads: int) -> nn.Module:
        # Kernel (M, heads*H) carries logical axes (EMBED, HEADS): under the
        # reference rules EMBED→model splits its rows
        # (`/root/reference/case6_attention.py:56-59`); under Megatron-style
        # rules HEADS→model splits its columns.
        return self._dense(heads * self.head_dim, (EMBED, HEADS), name)

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        deterministic: bool = True,
        chunk_lengths: Optional[jax.Array] = None,
    ) -> jax.Array:
        """``chunk_lengths``: ragged decode only — per-row count of VALID
        tokens in this chunk (prefill: the prompt lengths; a frozen row
        passes 0). Drives how far each row's cache index advances; the
        chunk's padded tail is still written but never attended (causal
        masks stop at each row's index)."""
        b, s, m = x.shape
        if chunk_lengths is not None and not self.decode_ragged:
            raise ValueError("chunk_lengths requires decode_ragged=True")
        x = nn.with_logical_constraint(x, (BATCH, SEQ, EMBED))

        if self._fused_qkv(m):
            # q/k/v in ONE kernel launch: at M = 8 decode the serial launch
            # chain, not bytes, is the int4 floor (PERF.md round 3) — the
            # three projections share x, so two dependent boundaries per
            # block vanish. Param layout matches Int4Dense verbatim
            # (quantized trees apply unchanged).
            from learning_jax_sharding_tpu.models.quantize import Int4ProjParams
            from learning_jax_sharding_tpu.ops.int4_matmul import int4_matmul3

            g = min(self.quantization_group, m)
            n_out = self.num_heads * self.head_dim
            pairs = [
                Int4ProjParams(m // 2, n_out, m // g, name=nm)()
                for nm in ("query", "key", "value")
            ]
            q, k, v = int4_matmul3(x.astype(self.dtype), pairs, group=g)
        else:
            q = self._proj("query", self.num_heads)(x)
            k = self._proj("key", self.kv_heads)(x)
            v = self._proj("value", self.kv_heads)(x)
        # Projections emerge (B, S, N*H); constrain before the head split
        # (the reference constrains the same three activations,
        # `case6_attention.py:105-116`, but names dim 1 'embed').
        q = nn.with_logical_constraint(q, (BATCH, SEQ, HEADS))
        k = nn.with_logical_constraint(k, (BATCH, SEQ, HEADS))
        v = nn.with_logical_constraint(v, (BATCH, SEQ, HEADS))

        q = q.reshape(b, s, self.num_heads, self.head_dim)
        k = k.reshape(b, s, self.kv_heads, self.head_dim)
        v = v.reshape(b, s, self.kv_heads, self.head_dim)
        q = nn.with_logical_constraint(q, (BATCH, SEQ, HEADS, KV))
        k = nn.with_logical_constraint(k, (BATCH, SEQ, HEADS, KV))
        v = nn.with_logical_constraint(v, (BATCH, SEQ, HEADS, KV))

        if self.rope:
            # Rotate BEFORE caching so cached keys carry their absolute
            # positions and chunked decode needs no re-rotation.
            if self.decode:
                # Read-only peek: _cached_attention owns (declares and
                # advances) this variable; during init it doesn't exist yet
                # and the chunk starts at position 0.
                idx = self.get_variable(
                    "cache", "cache_index",
                    jnp.zeros((b,) if self.decode_ragged else (), jnp.int32),
                )
                if self.decode_ragged:
                    positions = idx[:, None] + jnp.arange(s)   # (B, S)
                else:
                    positions = idx + jnp.arange(s)
            else:
                positions = jnp.arange(s)
            q = apply_rope(q, positions, self.rope_theta)
            k = apply_rope(k, positions, self.rope_theta)

        if self.decode:
            out = self._cached_attention(q, k, v, chunk_lengths)
        elif self.attn_fn is None:
            if self.window is not None:
                if not self.causal:
                    raise ValueError("window (sliding-window attention) requires causal=True")
                mask = sliding_window_mask(s, self.window)
            else:
                mask = causal_mask(s) if self.causal else None
            dense = functools.partial(_dense_attention, num_heads=self.num_heads)
            if self.remat_attention:
                dense = jax.checkpoint(
                    dense, policy=jax.checkpoint_policies.nothing_saveable
                )
            out = dense(q, k, v, mask)
        else:
            if self.window is not None:
                raise ValueError(
                    "window with a custom attn_fn: configure the backend "
                    "instead (e.g. make_flash_attn_fn(window=...))"
                )
            # Custom backends (flash/ring) take the structural flag, not a
            # dense mask — they cannot honor arbitrary masks and must not
            # silently reinterpret one. GQA-native backends (the flash
            # kernel) read k/v at N_kv heads directly — no repeat_kv
            # expansion materializes, which is GQA's bandwidth win.
            if getattr(self.attn_fn, "supports_gqa", False):
                out = self.attn_fn(q, k, v, causal=self.causal)
            else:
                out = self.attn_fn(
                    q, repeat_kv(k, self.num_heads),
                    repeat_kv(v, self.num_heads),
                    causal=self.causal,
                )
        out = nn.with_logical_constraint(out, (BATCH, SEQ, HEADS, KV))
        out = out.reshape(b, s, self.inner_dim)

        # Output projection (N*H, M) with logical (HEADS, EMBED)
        # (`case6_attention.py:83-90`).
        out = self._dense(self.features, (HEADS, EMBED), "out")(out)
        out = nn.with_logical_constraint(out, (BATCH, SEQ, EMBED))
        if self.dropout_rate > 0.0:
            out = nn.Dropout(rate=self.dropout_rate, deterministic=deterministic)(out)
        return out

    def _advance(self, cache_index, s: int, chunk_lengths) -> jax.Array:
        """Read the index, advance it by the chunk's VALID length — ``s``
        (rectangular), or per-row ``chunk_lengths`` (ragged: prefill passes
        prompt lengths, a frozen row passes 0 and stops consuming cache)."""
        idx = cache_index.value
        cache_index.value = idx + (s if chunk_lengths is None else chunk_lengths)
        return idx

    def _cached_attention(
        self, q: jax.Array, k: jax.Array, v: jax.Array, chunk_lengths=None
    ) -> jax.Array:
        """Autoregressive attention against an in-module KV cache.

        The cache (absent from the reference, which has no inference path —
        SURVEY.md §5) holds ``(B, max_decode_len, N, H)`` keys/values in
        Flax's ``"cache"`` collection plus a write index. Each call appends
        the chunk's k/v at the index and attends q against the full cache
        with positions past the chunk masked — so one code path serves both
        prompt prefill (S = prompt length) and single-token decode (S = 1).
        Shapes stay static (attention always spans the whole cache buffer):
        XLA compiles exactly two executables for the whole generate loop.

        ``decode_ragged``: the index is per-row ``(B,)`` — writes scatter
        each row's chunk at its own offset and the causal mask compares
        per-row positions, so mixed-length batches attend exactly their own
        valid prefixes (padded prefill rows produce garbage outputs that
        the caller discards by gathering logits at each row's length).
        """
        if self.attn_fn is not None:
            raise ValueError(
                "decode mode uses the cached paths (dense or blocked); "
                "attn_fn backends (flash/ring) are for training-length "
                "sequences"
            )
        if self.max_decode_len <= 0:
            raise ValueError("decode=True requires max_decode_len > 0")
        if resolve_decode_backend(self.decode_attention) == "blocked":
            return self._blocked_cached_attention(q, k, v, chunk_lengths)
        if self.decode_paged:
            raise ValueError(
                "decode_paged requires the blocked decode backend (the "
                "dense path attends per-row buffers, not page pools)"
            )
        b, s, n, h = q.shape
        n_kv = k.shape[2]  # GQA caches only the k/v heads — the GQA win
        ragged = self.decode_ragged
        length = self.max_decode_len
        store = self.kv_cache_dtype if self.kv_cache_dtype is not None else self.dtype
        quantized = store == jnp.int8

        cached_k = self.variable(
            "cache", "cached_key", jnp.zeros, (b, length, n_kv, h), store
        )
        cached_v = self.variable(
            "cache", "cached_value", jnp.zeros, (b, length, n_kv, h), store
        )
        cache_index = self.variable(
            "cache", "cache_index",
            lambda: jnp.zeros((b,) if ragged else (), jnp.int32),
        )
        if quantized:
            # Symmetric per-(token, kv-head) scales, written with the chunk.
            k_scale = self.variable(
                "cache", "key_scale", jnp.ones, (b, length, n_kv), jnp.float32
            )
            v_scale = self.variable(
                "cache", "value_scale", jnp.ones, (b, length, n_kv), jnp.float32
            )

        def ragged_write(buf, chunk, seq_dim):
            # Length-aware when per-row valid counts ride the call: rows
            # with 0 valid tokens (and clamped near-end windows) must not
            # disturb existing cache (see row_update_masked).
            if chunk_lengths is not None:
                return row_update_masked(
                    buf, chunk, idx, chunk_lengths, seq_dim=seq_dim
                )
            return row_update(buf, chunk, idx, seq_dim=seq_dim)

        def write(var, chunk, scale_var=None):
            if quantized:
                scale, chunk = quantize_kv_chunk(chunk)
                if ragged:
                    scale_var.value = ragged_write(scale_var.value, scale, 1)
                else:
                    scale_var.value = jax.lax.dynamic_update_slice(
                        scale_var.value, scale, (0, idx, 0)
                    )
            if ragged:
                var.value = ragged_write(var.value, chunk.astype(store), 1)
            else:
                var.value = jax.lax.dynamic_update_slice(
                    var.value, chunk.astype(store), (0, idx, 0, 0)
                )

        def read(var, scale_var=None):
            full = var.value
            if quantized:
                full = full.astype(jnp.float32) * scale_var.value[..., None]
            return repeat_kv(
                nn.with_logical_constraint(
                    full.astype(self.dtype), (BATCH, None, HEADS, KV)
                ),
                n,
            )

        idx = self._advance(cache_index, s, chunk_lengths)
        write(cached_k, k, k_scale if quantized else None)
        write(cached_v, v, v_scale if quantized else None)

        k_full = read(cached_k, k_scale if quantized else None)
        v_full = read(cached_v, v_scale if quantized else None)
        # Query i sits at absolute position idx + i: attend to every cache
        # slot at or before it (this also hides the zero-initialized tail).
        if ragged:
            q_pos = idx[:, None, None] + jnp.arange(s)[None, :, None]  # (B,S,1)
            k_pos = jnp.arange(length)[None, None, :]
        else:
            q_pos = idx + jnp.arange(s)[:, None]
            k_pos = jnp.arange(length)[None, :]
        mask = k_pos <= q_pos                          # (S, L) or (B, S, L)
        if self.window is not None:
            # SWA decode: attend only to the last `window` cache slots.
            mask = mask & (k_pos > q_pos - self.window)
        mask = mask[:, None] if ragged else mask[None, None]
        return dot_product_attention(q, k_full, v_full, mask=mask)

    def _blocked_cached_attention(
        self, q: jax.Array, k: jax.Array, v: jax.Array, chunk_lengths=None
    ) -> jax.Array:
        """Length-aware cached attention via the Pallas decode kernel.

        Same cache protocol as the dense path (append chunk at the index,
        attend against the valid prefix) but the cache lives sequence-major
        per head — ``(B, N_kv, L, H)`` — and attention runs through
        :func:`ops.decode_attention.decode_attention`: HBM traffic per step
        scales with the valid cache length instead of ``max_decode_len``,
        GQA caches are read at N_kv heads (no ``repeat_kv`` expansion), and
        int8 caches are dequantized only for the blocks actually read —
        the three decode costs the dense path pays in full every token.
        """
        from learning_jax_sharding_tpu.ops.decode_attention import decode_attention

        b, s, n, h = q.shape
        n_kv = k.shape[2]
        ragged = self.decode_ragged
        paged = self.decode_paged
        length = self.max_decode_len
        store = self.kv_cache_dtype if self.kv_cache_dtype is not None else self.dtype
        quantized = store == jnp.int8

        if paged:
            if not ragged:
                raise ValueError("decode_paged requires decode_ragged")
            page = self.decode_block_k
            if not page or length % page:
                raise ValueError(
                    f"decode_paged needs decode_block_k (page size) "
                    f"dividing max_decode_len ({length}); got {page}"
                )
            pool = self.decode_page_count
            kv_shape, sc_shape = (pool, n_kv, page, h), (pool, n_kv, page)
            block_table = self.variable(
                "cache", "block_table", jnp.zeros, (b, length // page),
                jnp.int32,
            )
        else:
            kv_shape, sc_shape = (b, n_kv, length, h), (b, n_kv, length)
        cached_k = self.variable(
            "cache", "cached_key", jnp.zeros, kv_shape, store
        )
        cached_v = self.variable(
            "cache", "cached_value", jnp.zeros, kv_shape, store
        )
        cache_index = self.variable(
            "cache", "cache_index",
            lambda: jnp.zeros((b,) if ragged else (), jnp.int32),
        )
        if quantized:
            k_scale = self.variable(
                "cache", "key_scale", jnp.ones, sc_shape, jnp.float32
            )
            v_scale = self.variable(
                "cache", "value_scale", jnp.ones, sc_shape, jnp.float32
            )

        idx = self._advance(cache_index, s, chunk_lengths)
        # Ragged single-token steps FOLD the write into the kernel: the new
        # k/v merge in-VMEM at each row's slot and flush back through cache
        # outputs aliased to the inputs — the per-row scatter (measured at
        # ~18 µs of serial launch per layer, PERF.md "Ragged serving") never
        # exists. Multi-token ragged chunks (prefill) still scatter — once
        # per generation, amortized.
        fold = ragged and s == 1

        def to_seq_major(chunk):
            if quantized:
                scale, chunk = quantize_kv_chunk(chunk)
                return (
                    chunk.astype(store).transpose(0, 2, 1, 3),
                    scale.transpose(0, 2, 1),
                )
            return chunk.astype(store).transpose(0, 2, 1, 3), None

        def ragged_write(buf, chunk):
            # Length-aware when per-row valid counts ride the call (see
            # row_update_masked: zero-length / clamped windows must be
            # no-ops on existing cache).
            if chunk_lengths is not None:
                return row_update_masked(
                    buf, chunk, idx, chunk_lengths, seq_dim=2
                )
            return row_update(buf, chunk, idx, seq_dim=2)

        def paged_write(pool_buf, chunk):
            # Scatter a sequence-major chunk through the block table: cache
            # position idx_b + t lives at (table[b, pos // page], pos %
            # page) in the pool. Invalid positions (padding past a row's
            # chunk_lengths) are redirected to the reserved scratch page 0,
            # so masked writes can never touch live pages.
            tbl = block_table.value
            pos = idx[:, None] + jnp.arange(s)[None, :]          # (B, S)
            t_cap = tbl.shape[1]
            pages = jnp.take_along_axis(
                tbl, jnp.minimum(pos // page, t_cap - 1), axis=1
            )
            slots = pos % page
            if chunk_lengths is not None:
                valid = jnp.arange(s)[None, :] < chunk_lengths[:, None]
            else:
                valid = pos < length
            pages = jnp.where(valid, pages, 0)
            # chunk (B, N_kv, S, ...) → (B, S, N_kv, ...): advanced indices
            # on pool axes 0 and 2 put the (B, S) index shape in front.
            upd = jnp.moveaxis(chunk, 2, 1)
            return pool_buf.at[pages, :, slots].set(upd)

        def write(var, chunk, scale_var=None):
            chunk, scale = to_seq_major(chunk)
            if paged:
                if quantized:
                    scale_var.value = paged_write(scale_var.value, scale)
                var.value = paged_write(var.value, chunk)
            elif ragged:
                if quantized:
                    scale_var.value = ragged_write(scale_var.value, scale)
                var.value = ragged_write(var.value, chunk)
            else:
                if quantized:
                    scale_var.value = jax.lax.dynamic_update_slice(
                        scale_var.value, scale, (0, 0, idx)
                    )
                var.value = jax.lax.dynamic_update_slice(
                    var.value, chunk, (0, 0, idx, 0)
                )

        fold_args = {}
        if fold:
            k_sm, ks_sm = to_seq_major(k)
            v_sm, vs_sm = to_seq_major(v)
            fold_args = dict(k_new=k_sm, v_new=v_sm)
            if quantized:
                fold_args.update(ks_new=ks_sm, vs_new=vs_sm)
            if chunk_lengths is not None:
                # Frozen rows (length 0) must not have their garbage token
                # merged into the cache — the kernel pushes their write
                # slot out of range and flushes the block unchanged.
                fold_args["write_enable"] = chunk_lengths
        else:
            write(cached_k, k, k_scale if quantized else None)
            write(cached_v, v, v_scale if quantized else None)

        # Paged pools lead with the PAGE axis (shared across rows), so only
        # the heads dim carries a sharding hint; per-row buffers shard
        # batch × heads as before.
        kv_axes = (None, HEADS, None, KV) if paged else (BATCH, HEADS, None, KV)
        sc_axes = kv_axes[:-1]
        kc = nn.with_logical_constraint(cached_k.value, kv_axes)
        vc = nn.with_logical_constraint(cached_v.value, kv_axes)
        scales = {}
        if quantized:
            scales = dict(
                k_scale=nn.with_logical_constraint(k_scale.value, sc_axes),
                v_scale=nn.with_logical_constraint(v_scale.value, sc_axes),
            )
        fn = self.decode_attn_fn if self.decode_attn_fn is not None else decode_attention
        table_args = {}
        if paged:
            table_args = dict(block_table=block_table.value)
        # window/block_k pass at CALL time either way: the module is the
        # single source of truth, so a mesh-aware wrapper built without them
        # cannot silently drop the sliding window.
        if fold:
            result = fn(
                q, kc, vc, idx,
                window=self.window, block_k=self.decode_block_k,
                **scales, **fold_args, **table_args,
            )
            out, new_k, new_v = result[:3]
            cached_k.value = new_k
            cached_v.value = new_v
            if quantized:
                k_scale.value, v_scale.value = result[3:]
            return out
        return fn(
            q, kc, vc, idx,
            window=self.window, block_k=self.decode_block_k,
            **scales, **table_args,
        )
