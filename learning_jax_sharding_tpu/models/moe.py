"""Mixture-of-Experts feed-forward with expert parallelism.

Expert parallelism is absent from the reference (SURVEY.md §2.4 "Expert
parallelism (EP/MoE): ❌"); this module adds it the TPU way:

* **Static shapes everywhere.** Routing uses the GShard/Switch capacity
  scheme: every expert processes exactly ``C`` token slots per step, chosen
  by position-in-expert cumsum; overflow tokens are dropped (their residual
  path carries them). No gather/scatter with data-dependent shapes — XLA
  sees three einsums it can tile onto the MXU.
* **Dispatch/combine as einsums.** ``dispatch (T,E,C)`` one-hot tensors
  route tokens to expert slots and back; under ``EXPERT→model`` rules GSPMD
  turns those einsums into the expert all-to-all over ICI.
* **Expert weights (E, M, H) / (E, H, M)** carry logical axes
  ``(EXPERT, EMBED, MLP)`` / ``(EXPERT, MLP, EMBED)`` — EP shards the E dim;
  a 3D mesh can additionally shard MLP for TP-within-expert.
* **fp32 router.** Gate logits/softmax stay fp32 regardless of compute dtype
  (the same stability reasoning as the reference's softmax upcast,
  `/root/reference/case6_attention.py:121-122`).

The load-balancing auxiliary loss (Switch Transformer eq. 4) is sown into the
``"losses"`` collection; ``training.pipeline.make_train_step(...,
aux_loss_collection="losses")`` adds it to the task loss.
"""

from __future__ import annotations

import math
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from learning_jax_sharding_tpu.parallel.logical import (
    BATCH,
    EMBED,
    EXPERT,
    MLP,
    SEQ,
)


def assign_slots(probs: jax.Array, top_k: int, capacity: int):
    """THE slot-assignment rule, shared by every dispatch implementation
    (einsum, scatter, all-to-all) so routing math cannot drift between
    them: top-k choices, rank-major GShard priority, int32 position
    cumsum, capacity drop, and surviving-gate renormalization.

    Returns ``(gate_vals, gate_idx, pos, fits, masks)`` for ``probs``
    of shape (T, E) — T is whatever token GROUP the caller routes over
    (the global batch for the single-group paths; one shard's tokens for
    the grouped all-to-all path, GShard's actual formulation)."""
    t, e = probs.shape
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)          # (T, k)
    masks = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)     # (T, k, E)
    # Rank-major priority: all rank-0 choices claim slots before any
    # rank-1 choice, matching GShard's dispatch order. Slot counting in
    # int32: fp32 cumsum would lose exactness past 2^24 slots per expert.
    flat = masks.transpose(1, 0, 2).reshape(top_k * t, e)      # (k·T, E)
    pos = jnp.cumsum(flat.astype(jnp.int32), axis=0) - flat.astype(jnp.int32)
    fits = flat * (pos < capacity)                             # drop overflow
    pos = pos.reshape(top_k, t, e).transpose(1, 0, 2)          # (T, k, E)
    fits = fits.reshape(top_k, t, e).transpose(1, 0, 2)        # (T, k, E)
    if top_k > 1:
        # Normalize the surviving gate weights per token (GShard).
        kept_vals = gate_vals * jnp.sum(masks * fits, axis=-1)  # (T, k)
        denom = jnp.maximum(jnp.sum(kept_vals, axis=-1, keepdims=True), 1e-9)
        gate_vals = kept_vals / denom
    else:
        gate_vals = gate_vals * jnp.sum(masks * fits, axis=-1)
    return gate_vals, gate_idx, pos, fits, masks


def scatter_slot_ids(pos, fits, masks, gate_idx, capacity, num_experts):
    """Each accepted (token, rank)'s flat slot id ``expert·C + position``
    (unique — ranks pick distinct experts); dropped entries target the
    dump slot ``E·C``. Shared by the scatter and all-to-all dispatches."""
    slot_pos = jnp.sum(pos * masks.astype(jnp.int32), axis=-1)   # (T, k)
    kept = jnp.sum(masks * fits, axis=-1) > 0                    # (T, k)
    return jnp.where(
        kept, gate_idx * capacity + slot_pos, num_experts * capacity
    ).reshape(-1)                                                # (T·k,)


def bucket_tokens(xf, flat_slot, num_experts, capacity, top_k, dtype):
    """Scatter tokens into the ``(E, C, M)`` slot pool by their flat slot
    ids (dump row absorbs capacity-dropped entries) — the movement half
    of the flop-free dispatch, shared by the scatter and all-to-all
    paths."""
    t, m = xf.shape
    token_of = jnp.repeat(jnp.arange(t), top_k)              # (T·k,)
    pool = jnp.zeros((num_experts * capacity + 1, m), dtype)
    pool = pool.at[flat_slot].set(xf.astype(dtype)[token_of])
    return pool[:-1].reshape(num_experts, capacity, m)


def combine_slots(expert_out, flat_slot, gate_vals, top_k, dtype):
    """Gather each (token, rank)'s slot output (dump slot reads zero) and
    fold the gate weights in one tiny contraction — gate_vals already
    carries the kept mask and normalization, exactly as the combine
    einsum's gating. Shared by the scatter and all-to-all paths."""
    e, c, m = expert_out.shape
    eflat = jnp.concatenate(
        [expert_out.reshape(e * c, m), jnp.zeros((1, m), expert_out.dtype)]
    )
    per_rank = eflat[flat_slot].reshape(gate_vals.shape[0], top_k, m)
    return jnp.einsum("tkm,tk->tm", per_rank, gate_vals.astype(dtype))


class MoEFeedForward(nn.Module):
    """Top-k routed expert FFN, drop-in for the dense ``FeedForward``.

    Attributes:
        features: residual-stream width M.
        hidden: per-expert FF hidden width H.
        num_experts: expert count E.
        top_k: experts per token (1 = Switch, 2 = GShard-style).
        capacity_factor: slack over the even-load capacity; each expert gets
            ``C = ceil(top_k · T · capacity_factor / E)`` slots for the
            ``T = B·S`` tokens of the step.
        aux_loss_weight: coefficient on the sown load-balancing loss.
        router_noise: stddev of multiplicative jitter on router logits during
            training (0 disables; Switch uses 1e-2).
    """

    features: int
    hidden: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    router_noise: float = 0.0
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    dispatch: str = "einsum"
    dispatch_fn: Callable | None = None
    # Token routing implementation — identical math, different cost model:
    # "einsum" builds (T, E, C) one-hot dispatch/combine tensors whose
    #   contractions cost O(E·C·M·T) MXU FLOPs (≈40% of MoE step time at
    #   E=8 top-2, PERF.md round 3) but shard cleanly under EXPERT→model
    #   rules (GSPMD lowers them to the expert all-to-all) — the
    #   zero-configuration multi-device EP path;
    # "scatter" computes each (token, rank)'s slot index directly from the
    #   shared cumsum (expert·C + position-in-expert) and moves rows by
    #   .at[].set scatter / gather — O(k·T·M) bytes, no routing FLOPs.
    #   Slot assignment is bit-identical to the einsum path (same cumsum,
    #   same GShard rank-major priority). Single-device oriented:
    #   data-dependent gathers don't partition over EXPERT.
    # "alltoall" (dispatch_fn = ops.moe_dispatch.make_moe_a2a_fn(mesh)):
    #   the EXPLICIT expert-parallel path — scatter's flop-free bucketing
    #   per TOKEN SHARD, then lax.all_to_all over the expert mesh axis
    #   each way (GShard's grouped formulation: capacity per token group,
    #   not global — see make_moe_a2a_fn). Deletes the one-hot FLOPs the
    #   einsum EP path still pays AND partitions over EXPERT.

    @nn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        if not 1 <= self.top_k <= self.num_experts:
            raise ValueError(f"top_k={self.top_k} not in [1, {self.num_experts}]")
        b, s, m = x.shape
        e = self.num_experts
        t = b * s
        capacity = min(t, max(1, math.ceil(self.top_k * t * self.capacity_factor / e)))

        x = nn.with_logical_constraint(x, (BATCH, SEQ, EMBED))

        # --- Router (fp32) -------------------------------------------------
        router = nn.Dense(
            e,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(self.kernel_init, (EMBED, EXPERT)),
            name="router",
        )
        logits = router(x.astype(jnp.float32)).reshape(t, e)
        if self.router_noise > 0.0 and not deterministic:
            key = self.make_rng("dropout")
            logits = logits * jax.random.uniform(
                key, logits.shape, jnp.float32,
                1.0 - self.router_noise, 1.0 + self.router_noise,
            )
        probs = jax.nn.softmax(logits, axis=-1)                    # (T, E)

        # --- Load-balancing aux loss + the expert weights (shared by all
        # dispatch paths; the all-to-all path routes inside dispatch_fn).
        w_up = self.param(
            "up",
            nn.with_logical_partitioning(self.kernel_init, (EXPERT, EMBED, MLP)),
            (e, m, self.hidden),
            self.param_dtype,
        )
        w_down = self.param(
            "down",
            nn.with_logical_partitioning(self.kernel_init, (EXPERT, MLP, EMBED)),
            (e, self.hidden, m),
            self.param_dtype,
        )

        def sow_aux(probs, masks0):
            load = jnp.mean(masks0, axis=0)                         # (E,)
            importance = jnp.mean(probs, axis=0)                    # (E,)
            self.sow(
                "losses",
                "load_balancing",
                self.aux_loss_weight * e * jnp.sum(load * importance),
                reduce_fn=lambda a, b: a + b,
                init_fn=lambda: jnp.zeros((), jnp.float32),
            )

        if self.dispatch == "alltoall":
            if self.dispatch_fn is None:
                raise ValueError(
                    "dispatch='alltoall' needs dispatch_fn — build one with "
                    "ops.moe_dispatch.make_moe_a2a_fn(mesh)"
                )
            sow_aux(
                probs, jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=probs.dtype)
            )
            out = self.dispatch_fn(
                x.reshape(t, m), probs, w_up, w_down,
                top_k=self.top_k, capacity_factor=self.capacity_factor,
                dtype=self.dtype,
            )
            out = out.reshape(b, s, m)
            return nn.with_logical_constraint(out, (BATCH, SEQ, EMBED))

        # --- Top-k assignment with capacity (ONE global group) -------------
        gate_vals, gate_idx, pos, fits, masks = assign_slots(
            probs, self.top_k, capacity
        )

        if self.dispatch == "einsum":
            slot = jax.nn.one_hot(
                jnp.sum(pos * masks.astype(jnp.int32), axis=-1), capacity,
                dtype=jnp.float32,
            )                                                       # (T, k, C)
            # (T,k,E) × (T,k,C) → (T,E,C): one-hot routing tensors.
            dispatch = jnp.einsum("tke,tkc->tec", fits, slot)
            combine = jnp.einsum("tke,tkc,tk->tec", fits, slot, gate_vals)
        elif self.dispatch == "scatter":
            # Same priority/capacity assignment, but tokens MOVE by
            # scatter/gather instead of (T,E,C) contractions: each
            # accepted (token, rank) owns slot expert·C + position
            # (unique — ranks pick distinct experts); dropped entries
            # target a dump slot past the pool. The expensive part of the
            # einsum path was never the int cumsum above — it is the
            # O(E·C·M·T) dispatch/combine MXU work this branch deletes.
            flat_slot = scatter_slot_ids(
                pos, fits, masks, gate_idx, capacity, e
            )
        else:
            raise ValueError(
                f"unknown dispatch {self.dispatch!r}: 'einsum', 'scatter', "
                f"or 'alltoall'"
            )

        # --- Load-balancing aux loss (Switch eq. 4, on rank-0 choices) -----
        sow_aux(probs, masks[:, 0])

        # --- Expert computation --------------------------------------------
        xf = x.reshape(t, m)
        if self.dispatch == "scatter":
            expert_in = bucket_tokens(
                xf, flat_slot, e, capacity, self.top_k, self.dtype
            )
        else:
            expert_in = jnp.einsum(
                "tec,tm->ecm", dispatch.astype(self.dtype), xf.astype(self.dtype)
            )
        expert_in = nn.with_logical_constraint(expert_in, (EXPERT, None, EMBED))

        h = jnp.einsum("ecm,emh->ech", expert_in, w_up.astype(self.dtype))
        h = nn.with_logical_constraint(h, (EXPERT, None, MLP))
        h = nn.gelu(h)
        expert_out = jnp.einsum("ech,ehm->ecm", h, w_down.astype(self.dtype))
        expert_out = nn.with_logical_constraint(expert_out, (EXPERT, None, EMBED))

        if self.dispatch == "scatter":
            out = combine_slots(
                expert_out, flat_slot, gate_vals, self.top_k, self.dtype
            )
        else:
            out = jnp.einsum(
                "tec,ecm->tm", combine.astype(self.dtype), expert_out
            )
        out = out.reshape(b, s, m)
        return nn.with_logical_constraint(out, (BATCH, SEQ, EMBED))
