"""Mixture-of-Experts feed-forward with expert parallelism.

Expert parallelism is absent from the reference (SURVEY.md §2.4 "Expert
parallelism (EP/MoE): ❌"); this module adds it the TPU way:

* **Static shapes everywhere.** Routing uses the GShard/Switch capacity
  scheme: every expert processes exactly ``C`` token slots per step, chosen
  by position-in-expert cumsum; overflow tokens are dropped (their residual
  path carries them). No gather/scatter with data-dependent shapes — XLA
  sees three einsums it can tile onto the MXU.
* **Dispatch/combine as einsums.** ``dispatch (T,E,C)`` one-hot tensors
  route tokens to expert slots and back; under ``EXPERT→model`` rules GSPMD
  turns those einsums into the expert all-to-all over ICI.
* **Expert weights (E, M, H) / (E, H, M)** carry logical axes
  ``(EXPERT, EMBED, MLP)`` / ``(EXPERT, MLP, EMBED)`` — EP shards the E dim;
  a 3D mesh can additionally shard MLP for TP-within-expert.
* **fp32 router.** Gate logits/softmax stay fp32 regardless of compute dtype
  (the same stability reasoning as the reference's softmax upcast,
  `/root/reference/case6_attention.py:121-122`).

The load-balancing auxiliary loss (Switch Transformer eq. 4) is sown into the
``"losses"`` collection; ``training.pipeline.make_train_step(...,
aux_loss_collection="losses")`` adds it to the task loss.
"""

from __future__ import annotations

import math
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from learning_jax_sharding_tpu.parallel.logical import (
    BATCH,
    EMBED,
    EXPERT,
    MLP,
    SEQ,
)


class MoEFeedForward(nn.Module):
    """Top-k routed expert FFN, drop-in for the dense ``FeedForward``.

    Attributes:
        features: residual-stream width M.
        hidden: per-expert FF hidden width H.
        num_experts: expert count E.
        top_k: experts per token (1 = Switch, 2 = GShard-style).
        capacity_factor: slack over the even-load capacity; each expert gets
            ``C = ceil(top_k · T · capacity_factor / E)`` slots for the
            ``T = B·S`` tokens of the step.
        aux_loss_weight: coefficient on the sown load-balancing loss.
        router_noise: stddev of multiplicative jitter on router logits during
            training (0 disables; Switch uses 1e-2).
    """

    features: int
    hidden: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    router_noise: float = 0.0
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    dispatch: str = "einsum"
    # Token routing implementation — identical math, different cost model:
    # "einsum" builds (T, E, C) one-hot dispatch/combine tensors whose
    #   contractions cost O(E·C·M·T) MXU FLOPs (≈40% of MoE step time at
    #   E=8 top-2, PERF.md round 3) but shard cleanly under EXPERT→model
    #   rules (GSPMD lowers them to the expert all-to-all) — the
    #   multi-device EP path;
    # "scatter" computes each (token, rank)'s slot index directly from the
    #   shared cumsum (expert·C + position-in-expert) and moves rows by
    #   .at[].set scatter / gather — O(k·T·M) bytes, no routing FLOPs.
    #   Slot assignment is bit-identical to the einsum path (same cumsum,
    #   same GShard rank-major priority). Single-device oriented:
    #   data-dependent gathers don't partition over EXPERT.

    @nn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        if not 1 <= self.top_k <= self.num_experts:
            raise ValueError(f"top_k={self.top_k} not in [1, {self.num_experts}]")
        b, s, m = x.shape
        e = self.num_experts
        t = b * s
        capacity = min(t, max(1, math.ceil(self.top_k * t * self.capacity_factor / e)))

        x = nn.with_logical_constraint(x, (BATCH, SEQ, EMBED))

        # --- Router (fp32) -------------------------------------------------
        router = nn.Dense(
            e,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(self.kernel_init, (EMBED, EXPERT)),
            name="router",
        )
        logits = router(x.astype(jnp.float32)).reshape(t, e)
        if self.router_noise > 0.0 and not deterministic:
            key = self.make_rng("dropout")
            logits = logits * jax.random.uniform(
                key, logits.shape, jnp.float32,
                1.0 - self.router_noise, 1.0 + self.router_noise,
            )
        probs = jax.nn.softmax(logits, axis=-1)                    # (T, E)

        # --- Top-k assignment with capacity --------------------------------
        gate_vals, gate_idx = jax.lax.top_k(probs, self.top_k)     # (T, k)
        masks = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)     # (T, k, E)
        # Rank-major priority: all rank-0 choices claim slots before any
        # rank-1 choice, matching GShard's dispatch order. Slot counting in
        # int32: fp32 cumsum would lose exactness past 2^24 slots per expert.
        flat = masks.transpose(1, 0, 2).reshape(self.top_k * t, e)  # (k·T, E)
        pos = jnp.cumsum(flat.astype(jnp.int32), axis=0) - flat.astype(jnp.int32)
        fits = flat * (pos < capacity)                              # drop overflow
        pos = pos.reshape(self.top_k, t, e).transpose(1, 0, 2)      # (T, k, E)
        fits = fits.reshape(self.top_k, t, e).transpose(1, 0, 2)    # (T, k, E)

        if self.top_k > 1:
            # Normalize the surviving gate weights per token (GShard).
            kept_vals = gate_vals * jnp.sum(masks * fits, axis=-1)  # (T, k)
            denom = jnp.maximum(jnp.sum(kept_vals, axis=-1, keepdims=True), 1e-9)
            gate_vals = kept_vals / denom
        else:
            gate_vals = gate_vals * jnp.sum(masks * fits, axis=-1)

        if self.dispatch == "einsum":
            slot = jax.nn.one_hot(
                jnp.sum(pos * masks.astype(jnp.int32), axis=-1), capacity,
                dtype=jnp.float32,
            )                                                       # (T, k, C)
            # (T,k,E) × (T,k,C) → (T,E,C): one-hot routing tensors.
            dispatch = jnp.einsum("tke,tkc->tec", fits, slot)
            combine = jnp.einsum("tke,tkc,tk->tec", fits, slot, gate_vals)
        elif self.dispatch == "scatter":
            # Same priority/capacity assignment, but tokens MOVE by
            # scatter/gather instead of (T,E,C) contractions: each
            # accepted (token, rank) owns slot expert·C + position
            # (unique — ranks pick distinct experts); dropped entries
            # target a dump slot past the pool. The expensive part of the
            # einsum path was never the int cumsum above — it is the
            # O(E·C·M·T) dispatch/combine MXU work this branch deletes.
            slot_pos = jnp.sum(pos * masks.astype(jnp.int32), axis=-1)  # (T,k)
            kept = jnp.sum(masks * fits, axis=-1) > 0                    # (T,k)
            flat_slot = jnp.where(
                kept, gate_idx * capacity + slot_pos, e * capacity
            ).reshape(-1)                                                # (T·k,)
        else:
            raise ValueError(
                f"unknown dispatch {self.dispatch!r}: 'einsum' or 'scatter'"
            )

        # --- Load-balancing aux loss (Switch eq. 4, on rank-0 choices) -----
        load = jnp.mean(masks[:, 0], axis=0)                        # (E,)
        importance = jnp.mean(probs, axis=0)                        # (E,)
        self.sow(
            "losses",
            "load_balancing",
            self.aux_loss_weight * e * jnp.sum(load * importance),
            reduce_fn=lambda a, b: a + b,
            init_fn=lambda: jnp.zeros((), jnp.float32),
        )

        # --- Expert computation --------------------------------------------
        xf = x.reshape(t, m)
        if self.dispatch == "scatter":
            token_of = jnp.repeat(jnp.arange(t), self.top_k)         # (T·k,)
            pool = jnp.zeros((e * capacity + 1, m), self.dtype)
            pool = pool.at[flat_slot].set(xf.astype(self.dtype)[token_of])
            expert_in = pool[:-1].reshape(e, capacity, m)
        else:
            expert_in = jnp.einsum(
                "tec,tm->ecm", dispatch.astype(self.dtype), xf.astype(self.dtype)
            )
        expert_in = nn.with_logical_constraint(expert_in, (EXPERT, None, EMBED))

        w_up = self.param(
            "up",
            nn.with_logical_partitioning(self.kernel_init, (EXPERT, EMBED, MLP)),
            (e, m, self.hidden),
            self.param_dtype,
        )
        w_down = self.param(
            "down",
            nn.with_logical_partitioning(self.kernel_init, (EXPERT, MLP, EMBED)),
            (e, self.hidden, m),
            self.param_dtype,
        )
        h = jnp.einsum("ecm,emh->ech", expert_in, w_up.astype(self.dtype))
        h = nn.with_logical_constraint(h, (EXPERT, None, MLP))
        h = nn.gelu(h)
        expert_out = jnp.einsum("ech,ehm->ecm", h, w_down.astype(self.dtype))
        expert_out = nn.with_logical_constraint(expert_out, (EXPERT, None, EMBED))

        if self.dispatch == "scatter":
            # Each (token, rank) gathers its slot's output (dump slot reads
            # zero) and the gate weights fold in one tiny contraction —
            # gate_vals already carries the kept mask and normalization,
            # exactly as the combine einsum's gating.
            eflat = jnp.concatenate(
                [
                    expert_out.reshape(e * capacity, m),
                    jnp.zeros((1, m), expert_out.dtype),
                ]
            )
            per_rank = eflat[flat_slot].reshape(t, self.top_k, m)
            out = jnp.einsum(
                "tkm,tk->tm", per_rank, gate_vals.astype(self.dtype)
            )
        else:
            out = jnp.einsum(
                "tec,ecm->tm", combine.astype(self.dtype), expert_out
            )
        out = out.reshape(b, s, m)
        return nn.with_logical_constraint(out, (BATCH, SEQ, EMBED))
