"""Weight-only int8 quantization for serving.

Decode is memory-bandwidth-bound at scale: every generated token re-reads the
full weight set from HBM, so bytes-per-weight is the fit (and often the
throughput) currency. This module stores every matmul kernel — 2D ``kernel``
leaves and the 3D MoE expert stacks — as int8 with a per-output-channel fp32
scale: symmetric, zero-point-free (dequant is one convert + one broadcast
multiply), halving weight bytes vs bf16 and quartering vs fp32 at ≤0.4%
per-channel relative error. The STORAGE saving is unconditional; the decode
bandwidth effect depends on XLA fusing the upcast into the consuming matmul
rather than materializing bf16 weights per step — measure with ``bench.py``'s
int8 decode context before claiming a speedup at a new shape.

The reference has no inference path at all (SURVEY.md §5 — its ``apply_fn``
exists only for timing, `/root/reference/case6_attention.py:229-238`); this
extends the framework's own generation stack (``models/generate.py``).

Quantization is offline and eager (``quantize_tree``); dequantization happens
INSIDE the jitted program (``make_generate_fn(..., dequantize=True)`` routes
through :func:`dequantize_tree`), so HBM holds and streams int8 and the
upcast happens on-chip. Sharding is preserved: ``q`` inherits the kernel's
NamedSharding, the scale vector its column spec, so tensor-parallel serving
is unchanged.

Embeddings, norms, and biases stay in full precision (a few % of weight
bytes; quantizing the embedding table measurably hurts output quality for
negligible savings).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

Path = tuple[str, ...]


def default_match(path: Path, leaf: Any) -> bool:
    """Quantize every 2D ``kernel`` (q/k/v/out, FF up/down, lm_head) and the
    3D MoE expert stacks (``moe/up``, ``moe/down`` — the dominant params of
    an MoE config). The MoE ``router`` kernel is excluded: routing is fp32
    on purpose (`models/moe.py`), and a quantization-flipped top-k there
    reroutes whole tokens — a far larger perturbation than the ≤0.4%
    per-channel error everywhere else."""
    if len(path) >= 2 and path[-2] == "router":
        return False
    if path[-1] == "kernel" and getattr(leaf, "ndim", 0) == 2:
        return True
    return path[-1] in ("up", "down") and getattr(leaf, "ndim", 0) == 3


def _is_quantized(node: Any) -> bool:
    return isinstance(node, dict) and set(node) == {"q", "scale"}


def quantize_leaf(w: jax.Array) -> dict[str, jax.Array]:
    """(..., in, out) kernel → {"q": int8 same shape, "scale": fp32 (..., out)}.

    Symmetric per-output-channel: scale = max|W|/127 over the contraction
    (second-to-last) dim, so dequant error per element is ≤ scale/2 (≈0.4% of
    the channel's max). Leading dims (the MoE expert dim) keep their own
    scales per channel.
    """
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale[..., None, :]), -127, 127
    ).astype(jnp.int8)
    return {"q": q, "scale": scale}


def dequantize_leaf(node: dict[str, jax.Array], dtype: Any = jnp.bfloat16) -> jax.Array:
    return (node["q"].astype(jnp.float32) * node["scale"][..., None, :]).astype(dtype)


def quantize_tree(
    params: Any,
    *,
    match: Callable[[Path, Any], bool] = default_match,
) -> Any:
    """Replace matched kernels with ``{"q", "scale"}`` nodes; rest untouched.

    Eager/offline — run once after training (or checkpoint load). Sharded
    inputs stay sharded: the reduction and rounding follow the kernel's own
    placement, and ``q`` lands with the kernel's sharding.
    """

    def walk(node: Any, prefix: Path) -> Any:
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            path = prefix + (k,)
            if not isinstance(v, dict) and match(path, v):
                out[k] = quantize_leaf(v)
                # Pin the shardings explicitly: q like the kernel, the scale
                # like the kernel's columns (eager propagation already does
                # this for NamedSharding inputs; device_put makes it a
                # guarantee rather than a propagation detail).
                if isinstance(v.sharding, NamedSharding):
                    spec = tuple(v.sharding.spec) + (None,) * (v.ndim - len(v.sharding.spec))
                    # The scale drops the contraction (-2) dim of the kernel.
                    scale_spec = spec[:-2] + (spec[-1],)
                    out[k] = {
                        "q": jax.device_put(out[k]["q"], v.sharding),
                        "scale": jax.device_put(
                            out[k]["scale"],
                            NamedSharding(v.sharding.mesh, PartitionSpec(*scale_spec)),
                        ),
                    }
            else:
                out[k] = walk(v, path)
        return out

    return walk(params, ())


def dequantize_tree(params: Any, dtype: Any = jnp.bfloat16) -> Any:
    """Inverse of :func:`quantize_tree`; traceable — call it inside jit so
    the int8→dtype upcast happens on-chip, next to the consuming matmul."""

    def walk(node: Any) -> Any:
        if not isinstance(node, dict):
            return node
        if _is_quantized(node):
            return dequantize_leaf(node, dtype)
        return {k: walk(v) for k, v in node.items()}

    return walk(params)


def map_unquantized(fn: Callable[[Any], Any], tree: Any) -> Any:
    """Map ``fn`` over every leaf that is NOT part of a quantized node,
    passing ``{"q","scale"}`` nodes through untouched — the traversal every
    consumer of a partially quantized tree needs (e.g. casting embeddings/
    norms while keeping int8 kernels)."""

    def walk(node: Any) -> Any:
        if _is_quantized(node):
            return node
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return fn(node)

    return walk(tree)


def quantized_bytes(params: Any) -> int:
    """Total serving bytes of a (possibly partially) quantized tree."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
