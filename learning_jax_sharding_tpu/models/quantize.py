"""Weight-only int8 / int4 quantization for serving.

Decode is memory-bandwidth-bound at scale: every generated token re-reads the
full weight set from HBM, so bytes-per-weight is the fit (and often the
throughput) currency. This module stores every matmul kernel — 2D ``kernel``
leaves and the 3D MoE expert stacks — quantized symmetric and
zero-point-free:

* **int8** (default): per-output-channel fp32 scale; half of bf16, ≤0.4%
  per-channel error, dequant is one convert + one broadcast multiply.
* **int4** (``bits=4``): two weights packed per byte (offset-binary nibbles)
  with GROUP-WISE scales every ``group_size`` contraction rows (the
  GPTQ/AWQ convention — pure per-channel scales lose too much at 4 bits);
  a quarter of bf16.

The STORAGE saving is unconditional; the decode bandwidth effect depends on
XLA fusing the upcast into the consuming matmul rather than materializing
bf16 weights per step — measure with ``bench.py``'s int8 decode context
before claiming a speedup at a new shape.

The reference has no inference path at all (SURVEY.md §5 — its ``apply_fn``
exists only for timing, `/root/reference/case6_attention.py:229-238`); this
extends the framework's own generation stack (``models/generate.py``).

Quantization is offline and eager (``quantize_tree``); dequantization happens
INSIDE the jitted program (``make_generate_fn(..., dequantize=True)`` routes
through :func:`dequantize_tree`), so HBM holds and streams int8 and the
upcast happens on-chip. Sharding is preserved: ``q`` inherits the kernel's
NamedSharding, the scale vector its column spec, so tensor-parallel serving
is unchanged.

Embeddings, norms, and biases stay in full precision (a few % of weight
bytes; quantizing the embedding table measurably hurts output quality for
negligible savings).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

Path = tuple[str, ...]


def default_match(path: Path, leaf: Any) -> bool:
    """Quantize every 2D ``kernel`` (q/k/v/out, FF up/down, lm_head) and the
    3D MoE expert stacks (``moe/up``, ``moe/down`` — the dominant params of
    an MoE config). The MoE ``router`` kernel is excluded: routing is fp32
    on purpose (`models/moe.py`), and a quantization-flipped top-k there
    reroutes whole tokens — a far larger perturbation than the ≤0.4%
    per-channel error everywhere else."""
    if len(path) >= 2 and path[-2] == "router":
        return False
    if path[-1] == "kernel" and getattr(leaf, "ndim", 0) == 2:
        return True
    return path[-1] in ("up", "down") and getattr(leaf, "ndim", 0) == 3


def _is_quantized(node: Any) -> bool:
    return isinstance(node, dict) and set(node) in ({"q", "scale"}, {"q4", "scale"})


def quantize_leaf(w: jax.Array) -> dict[str, jax.Array]:
    """(..., in, out) kernel → {"q": int8 same shape, "scale": fp32 (..., out)}.

    Symmetric per-output-channel: scale = max|W|/127 over the contraction
    (second-to-last) dim, so dequant error per element is ≤ scale/2 (≈0.4% of
    the channel's max). Leading dims (the MoE expert dim) keep their own
    scales per channel.
    """
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale[..., None, :]), -127, 127
    ).astype(jnp.int8)
    return {"q": q, "scale": scale}


def dequantize_leaf(node: dict[str, jax.Array], dtype: Any = jnp.bfloat16) -> jax.Array:
    return (node["q"].astype(jnp.float32) * node["scale"][..., None, :]).astype(dtype)


def quantize_leaf_int4(w: jax.Array, group_size: int = 128) -> dict[str, jax.Array]:
    """(..., in, out) kernel → {"q4": uint8 (..., in/2, out), "scale": fp32
    (..., in/g, out)}.

    Symmetric 4-bit with GROUP-WISE scales: per-channel absmax over groups of
    ``group_size`` contraction rows (the GPTQ/AWQ convention — per-channel
    scales alone lose too much at 4 bits), values in [-7, 7], two rows packed
    per byte as offset-binary nibbles in split-half order (see below).
    Quarter the bytes of bf16; error ≤ group_scale/2 per element.
    """
    if group_size < 1:
        raise ValueError(f"group_size must be >= 1, got {group_size}")
    rows = w.shape[-2]
    g = min(group_size, rows)
    if rows % 2:
        raise ValueError(f"int4 packing needs an even contraction dim, got {rows}")
    if rows % g:
        raise ValueError(
            f"contraction dim {rows} not divisible by group_size {g}"
        )
    wf = w.astype(jnp.float32)
    grouped = wf.reshape(*w.shape[:-2], rows // g, g, w.shape[-1])
    absmax = jnp.max(jnp.abs(grouped), axis=-2)            # (..., in/g, out)
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    q = jnp.clip(jnp.round(grouped / scale[..., :, None, :]), -7, 7)
    q = q.reshape(*w.shape[:-2], rows, w.shape[-1]).astype(jnp.int32)
    # Split-half packing: low nibbles hold rows [0, in/2), high nibbles rows
    # [in/2, in) — dequant then rebuilds the kernel with ONE concatenate
    # instead of an even/odd interleave (which cost 3x decode throughput
    # when measured as a per-step reshuffle on the v5e).
    low = q[..., : rows // 2, :] + 8                        # [1, 15]
    high = q[..., rows // 2 :, :] + 8
    packed = (low | (high << 4)).astype(jnp.uint8)
    return {"q4": packed, "scale": scale}


def dequantize_leaf_int4(
    node: dict[str, jax.Array], dtype: Any = jnp.bfloat16
) -> jax.Array:
    """Unpack nibbles, interleave rows back, apply group scales. Traceable —
    runs inside jit so HBM streams the packed bytes."""
    p, scale = node["q4"], node["scale"]
    # Same-width nibble math (uint8→int8 is a free bitcast-level convert),
    # then one concatenate rebuilds the row order of split-half packing.
    low = (p & 0xF).astype(jnp.int8) - 8
    high = (p >> 4).astype(jnp.int8) - 8
    rows = p.shape[-2] * 2
    q = jnp.concatenate([low, high], axis=-2)               # (..., in, out)
    groups = scale.shape[-2]
    qg = q.reshape(*p.shape[:-2], groups, rows // groups, p.shape[-1])
    w = qg.astype(jnp.float32) * scale[..., :, None, :]
    return w.reshape(*p.shape[:-2], rows, p.shape[-1]).astype(dtype)


def quantize_tree(
    params: Any,
    *,
    match: Callable[[Path, Any], bool] = default_match,
    bits: int = 8,
    group_size: int = 128,
) -> Any:
    """Replace matched kernels with ``{"q", "scale"}`` (int8) or
    ``{"q4", "scale"}`` (int4, ``bits=4``) nodes; rest untouched.

    Eager/offline — run once after training (or checkpoint load). Sharded
    inputs stay sharded: the reduction and rounding follow the kernel's own
    placement, and the packed weights land with the kernel's sharding.
    ``group_size`` applies to int4 only (contraction rows per scale group).
    """
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")

    def walk(node: Any, prefix: Path) -> Any:
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            path = prefix + (k,)
            if not isinstance(v, dict) and match(path, v):
                if bits == 8:
                    out[k] = quantize_leaf(v)
                else:
                    out[k] = quantize_leaf_int4(v, group_size)
                # Pin the shardings explicitly: packed weights like the
                # kernel (specs name dims, not sizes, so the halved int4 row
                # dim keeps the same spec), the scale like the kernel's
                # columns with the group dim unsharded (eager propagation
                # already does this for NamedSharding inputs; device_put
                # makes it a guarantee rather than a propagation detail).
                if isinstance(v.sharding, NamedSharding):
                    spec = tuple(v.sharding.spec) + (None,) * (v.ndim - len(v.sharding.spec))
                    if bits == 8:
                        # The scale drops the contraction (-2) dim.
                        scale_spec = spec[:-2] + (spec[-1],)
                        q_sharding = v.sharding
                    else:
                        scale_spec = spec[:-2] + (None, spec[-1])
                        # Split-half packing folds row i with row i + n/2 into
                        # one int8 byte: a q4 row no longer IS a kernel row,
                        # so sharding the halved contraction dim would both
                        # risk a divisibility failure (rows/2 % axis) and put
                        # mismatched halves on each device — forcing a
                        # reshard at every in-jit dequant. Keep that dim
                        # unsharded (like the scale's group dim).
                        q_sharding = NamedSharding(
                            v.sharding.mesh,
                            PartitionSpec(*spec[:-2], None, spec[-1]),
                        )
                    (qk,) = set(out[k]) - {"scale"}
                    out[k] = {
                        qk: jax.device_put(out[k][qk], q_sharding),
                        "scale": jax.device_put(
                            out[k]["scale"],
                            NamedSharding(v.sharding.mesh, PartitionSpec(*scale_spec)),
                        ),
                    }
            else:
                out[k] = walk(v, path)
        return out

    return walk(params, ())


def dequantize_tree(params: Any, dtype: Any = jnp.bfloat16) -> Any:
    """Inverse of :func:`quantize_tree`; traceable — call it inside jit so
    the int8→dtype upcast happens on-chip, next to the consuming matmul."""

    def walk(node: Any) -> Any:
        if not isinstance(node, dict):
            return node
        if _is_quantized(node):
            if "q4" in node:
                return dequantize_leaf_int4(node, dtype)
            return dequantize_leaf(node, dtype)
        return {k: walk(v) for k, v in node.items()}

    return walk(params)


def map_unquantized(fn: Callable[[Any], Any], tree: Any) -> Any:
    """Map ``fn`` over every leaf that is NOT part of a quantized node,
    passing ``{"q","scale"}`` nodes through untouched — the traversal every
    consumer of a partially quantized tree needs (e.g. casting embeddings/
    norms while keeping int8 kernels)."""

    def walk(node: Any) -> Any:
        if _is_quantized(node):
            return node
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return fn(node)

    return walk(tree)


def quantized_bytes(params: Any) -> int:
    """Total serving bytes of a (possibly partially) quantized tree."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))


class Int4Dense(nn.Module):
    """Drop-in for ``nn.Dense`` over an int4-quantized kernel, computed by
    the FUSED dequant-matmul Pallas kernel (``ops/int4_matmul.py``) — the
    packed nibbles stream straight into the dot, with no dequantized weight
    array ever touching HBM.

    Parameter layout matches :func:`quantize_tree` ``bits=4`` output
    exactly: a child scope named ``"kernel"`` holding ``q4`` (uint8,
    ``(K/2, N)``, split-half packed) and ``scale`` (fp32, ``(K/group, N)``)
    — so a quantized tree applies VERBATIM, no key surgery. Constructed by
    the transformer when ``TransformerConfig(quantization="int4")``; init
    creates zero placeholders (real weights always come from
    ``quantize_tree``).

    Layouts the kernel cannot tile (odd group count — split-half packing
    needs ``group | K/2``) fall back to ``dequantize_leaf_int4`` + XLA
    matmul, trading the fusion win for generality.

    Multi-device serving: GSPMD cannot partition the pallas custom call, so
    ``make_generate_fn`` injects ``matmul_fn`` (a shard_map wrapper from
    ``ops.int4_matmul.make_int4_matmul_fn``) on >1-device meshes — q4
    columns stay local at column-parallel sites, only activations gather at
    row-parallel ones (test-pinned: no uint8 all-gather in the compiled
    program). Without the injection the kernel runs direct (single device,
    or GSPMD-replicated).
    """

    features: int
    use_bias: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    group_size: int = 128
    kernel_axes: tuple = (None, None)   # the projection's logical axes
    matmul_fn: Any = None
    # Mesh-aware override (ops.int4_matmul.make_int4_matmul_fn): shard_map
    # around the kernel for tensor-parallel serving; None runs it direct
    # (single-device, or GSPMD-replicated).
    activation_bits: int = 16
    # 8 → w4a8: per-row int8 activations, int8×int4→int32 on the MXU,
    # group scales applied once to the int32 partials (the throughput point
    # of the quantization ladder — see ops/int4_matmul.py::_kernel_w4a8).

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from learning_jax_sharding_tpu.ops.int4_matmul import int4_matmul

        k = x.shape[-1]
        if k % 2:
            raise ValueError(f"int4 packing needs an even contraction dim, got {k}")
        g = min(self.group_size, k)

        class _Kernel(nn.Module):
            @nn.compact
            def __call__(self):
                q4 = self.param(
                    "q4", nn.initializers.zeros_init(),
                    (k // 2, features), jnp.uint8,
                )
                scale = self.param(
                    "scale", nn.initializers.ones_init(),
                    (k // g, features), jnp.float32,
                )
                return q4, scale

        features = self.features
        q4, scale = _Kernel(name="kernel")()
        x = x.astype(self.dtype)
        w4a8 = self.activation_bits == 8
        if scale.shape[0] == 1 or (k // 2) % g == 0:
            if self.matmul_fn is not None:
                y = self.matmul_fn(
                    x, q4, scale, group=g, kernel_axes=self.kernel_axes
                )
            else:
                y = int4_matmul(x, q4, scale, group=g, w4a8=w4a8)
        else:
            if w4a8:
                # Falling back to full-precision activations would silently
                # change the served numerics the caller measured/accepted.
                raise ValueError(
                    f"w4a8 requested but the kernel cannot tile this layout "
                    f"(scale rows {scale.shape[0]}, group {g} over K={k}); "
                    f"re-quantize with a group dividing K/2"
                )
            w = dequantize_leaf_int4({"q4": q4, "scale": scale}, self.dtype)
            y = x @ w
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (features,),
                self.param_dtype,
            )
            y = y + bias.astype(y.dtype)
        return y



class Int4ProjParams(nn.Module):
    """Parameter-only twin of :class:`Int4Dense`: declares the SAME
    ``<name>/kernel/{q4, scale}`` layout (so ``quantize_tree`` output
    applies verbatim) but returns the arrays instead of computing — for
    multi-projection fused kernels (``ops/int4_ff.py``) that consume
    several packed weights in one call."""

    rows: int        # packed rows (in_features / 2)
    cols: int
    scale_rows: int  # in_features / group (1 when one group covers all)

    @nn.compact
    def __call__(self):
        class _Kernel(nn.Module):
            @nn.compact
            def __call__(self, rows, cols, scale_rows):
                q4 = self.param(
                    "q4", nn.initializers.zeros_init(),
                    (rows, cols), jnp.uint8,
                )
                scale = self.param(
                    "scale", nn.initializers.ones_init(),
                    (scale_rows, cols), jnp.float32,
                )
                return q4, scale

        return _Kernel(name="kernel")(self.rows, self.cols, self.scale_rows)


def projection_dense(
    *,
    quantization,
    features: int,
    kernel_axes: tuple,
    use_bias: bool,
    dtype: Any,
    param_dtype: Any,
    kernel_init: Callable,
    name: str,
    group_size: int = 128,
    quantized_matmul_fn: Callable | None = None,
):
    """THE dense/Int4Dense dispatch — every projection site (attention
    q/k/v/out, FF up/down, lm_head) builds through here so the quantized
    serving path cannot drift between modules."""
    if quantization in ("int4", "int4_w4a8"):
        return Int4Dense(
            features=features,
            use_bias=use_bias,
            dtype=dtype,
            param_dtype=param_dtype,
            group_size=group_size,
            kernel_axes=tuple(kernel_axes),
            matmul_fn=quantized_matmul_fn,
            activation_bits=8 if quantization == "int4_w4a8" else 16,
            name=name,
        )
    if quantization is not None:
        raise ValueError(
            f"unknown quantization {quantization!r}: expected None, 'int4', "
            f"or 'int4_w4a8'"
        )
    return nn.Dense(
        features,
        use_bias=use_bias,
        dtype=dtype,
        param_dtype=param_dtype,
        kernel_init=nn.with_logical_partitioning(kernel_init, kernel_axes),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), (kernel_axes[-1],)
        ),
        name=name,
    )
