"""Autoregressive generation with a sharded KV cache.

The reference has no inference path at all — its ``apply_fn`` is a full-
sequence forward used only for timing (`/root/reference/case6_attention.py:
229-238`). This module adds real decoding on top of the transformer's
``decode`` mode:

* **prefill**: one apply over the whole prompt fills every block's KV cache
  (chunked attention against the cache handles intra-prompt causality);
* **decode loop**: a ``lax.scan`` feeds one token per step — static shapes,
  so XLA compiles a fixed handful of executables for any prompt and
  generation length (prefill + step; chunked prefill adds a chunk body and
  an optional remainder);
* **sharded throughout**: runs under mesh + rules like every other entry
  point; the caches inherit the activation shardings (batch over ``data``,
  heads over ``model`` under TP rules), so tensor-parallel decoding works
  unchanged — per-step collectives ride the same GSPMD annotations as
  training.

Greedy (``temperature=0``), temperature, top-k, nucleus (top-p), min-p, and
vocab-limited sampling plus a CTRL-style repetition penalty are supported;
filters compose vocab-limit → top-k → top-p → min-p (``filtered_logits`` is
the single definition of the order, shared with speculative verification).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from learning_jax_sharding_tpu.models.decoding import (
    apply_dequantize_policy,
    check_sequence_budget,
    derive_decode_config,
    make_cached_apply,
    make_param_caster,
)
from learning_jax_sharding_tpu.models.transformer import Transformer, TransformerConfig
from learning_jax_sharding_tpu.parallel.logical import Rules, activate


def top_k_filter(logits: jax.Array, k: int) -> jax.Array:
    """Mask all but the k largest logits per row to -inf. Static shapes:
    one ``lax.top_k`` for the threshold, then a compare — no gather/scatter,
    which is what the TPU wants for a (B, V) vocab-wide op."""
    if k <= 0:
        raise ValueError(f"top_k must be positive, got {k}")
    kth = lax.top_k(logits, k)[0][..., -1:]  # (B, 1) k-th largest value
    return jnp.where(logits < kth, -jnp.inf, logits)


def top_p_filter(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filter: keep the smallest set of tokens with cumulative
    probability ≥ p, mask the rest to -inf.

    Implemented sort-side (sort probabilities descending, cumulative-sum,
    map the cutoff back through a second sort of the original positions) so
    everything is a fixed-shape sort/scan — XLA-friendly, no dynamic shapes.
    """
    if not 0.0 < p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {p}")
    probs = jax.nn.softmax(logits, axis=-1)
    order = jnp.argsort(probs, axis=-1)[..., ::-1]               # descending
    sorted_probs = jnp.take_along_axis(probs, order, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    # Keep the first tokens whose cumsum-before crosses p (always ≥ 1), then
    # scatter the kept mask back through the inverse permutation — a
    # probability THRESHOLD would also keep every token tied with the nucleus
    # boundary and overshoot p badly under tied logits.
    keep_sorted = cumulative - sorted_probs < p                  # (B, V) bools
    inverse = jnp.argsort(order, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inverse, axis=-1)
    return jnp.where(keep, logits, -jnp.inf)


def min_p_filter(logits: jax.Array, p: float) -> jax.Array:
    """Min-p filter: keep tokens whose probability is at least ``p`` times
    the most likely token's, mask the rest to -inf. Scales the kept set with
    the model's confidence (sharp distribution → few survivors, flat → many)
    where top-p keeps a fixed probability mass. One max + compare — cheaper
    than the top-p sort."""
    if not 0.0 < p <= 1.0:
        raise ValueError(f"min_p must be in (0, 1], got {p}")
    # prob >= p·max_prob ⇔ logit >= max_logit + log(p): the softmax
    # normalizer cancels, so no logsumexp in the decode hot loop.
    cutoff = jnp.max(logits, axis=-1, keepdims=True) + jnp.log(p)
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def repetition_penalty_filter(
    logits: jax.Array, seen: jax.Array, penalty: float
) -> jax.Array:
    """CTRL-style repetition penalty: for tokens already in the sequence
    (``seen``: (B, V) bool), positive logits are divided by ``penalty`` and
    negative ones multiplied — both push repeated tokens down regardless of
    sign. ``penalty`` > 1 discourages repeats; 1 is a no-op."""
    if penalty <= 0:
        raise ValueError(f"repetition_penalty must be positive, got {penalty}")
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def vocab_limit_filter(logits: jax.Array, limit: int) -> jax.Array:
    """Mask logits at ids ≥ ``limit`` to -inf.

    Model vocabularies are padded to lane-friendly multiples (the default
    config rounds GPT-2's 50257 up to 50304), so an un-trained or lightly
    trained model assigns real probability to ids NO tokenizer can decode.
    Masking at the source means the pad region can never be emitted — the
    loud ``BPETokenizer.decode`` range check then only fires on genuine
    corruption."""
    if limit < 1:
        raise ValueError(f"vocab_limit must be >= 1, got {limit}")
    return jnp.where(jnp.arange(logits.shape[-1]) < limit, logits, -jnp.inf)


def filtered_logits(
    logits: jax.Array,
    temperature: float,
    top_k: int | None = None,
    top_p: float | None = None,
    min_p: float | None = None,
    vocab_limit: int | None = None,
) -> jax.Array:
    """The sampling distribution in logit space: vocab-limit → temperature →
    top-k → top-p → min-p, fp32. THE single definition of filter order —
    plain sampling and speculative verification (``models/speculative.py``)
    both call it, which is what makes speculative sampling exact for the same
    distribution plain sampling draws from. Requires ``temperature > 0``."""
    logits = logits.astype(jnp.float32) / temperature
    if vocab_limit is not None:
        logits = vocab_limit_filter(logits, vocab_limit)
    if top_k is not None:
        logits = top_k_filter(logits, top_k)
    if top_p is not None:
        logits = top_p_filter(logits, top_p)
    if min_p is not None:
        logits = min_p_filter(logits, min_p)
    return logits


def _sample(
    logits: jax.Array,
    temperature: float,
    rng: jax.Array,
    top_k: int | None = None,
    top_p: float | None = None,
    min_p: float | None = None,
    vocab_limit: int | None = None,
) -> jax.Array:
    """(B, V) logits → (B,) token ids; argmax at temperature 0."""
    if temperature == 0.0:
        if vocab_limit is not None:
            logits = vocab_limit_filter(logits, vocab_limit)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng,
        filtered_logits(logits, temperature, top_k, top_p, min_p, vocab_limit),
        axis=-1,
    ).astype(jnp.int32)


def make_generate_fn(
    config: TransformerConfig,
    mesh: Mesh,
    rules: Rules,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    min_p: float | None = None,
    vocab_limit: int | None = None,
    repetition_penalty: float | None = None,
    eos_id: int | None = None,
    prefill_chunk_size: int | None = None,
    inference_dtype: Any | None = None,
    dequantize: bool | str = False,
    ragged: bool = False,
):
    """Build ``generate(params, prompt, rng) -> (B, prompt+new) tokens``.

    ``ragged``: mixed-length prompt batches — the normal serving case. The
    returned function takes ``lengths`` (``(B,)`` int32, each row's true
    prompt length; the prompt arrives RIGHT-padded to the batch max) and
    every row generates from its own length: per-row KV-cache positions
    (``config.decode_ragged``), per-row first-token logits, and per-row
    output placement — row ``b`` of the result is
    ``[prompt_b, generated tokens, fill]`` with the generated span starting
    at ``lengths[b]``, exactly what a per-row single run would produce (test
    -pinned, dense and blocked backends). With ``eos_id`` set, finished rows
    STOP consuming cache (their index freezes), so attention traffic tracks
    live rows only. Not combinable with ``prefill_chunk_size``.

    ``eos_id``: rows that emit it are frozen (EOS padding from there on) and
    the decode loop EXITS EARLY once every row has finished — a
    ``lax.while_loop`` instead of the fixed-length scan, so short
    completions don't pay for ``max_new_tokens`` steps. The output length is
    still static (``prompt + max_new_tokens``); only device time shrinks.
    Measured on the v5e 125M bench shape: 241 → 72 ms when all rows finish
    by step 5 of 128; the while_loop costs ~20% over the scan when nothing
    finishes — set ``eos_id`` when completions are usually shorter than the
    budget, leave it ``None`` for fixed-length workloads.

    ``prefill_chunk_size``: feed the prompt through the cache in fixed-size
    chunks (a ``lax.scan``) instead of one apply. Prefill's peak memory is
    the (chunk × cache_len) attention scores plus chunk-length activations,
    so long prompts stop scaling prefill memory with their own length. The
    cache path is position-exact, so results match whole-prompt prefill —
    bit-identical at fp32 on the CPU backend (test-pinned); on TPU the
    different matmul shapes tile (and so accumulate) differently, leaving
    ~1e-2 logit jitter at bf16 (measured, 1900-token prompt; argmax was
    unaffected) that can flip greedy picks only between near-tied tokens.
    ``None`` (default) prefills in one apply.

    ``config`` is the TRAINING config — the decode variant (KV caches sized
    ``max_seq_len``) is derived here, so train and generate share params
    verbatim.

    The returned function is jit-compiled as one program: prompt prefill,
    then a ``lax.scan`` over single-token steps. ``rng`` is ignored for
    greedy decoding (pass anything); with ``temperature > 0`` it drives
    per-step categorical sampling, optionally truncated by ``top_k``,
    nucleus ``top_p``, and/or confidence-scaled ``min_p`` (filters compose
    in that order). ``vocab_limit`` masks ids ≥ it for sampling AND greedy
    argmax — set it to the TOKENIZER's vocab size when the model vocab is
    padded to a lane multiple, so undecodable pad ids can never be emitted.
    ``repetition_penalty`` (> 1) down-weights every token
    already in the row — prompt included — before sampling OR greedy argmax;
    the seen-set is a (B, V) presence mask carried through the decode scan.

    ``inference_dtype``: cast floating-point params to this dtype (eagerly,
    once per generate call — NOT inside the jitted program: XLA does not
    hoist the cast out of the decode scan and re-casting every token step
    measured 20% slower) and run the whole model at it. bf16 halves weight
    memory; throughput is neutral on the v5e 125M bench (decode there is
    bound by KV-cache attention and per-step work, not weight reads).
    ``None`` keeps training dtypes.

    ``dequantize``: ``"fused"`` — the params are an int4 tree from
    ``models.quantize.quantize_tree(bits=4)`` and every projection streams
    the packed nibbles straight into its matmul via the fused Pallas kernel
    (``ops/int4_matmul.py``): no dequantized weight array ever lands in HBM,
    which removes the unpack-then-matmul traffic that made int4 slower than
    int8 in round 1. ``"fused_w4a8"`` — same packed tree, but activations
    are quantized per-row to int8 inside the kernel path and the
    contraction runs int8×int4→int32 on the MXU with group scales applied
    once to the int32 partials — removes the per-byte dequant VPU work
    that kept "fused" below int8 throughput, at ~0.8% extra activation
    rounding error (greedy tokens can differ near ties; measure on your
    eval set before shipping). ``True`` — the params are an int8 tree from
    ``models.quantize.quantize_tree``; they are dequantized INSIDE the jitted
    program (per step, next to the consuming matmuls), so HBM STORES int8 —
    the guaranteed win is weight memory (half of bf16). Whether the decode
    loop also streams int8 (a bandwidth win) depends on XLA fusing the
    upcast into the matmul operands instead of materializing bf16 weights
    each step; measure at your shape (``bench.py`` prints an int8 decode
    context line). Combine with ``inference_dtype=bf16`` to set the
    compute/dequant dtype; non-quantized leaves (embeddings, norms) are
    still cast to it eagerly.
    """
    import dataclasses as _dc

    if ragged and prefill_chunk_size is not None:
        raise ValueError(
            "ragged and prefill_chunk_size cannot combine (chunked ragged "
            "prefill would need per-chunk logit gathers; prefill whole)"
        )
    cfg = derive_decode_config(config, inference_dtype, mesh=mesh, rules=rules)
    if ragged:
        cfg = _dc.replace(cfg, decode_ragged=True)
    # The quantized-serving policy (mode validation, fused int4 config,
    # TP shard_map injection) is decoding.apply_dequantize_policy — ONE
    # copy shared with the continuous engine.
    cfg, fused = apply_dequantize_policy(cfg, dequantize, mesh, rules)
    model = Transformer(cfg)
    maybe_cast = make_param_caster(inference_dtype, dequantize=bool(dequantize))
    # dequant dtype == inference_dtype when one was given (models.decoding)
    apply = make_cached_apply(
        model, dequantize=bool(dequantize) and not fused,
        dequant_dtype=cfg.param_dtype,
    )

    def step_apply(params, cache, tokens, chunk_lengths=None):
        logits, cache = apply(params, cache, tokens, chunk_lengths)
        return logits[:, -1], cache

    def generate(params, prompt, rng, lengths=None):
        b, prompt_len = prompt.shape
        check_sequence_budget(
            prompt_len + max_new_tokens, cfg.max_seq_len,
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens})",
        )
        # Prefill: creates the caches (they are born inside this jitted
        # program, sized (B, max_seq_len, ...)) and returns the last-position
        # logits, from which the first new token is sampled. With
        # prefill_chunk_size, the prompt streams through the cache chunk by
        # chunk: first chunk creates the caches, full chunks ride a scan,
        # a static remainder finishes — same cache contents, bounded memory.
        if ragged:
            # Ragged prefill: the padded prompt runs whole (each row's pad
            # tail writes garbage K/V BEYOND its length — masked now, then
            # overwritten as the row generates); the first-token logits come
            # from each row's own last valid position, not column -1.
            logits_all, cache = apply(params, None, prompt, lengths)
            logits = jnp.take_along_axis(
                logits_all, (lengths - 1)[:, None, None], axis=1
            )[:, 0]
        elif prefill_chunk_size is None or prompt_len <= prefill_chunk_size:
            logits, cache = step_apply(params, None, prompt)
        else:
            if prefill_chunk_size < 1:
                raise ValueError(
                    f"prefill_chunk_size must be >= 1, got {prefill_chunk_size}"
                )
            c = prefill_chunk_size
            logits, cache = step_apply(params, None, prompt[:, :c])
            nfull = (prompt_len - c) // c
            if nfull:
                chunks = jnp.moveaxis(
                    prompt[:, c : c + nfull * c].reshape(b, nfull, c), 1, 0
                )

                def pf(carry, chunk):
                    cache, _ = carry
                    lg, cache = step_apply(params, cache, chunk)
                    # Last logits ride the CARRY (not stacked per-step
                    # outputs, which would grow with prompt length — the
                    # memory this feature exists to bound).
                    return (cache, lg), None

                (cache, logits), _ = lax.scan(pf, (cache, logits), chunks)
            rem = prompt_len - c - nfull * c
            if rem:
                logits, cache = step_apply(params, cache, prompt[:, -rem:])
        rng0, rng_loop = jax.random.split(rng)
        rows = jnp.arange(b)

        def pick(logits, seen, rng):
            # One place for the penalty→sample→seen-update sequence so the
            # prefill token and the scan tokens cannot diverge.
            if repetition_penalty is not None:
                logits = repetition_penalty_filter(
                    logits, seen, repetition_penalty
                )
            tok = _sample(
                logits, temperature, rng, top_k, top_p, min_p, vocab_limit
            )
            if repetition_penalty is not None:
                seen = seen.at[rows, tok].set(True)
            return tok, seen

        if repetition_penalty is not None:
            # (B, V) presence mask of every token in the row so far; a
            # scatter per step keeps it current inside the scan carry.
            seen = jnp.zeros((b, logits.shape[-1]), bool)
            if ragged:
                # Only VALID prompt positions count as seen — a short row's
                # pad tail must not penalize the pad id.
                valid = jnp.arange(prompt_len)[None, :] < lengths[:, None]
                seen = seen.at[rows[:, None], prompt].max(valid)
            else:
                seen = seen.at[rows[:, None], prompt].set(True)
        else:
            seen = None
        tok, seen = pick(logits, seen, rng0)

        def assemble(new_tokens):
            # Row b's generated span starts at ITS length, matching what a
            # per-row single run would return; EVERY cell past the span —
            # including the caller's prompt padding between lengths[b] and
            # prompt_len — becomes the fill value (eos when set, a decodable
            # row terminator), so consumers scanning past the generated span
            # never read stale pad ids as output.
            if not ragged:
                return jnp.concatenate([prompt, new_tokens], axis=1)
            fill = 0 if eos_id is None else eos_id
            total = prompt_len + max_new_tokens
            col = jnp.arange(total)[None, :]
            out = jnp.where(
                col < lengths[:, None],
                jnp.pad(prompt, ((0, 0), (0, max_new_tokens))),
                fill,
            )
            cols = lengths[:, None] + jnp.arange(max_new_tokens)[None, :]
            return out.at[rows[:, None], cols].set(new_tokens)

        def advance(tok, cache, rng, seen, active=None):
            # The per-token sequence shared by BOTH loop flavors — the eos
            # while_loop must equal the scan truncated at EOS, so there is
            # exactly one copy of it. ``active`` (ragged + eos): per-row 1/0
            # advance so finished rows stop consuming cache slots.
            logits, cache = step_apply(params, cache, tok[:, None], active)
            rng, sub = jax.random.split(rng)
            nxt, seen = pick(logits, seen, sub)
            return nxt, cache, rng, seen

        if eos_id is None:
            # Fixed trip count: a lax.scan over single-token steps.
            def step(carry, _):
                nxt, cache, rng, seen = advance(*carry)
                return (nxt, cache, rng, seen), nxt

            (_, _, _, _), rest = lax.scan(
                step, (tok, cache, rng_loop, seen), None,
                length=max_new_tokens - 1,
            )
            new_tokens = jnp.concatenate([tok[:, None], rest.T], axis=1)
            return assemble(new_tokens)

        # EOS early stop: a while_loop that ends as soon as EVERY row has
        # emitted eos_id — short completions don't pay for max_new_tokens
        # model steps. Finished rows are frozen to EOS padding (their model
        # step still runs — SPMD needs the full batch — but its output is
        # overwritten), so the output reads like the scan path truncated at
        # each row's EOS.
        finished = tok == eos_id
        buffer = jnp.full((b, max_new_tokens), eos_id, jnp.int32)
        buffer = buffer.at[:, 0].set(tok)

        def cond(carry):
            i, _, _, _, _, finished, _ = carry
            return (i < max_new_tokens) & ~jnp.all(finished)

        def body(carry):
            i, tok, cache, rng, seen, finished, buffer = carry
            active = (~finished).astype(jnp.int32) if ragged else None
            nxt, cache, rng, seen = advance(tok, cache, rng, seen, active)
            nxt = jnp.where(finished, eos_id, nxt)
            buffer = buffer.at[:, i].set(nxt)
            finished = finished | (nxt == eos_id)
            return (i + 1, nxt, cache, rng, seen, finished, buffer)

        *_, buffer = lax.while_loop(
            cond, body,
            (jnp.asarray(1, jnp.int32), tok, cache, rng_loop, seen,
             finished, buffer),
        )
        return assemble(buffer)

    jitted = jax.jit(generate, static_argnames=())

    def run(
        params,
        prompt: jax.Array,
        rng: Optional[jax.Array] = None,
        lengths: Optional[jax.Array] = None,
    ):
        if ragged and lengths is None:
            raise ValueError(
                "ragged=True: pass lengths (B,) — each row's true prompt "
                "length in the right-padded prompt batch"
            )
        if not ragged and lengths is not None:
            raise ValueError("lengths requires make_generate_fn(ragged=True)")
        rng = jax.random.key(0) if rng is None else rng
        params = maybe_cast(params)  # eager; pre-cast params make this a no-op
        with activate(mesh, rules):
            if ragged:
                return jitted(params, prompt, rng, jnp.asarray(lengths, jnp.int32))
            return jitted(params, prompt, rng)

    run.jitted = jitted
    return run
