"""Speculative decoding: a small draft model proposes, the target verifies.

Autoregressive decoding is latency-bound by one serialized target forward per
token. Speculative decoding breaks the serialization: a cheap draft model
greedily proposes ``num_draft`` tokens one-by-one, then the target scores the
whole proposal in ONE chunked forward (the same cache path that serves
prefill, `models/attention.py::_cached_attention` — chunk attention against
the KV cache at the current index). The longest prefix of draft tokens that
matches the target's own greedy choices is accepted, plus one bonus token
from the target's logits — so each round costs one target forward and yields
1..num_draft+1 tokens, and the output is EXACTLY what plain greedy decoding
of the target would produce (the oracle the tests pin).

Nothing like this exists in the reference (no inference path at all,
SURVEY.md §5); it composes the framework's own pieces:

* chunked verification reuses the cache-at-index attention;
* acceptance rollback is just rewinding each block's ``cache_index`` —
  stale K/V entries beyond the index are never attended (the causal mask is
  ``position < index + i``) and are overwritten by the next chunk write;
* batch handling (rectangular path) takes the MINIMUM acceptance across
  rows each round: rows that matched further ahead re-derive the same
  tokens in later rounds (the bonus token equals their next draft match),
  so exactness is preserved and only the speedup varies with batch
  agreement;
* the RAGGED path (``ragged=True``) upgrades acceptance to PER-ROW: each
  row keeps its own accepted count and its own cache rewind (the per-row
  ``cache_index`` the ragged serving machinery already provides), so one
  slow row no longer rolls back the whole batch — mixed-length prompt
  batches decode with per-row speeds, and rows that hit their budget
  freeze (``chunk_lengths`` 0) while the rest keep speculating;
* everything runs under mesh + rules — draft and target can use different
  shardings of the same mesh.

Two verification modes: greedy (``temperature == 0``, acceptance is a hard
token equality, output bit-identical to plain greedy) and **rejection
sampling** (``temperature > 0``, Leviathan-style: accept x with probability
``min(1, p(x)/q(x))``, correct rejections from ``norm(max(p − q, 0))``) —
the sampled output is distributed exactly as sampling the target alone,
with position-keyed randomness keeping the batch-min rollback exact.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from learning_jax_sharding_tpu.models.decoding import (
    check_sequence_budget,
    derive_decode_config,
    make_cached_apply,
    make_param_caster,
)
from learning_jax_sharding_tpu.models.transformer import Transformer, TransformerConfig
from learning_jax_sharding_tpu.parallel.logical import Rules, activate


def _rollback(cache: Any, index: jax.Array) -> Any:
    """Rewind the decode position counters to ``index``: every attention
    block's ``cache_index`` AND the transformer's top-level ``position``
    (which drives positional embeddings). Stale K/V beyond the index are
    masked out by the causal-at-index attention and later overwritten."""

    def leaf(path, x):
        if getattr(path[-1], "key", None) in ("cache_index", "position"):
            # Scalar index (rectangular) or per-row (B,) vector (ragged) —
            # broadcast either onto the counter's own shape.
            return jnp.broadcast_to(jnp.asarray(index, x.dtype), x.shape)
        return x

    return jax.tree_util.tree_map_with_path(leaf, cache)


def emit_vector(drafts: jax.Array, m: jax.Array, final: jax.Array) -> jax.Array:
    """``(B, num_draft + 1)`` emission rows: row b's accepted drafts below
    slot ``m_b``, its ``final`` token (greedy bonus / sampled residual)
    from slot ``m_b`` on (repeated past it — junk the caller masks or
    overwrites). ONE copy of the emission-vector rule for the greedy and
    sampling verifiers."""
    padded = jnp.pad(drafts, ((0, 0), (0, 1)))
    idx = jnp.arange(drafts.shape[1] + 1)
    return jnp.where(idx[None, :] < m[:, None], padded, final[:, None])


def greedy_accept_emit(
    drafts: jax.Array, choices: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """PER-ROW greedy acceptance over a verified chunk — THE shared core of
    ragged speculative decoding (used by both :func:`generate_ragged` here
    and the engine's speculative decode block, ``models/serving.py``, so
    the acceptance rule cannot drift between them).

    ``drafts`` is ``(B, num_draft)`` proposals; ``choices`` is
    ``(B, num_draft + 1)`` target greedy picks after each chunk position.
    Returns ``(m, emitted, bonus)``: ``m[b]`` = the longest prefix where
    row b's drafts match the target's own picks; ``emitted`` ``(B,
    num_draft+1)`` = the accepted drafts followed by the bonus/correction
    token (repeated past slot ``m`` — junk the caller masks or
    overwrites); ``bonus[b] = choices[b, m_b]``."""
    eq = drafts == choices[:, :-1]
    m = jnp.sum(jnp.cumprod(eq.astype(jnp.int32), axis=1), axis=1)
    bonus = jnp.take_along_axis(choices, m[:, None], axis=1)[:, 0]
    return m, emit_vector(drafts, m, bonus), bonus


def _greedy(logits: jax.Array, vocab_limit: int | None = None) -> jax.Array:
    if vocab_limit is not None:
        from learning_jax_sharding_tpu.models.generate import vocab_limit_filter

        logits = vocab_limit_filter(logits.astype(jnp.float32), vocab_limit)
    return jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)


def _pos_key(rng: jax.Array, pos: jax.Array, tag: int) -> jax.Array:
    """Randomness keyed by ABSOLUTE generated position (+ a role tag:
    0 = draft proposal, 1 = acceptance uniform, 2 = residual/bonus sample).

    Position-keyed keys are what make batch-min rollback exact under
    sampling: a row that accepted further than the batch minimum re-derives
    the SAME draft proposals and acceptance draws for the rolled-back
    positions next round, so its tokens cannot drift."""
    return jax.random.fold_in(jax.random.fold_in(rng, pos), tag)


def make_speculative_generate_fn(
    target_config: TransformerConfig,
    draft_config: TransformerConfig,
    mesh: Mesh,
    rules: Rules,
    *,
    max_new_tokens: int,
    num_draft: int = 4,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    min_p: float | None = None,
    vocab_limit: int | None = None,
    inference_dtype: Any | None = None,
    ragged: bool = False,
):
    """Build ``generate(target_params, draft_params, prompt[, rng]) -> tokens``.

    ``target_config``/``draft_config`` are TRAINING configs sharing a vocab;
    decode variants are derived here (as in ``make_generate_fn``).

    ``temperature == 0`` (default): greedy verification — the output is
    bit-identical to greedy decoding of the target alone; the draft only
    changes how many serialized target passes it takes to get there.

    ``temperature > 0``: **speculative sampling** (Leviathan-style rejection):
    the draft SAMPLES proposals from its own filtered distribution q, the
    target computes its filtered distribution p in one chunked pass, each
    proposal x is accepted with probability ``min(1, p(x)/q(x))``, and the
    first rejection is replaced by a sample from ``norm(max(p - q, 0))``
    (full acceptance earns a bonus sample from p). The emitted tokens are
    distributed EXACTLY as sampling the target alone — the property
    ``tests/test_speculative.py`` pins distributionally. ``top_k``/``top_p``/
    ``min_p`` shape both p and q the same way, so exactness holds for the
    filtered distribution (what plain ``make_generate_fn`` samples too).
    ``repetition_penalty`` is NOT supported here: it conditions the
    distribution on the growing output, which would invalidate the draft's
    q at every accepted token — use plain ``make_generate_fn`` for it.

    ``ragged``: mixed-length prompt batches with PER-ROW acceptance. The
    returned function takes ``lengths`` (``(B,)`` int32; the prompt arrives
    right-padded) and every row keeps its OWN accepted count and cache
    rewind each round — one slow row no longer rolls back the whole batch
    (the rectangular path's batch-min). Greedy output is bit-identical to
    ``make_generate_fn(ragged=True)``'s per-row greedy decode; sampling
    keys every draw by (row, absolute position), so a row's rolled-back
    positions re-derive identical draws AND a row's output stream is
    independent of the other rows' prompts. Output rows follow the ragged
    ``make_generate_fn`` convention: ``[prompt_b, generated..., 0-fill]``
    with the generated span starting at ``lengths[b]``. The jitted function
    additionally returns per-row stats ``{"accepted", "rounds",
    "emitted"}`` (total accepted draft tokens, verify rounds, tokens
    emitted per row — emitted can exceed ``max_new_tokens`` by up to
    ``num_draft``; the output slice keeps exactly ``max_new_tokens``);
    ``run(..., return_stats=True)`` surfaces them.
    """
    if target_config.vocab_size != draft_config.vocab_size:
        raise ValueError(
            f"target vocab {target_config.vocab_size} != draft vocab "
            f"{draft_config.vocab_size}"
        )
    if num_draft < 1:
        raise ValueError(f"num_draft must be >= 1, got {num_draft}")

    t_cfg = derive_decode_config(target_config, inference_dtype, mesh=mesh, rules=rules)
    d_cfg = derive_decode_config(draft_config, inference_dtype, mesh=mesh, rules=rules)
    if ragged:
        import dataclasses as _dc

        t_cfg = _dc.replace(t_cfg, decode_ragged=True)
        d_cfg = _dc.replace(d_cfg, decode_ragged=True)
    target, draft = Transformer(t_cfg), Transformer(d_cfg)
    t_apply, d_apply = make_cached_apply(target), make_cached_apply(draft)
    maybe_cast = make_param_caster(inference_dtype)

    def generate(t_params, d_params, prompt):
        b, prompt_len = prompt.shape
        # Verification writes up to num_draft+1 positions past the accepted
        # prefix before rolling back, so leave that much headroom.
        need = prompt_len + max_new_tokens + num_draft + 1
        for name, cfg in (("target", t_cfg), ("draft", d_cfg)):
            check_sequence_budget(
                need, cfg.max_seq_len, f"prompt+new+draft for {name}"
            )

        # Prefill both models on the prompt. The first new token comes from
        # the target's last-position logits — exactly as plain greedy.
        t_logits, t_cache = t_apply(t_params, None, prompt)
        _, d_cache = d_apply(d_params, None, prompt)
        t_cur = _greedy(t_logits[:, -1], vocab_limit)

        buf_len = max_new_tokens + num_draft + 1
        buffer = jnp.zeros((b, buf_len), jnp.int32)
        buffer = lax.dynamic_update_slice(buffer, t_cur[:, None], (0, 0))

        def cond(carry):
            n, *_ = carry
            return n < max_new_tokens

        def body(carry):
            n, t_cur, t_cache, d_cache, buffer = carry
            # Invariant: both caches hold prompt + the n-1 accepted tokens
            # BEFORE t_cur (t_cur itself is pending, fed by this round).
            base = prompt_len + n - 1

            # 1. Draft proposes num_draft tokens greedily, one at a time;
            #    one extra feed pushes the last proposal's K/V into the draft
            #    cache so a full acceptance leaves the cache complete.
            def draft_step(carry, _):
                prev, cache = carry
                logits, cache = d_apply(d_params, cache, prev[:, None])
                nxt = _greedy(logits[:, -1], vocab_limit)
                return (nxt, cache), nxt

            (last_d, d_cache), drafts = lax.scan(
                draft_step, (t_cur, d_cache), None, length=num_draft
            )
            drafts = drafts.T  # (num_draft, B) scan stack → (B, num_draft)
            _, d_cache = d_apply(d_params, d_cache, last_d[:, None])

            # 2. Target verifies the whole proposal in one chunked forward:
            #    [t_cur, d_1..d_num_draft] → greedy choice after each.
            chunk = jnp.concatenate([t_cur[:, None], drafts], axis=1)
            t_logits, t_cache = t_apply(t_params, t_cache, chunk)
            choices = _greedy(t_logits, vocab_limit)  # (B, num_draft+1)

            # 3. Accept the longest prefix where draft == target choice;
            #    batch-min keeps a single scalar cache index.
            eq = drafts == choices[:, :-1]  # (B, num_draft)
            m_row = jnp.sum(jnp.cumprod(eq.astype(jnp.int32), axis=1), axis=1)
            m = jnp.min(m_row)  # scalar: accepted draft count this round

            # 4. Emit d_1..d_m then the bonus/correction token choices[:, m].
            #    Positions past m hold the bonus too — junk that later rounds
            #    overwrite (and the final slice drops).
            idx = jnp.arange(num_draft + 1)
            bonus = jnp.take_along_axis(choices, jnp.full((b, 1), m), axis=1)[:, 0]
            padded = jnp.pad(drafts, ((0, 0), (0, 1)))  # (B, num_draft+1)
            emitted = jnp.where(idx[None, :] < m, padded, bonus[:, None])
            # buffer[i] is the (i+1)-th generated token; t_cur sits at n-1,
            # so this round's tokens start at n.
            buffer = lax.dynamic_update_slice(buffer, emitted, (0, n))

            # 5. Roll both caches back to the accepted length. The target
            #    consumed base..base+num_draft; valid prefix is base + 1 + m
            #    (t_cur and the m accepted drafts). Same for the draft.
            accepted = base + 1 + m
            t_cache = _rollback(t_cache, accepted)
            d_cache = _rollback(d_cache, accepted)

            return (n + 1 + m, bonus, t_cache, d_cache, buffer)

        n, _, _, _, buffer = lax.while_loop(
            cond, body, (jnp.asarray(1, jnp.int32), t_cur, t_cache, d_cache, buffer)
        )
        return jnp.concatenate([prompt, buffer[:, :max_new_tokens]], axis=1)

    def to_flogits(logits):
        """The filtered sampling distribution in logit space —
        ``generate.filtered_logits`` is THE definition of the filter order,
        shared with plain sampling so the two distributions cannot drift
        apart. Sampling draws straight from these (as plain ``_sample``
        does); acceptance ratios softmax them into probabilities."""
        from learning_jax_sharding_tpu.models.generate import filtered_logits

        return filtered_logits(
            logits, temperature, top_k, top_p, min_p, vocab_limit
        )

    def to_probs(logits):
        return jax.nn.softmax(to_flogits(logits), axis=-1)

    def generate_sampled(t_params, d_params, prompt, rng):
        b, prompt_len = prompt.shape
        need = prompt_len + max_new_tokens + num_draft + 1
        for name, cfg in (("target", t_cfg), ("draft", d_cfg)):
            check_sequence_budget(
                need, cfg.max_seq_len, f"prompt+new+draft for {name}"
            )

        t_logits, t_cache = t_apply(t_params, None, prompt)
        _, d_cache = d_apply(d_params, None, prompt)
        # Generated position 0 comes straight from the target's prefill
        # distribution (tag 2 = "the final sample of its position").
        t_cur = jax.random.categorical(
            _pos_key(rng, jnp.asarray(0), 2), to_flogits(t_logits[:, -1])
        ).astype(jnp.int32)

        buf_len = max_new_tokens + num_draft + 1
        buffer = jnp.zeros((b, buf_len), jnp.int32)
        buffer = lax.dynamic_update_slice(buffer, t_cur[:, None], (0, 0))

        def cond(carry):
            n, *_ = carry
            return n < max_new_tokens

        def body(carry):
            n, t_cur, t_cache, d_cache, buffer = carry
            base = prompt_len + n - 1  # same cache invariant as greedy

            # 1. Draft SAMPLES num_draft proposals, keeping its full filtered
            #    distribution per position (the residual needs p - q).
            def draft_step(carry, pos):
                prev, cache = carry
                logits, cache = d_apply(d_params, cache, prev[:, None])
                fl = to_flogits(logits[:, -1])
                tok = jax.random.categorical(
                    _pos_key(rng, pos, 0), fl
                ).astype(jnp.int32)
                return (tok, cache), (tok, jax.nn.softmax(fl, axis=-1))

            (last_d, d_cache), (drafts, q_all) = lax.scan(
                draft_step, (t_cur, d_cache), n + jnp.arange(num_draft)
            )
            drafts = drafts.T                      # (B, num_draft)
            q_all = jnp.moveaxis(q_all, 0, 1)      # (B, num_draft, V)
            _, d_cache = d_apply(d_params, d_cache, last_d[:, None])

            # 2. Target distribution at every proposal position + bonus slot.
            chunk = jnp.concatenate([t_cur[:, None], drafts], axis=1)
            t_logits, t_cache = t_apply(t_params, t_cache, chunk)
            p_all = to_probs(t_logits)             # (B, num_draft+1, V)

            # 3. Accept x_j with prob min(1, p(x_j)/q(x_j)); keep the longest
            #    accepted prefix, batch-min for a single scalar cache index.
            p_at = jnp.take_along_axis(
                p_all[:, :num_draft], drafts[..., None], axis=-1
            )[..., 0]
            q_at = jnp.take_along_axis(q_all, drafts[..., None], axis=-1)[..., 0]
            u = jax.vmap(
                lambda pos: jax.random.uniform(_pos_key(rng, pos, 1), (b,)),
                out_axes=1,
            )(n + jnp.arange(num_draft))           # (B, num_draft)
            # Strict <: with u ∈ [0,1), p==q still always accepts (u·q < q),
            # while p==0 (draft token outside the target's filtered support)
            # never does — <= would leak such tokens on exact u==0.0 draws.
            accept = u * q_at < p_at               # u < p/q without the div
            a_row = jnp.sum(
                jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1
            )
            m = jnp.min(a_row)                     # scalar accepted count

            # 4. The token at slot m: rows that accepted past m emit their
            #    draft token; rows that rejected AT m sample the residual
            #    norm(max(p - q, 0)). Padding q with zeros makes the
            #    full-acceptance bonus (sample from p, no q to subtract) the
            #    same code path.
            q_pad = jnp.concatenate(
                [q_all, jnp.zeros_like(q_all[:, :1])], axis=1
            )
            def take_m(x):  # x[:, m] with a traced m
                return jnp.take_along_axis(x, jnp.full((b, 1, 1), m), axis=1)[:, 0]

            p_m = take_m(p_all)                    # (B, V)
            q_m = take_m(q_pad)
            residual = jnp.maximum(p_m - q_m, 0.0)
            mass = jnp.sum(residual, axis=-1, keepdims=True)
            residual = jnp.where(mass > 0, residual / mass, p_m)
            res_tok = jax.random.categorical(
                _pos_key(rng, n + m, 2), jnp.log(residual)
            ).astype(jnp.int32)
            drafts_pad = jnp.pad(drafts, ((0, 0), (0, 1)))
            draft_m = jnp.take_along_axis(
                drafts_pad, jnp.full((b, 1), m), axis=1
            )[:, 0]
            token_m = jnp.where(a_row > m, draft_m, res_tok)

            # 5. Emit accepted drafts then token_m; junk past it is
            #    overwritten by later rounds (and the final slice drops it).
            idx = jnp.arange(num_draft + 1)
            emitted = jnp.where(
                idx[None, :] < m, drafts_pad, token_m[:, None]
            )
            buffer = lax.dynamic_update_slice(buffer, emitted, (0, n))

            accepted = base + 1 + m
            t_cache = _rollback(t_cache, accepted)
            d_cache = _rollback(d_cache, accepted)
            return (n + 1 + m, token_m, t_cache, d_cache, buffer)

        n, _, _, _, buffer = lax.while_loop(
            cond, body, (jnp.asarray(1, jnp.int32), t_cur, t_cache, d_cache, buffer)
        )
        return jnp.concatenate([prompt, buffer[:, :max_new_tokens]], axis=1)

    def _check_ragged_budget(prompt_len: int) -> None:
        need = prompt_len + max_new_tokens + num_draft + 1
        for name, cfg in (("target", t_cfg), ("draft", d_cfg)):
            check_sequence_budget(
                need, cfg.max_seq_len, f"prompt+new+draft for {name}"
            )

    def _assemble_ragged(prompt, lengths, buffer):
        # Row b's generated span starts at ITS length (the ragged
        # make_generate_fn convention); everything past it — including the
        # caller's prompt padding — becomes 0-fill.
        b, prompt_len = prompt.shape
        total = prompt_len + max_new_tokens
        col = jnp.arange(total)[None, :]
        out = jnp.where(
            col < lengths[:, None],
            jnp.pad(prompt, ((0, 0), (0, max_new_tokens))),
            0,
        )
        rows = jnp.arange(b)[:, None]
        cols = lengths[:, None] + jnp.arange(max_new_tokens)[None, :]
        return out.at[rows, cols].set(buffer[:, :max_new_tokens])

    def generate_ragged(t_params, d_params, prompt, lengths):
        """Per-row greedy speculative decode over the ragged cache.

        The invariant, per ROW: before a round, the caches hold the row's
        prompt plus its ``n_b - 1`` accepted tokens (``cache_index`` =
        ``lengths_b + n_b - 1``); ``t_cur_b`` is pending. After acceptance
        of ``m_b`` drafts the rewind target is ``lengths_b + n_b + m_b`` =
        ``lengths_b + n_new_b - 1`` — which for a FROZEN row (``n_b`` at
        budget, ``chunk_lengths`` 0 all round) equals its current index, so
        one broadcast rollback serves live and frozen rows alike."""
        from learning_jax_sharding_tpu.models.attention import row_update_masked

        b, prompt_len = prompt.shape
        _check_ragged_budget(prompt_len)

        t_logits_all, t_cache = t_apply(t_params, None, prompt, lengths)
        _, d_cache = d_apply(d_params, None, prompt, lengths)
        t_cur = _greedy(
            jnp.take_along_axis(
                t_logits_all, (lengths - 1)[:, None, None], axis=1
            )[:, 0],
            vocab_limit,
        )

        buf_len = max_new_tokens + num_draft + 1
        buffer = jnp.zeros((b, buf_len), jnp.int32).at[:, 0].set(t_cur)
        n = jnp.ones((b,), jnp.int32)
        acc = jnp.zeros((b,), jnp.int32)
        rounds = jnp.asarray(0, jnp.int32)

        def cond(carry):
            n, *_ = carry
            return jnp.any(n < max_new_tokens)

        def body(carry):
            n, t_cur, t_cache, d_cache, buffer, acc, rounds = carry
            live = n < max_new_tokens
            live32 = live.astype(jnp.int32)

            # 1. Draft proposes per row; frozen rows ride with length 0
            #    (no cache advance, no write disturbance).
            def draft_step(carry, _):
                prev, cache = carry
                logits, cache = d_apply(d_params, cache, prev[:, None], live32)
                nxt = jnp.where(live, _greedy(logits[:, -1], vocab_limit), prev)
                return (nxt, cache), nxt

            (last_d, d_cache), drafts = lax.scan(
                draft_step, (t_cur, d_cache), None, length=num_draft
            )
            drafts = drafts.T
            _, d_cache = d_apply(d_params, d_cache, last_d[:, None], live32)

            # 2. One chunked target verify; per-row valid chunk lengths.
            chunk = jnp.concatenate([t_cur[:, None], drafts], axis=1)
            t_logits, t_cache = t_apply(
                t_params, t_cache, chunk, live32 * (num_draft + 1)
            )
            choices = _greedy(t_logits, vocab_limit)

            # 3+4. PER-ROW acceptance (no batch-min), then emit each row's
            #      accepted drafts + its bonus at its own buffer offset;
            #      frozen rows write nothing.
            m, emitted, bonus = greedy_accept_emit(drafts, choices)
            buffer = row_update_masked(
                buffer, emitted, n, live32 * (num_draft + 1), seq_dim=1
            )

            # 5. Per-row rollback; frozen rows' target equals their index.
            n_new = n + live32 * (1 + m)
            roll = lengths + n_new - 1
            t_cache = _rollback(t_cache, roll)
            d_cache = _rollback(d_cache, roll)
            t_cur = jnp.where(live, bonus, t_cur)
            return (
                n_new, t_cur, t_cache, d_cache, buffer,
                acc + live32 * m, rounds + 1,
            )

        n, _, _, _, buffer, acc, rounds = lax.while_loop(
            cond, body, (n, t_cur, t_cache, d_cache, buffer, acc, rounds)
        )
        stats = {"accepted": acc, "rounds": rounds, "emitted": n}
        return _assemble_ragged(prompt, lengths, buffer), stats

    def _row_keys(rng, pos, tag: int):
        """(B,) keys from per-row (row index, absolute position, tag) — the
        ragged analogue of :func:`_pos_key`. Row-indexed keys make each
        row's stream independent of the rest of the batch; position-keying
        keeps per-row rollback exact (a rewound position re-derives its
        draw)."""
        b = pos.shape[0]

        def one(r, p):
            return jax.random.fold_in(
                jax.random.fold_in(jax.random.fold_in(rng, r), p), tag
            )

        return jax.vmap(one)(jnp.arange(b), pos)

    def generate_ragged_sampled(t_params, d_params, prompt, lengths, rng):
        """Per-row speculative SAMPLING (Leviathan rejection) — acceptance,
        residual draws, and rollback all per row, randomness keyed by
        (row, position) so rewinds re-derive their draws exactly."""
        from learning_jax_sharding_tpu.models.attention import row_update_masked

        b, prompt_len = prompt.shape
        _check_ragged_budget(prompt_len)

        t_logits_all, t_cache = t_apply(t_params, None, prompt, lengths)
        _, d_cache = d_apply(d_params, None, prompt, lengths)
        first_fl = to_flogits(
            jnp.take_along_axis(
                t_logits_all, (lengths - 1)[:, None, None], axis=1
            )[:, 0]
        )
        t_cur = jax.vmap(jax.random.categorical)(
            _row_keys(rng, jnp.zeros((b,), jnp.int32), 2), first_fl
        ).astype(jnp.int32)

        buf_len = max_new_tokens + num_draft + 1
        buffer = jnp.zeros((b, buf_len), jnp.int32).at[:, 0].set(t_cur)
        n = jnp.ones((b,), jnp.int32)
        acc = jnp.zeros((b,), jnp.int32)
        rounds = jnp.asarray(0, jnp.int32)

        def cond(carry):
            n, *_ = carry
            return jnp.any(n < max_new_tokens)

        def body(carry):
            n, t_cur, t_cache, d_cache, buffer, acc, rounds = carry
            live = n < max_new_tokens
            live32 = live.astype(jnp.int32)

            # 1. Draft SAMPLES per row at its own positions n_b + j.
            def draft_step(carry, j):
                prev, cache = carry
                logits, cache = d_apply(d_params, cache, prev[:, None], live32)
                fl = to_flogits(logits[:, -1])
                tok = jax.vmap(jax.random.categorical)(
                    _row_keys(rng, n + j, 0), fl
                ).astype(jnp.int32)
                tok = jnp.where(live, tok, prev)
                return (tok, cache), (tok, jax.nn.softmax(fl, axis=-1))

            (last_d, d_cache), (drafts, q_all) = lax.scan(
                draft_step, (t_cur, d_cache), jnp.arange(num_draft)
            )
            drafts = drafts.T                      # (B, num_draft)
            q_all = jnp.moveaxis(q_all, 0, 1)      # (B, num_draft, V)
            _, d_cache = d_apply(d_params, d_cache, last_d[:, None], live32)

            # 2. Target distribution at every proposal position + bonus.
            chunk = jnp.concatenate([t_cur[:, None], drafts], axis=1)
            t_logits, t_cache = t_apply(
                t_params, t_cache, chunk, live32 * (num_draft + 1)
            )
            p_all = to_probs(t_logits)             # (B, num_draft+1, V)

            # 3. Accept x_j with prob min(1, p/q), per-row prefix length.
            p_at = jnp.take_along_axis(
                p_all[:, :num_draft], drafts[..., None], axis=-1
            )[..., 0]
            q_at = jnp.take_along_axis(q_all, drafts[..., None], axis=-1)[..., 0]
            u = jax.vmap(
                lambda j: jax.vmap(jax.random.uniform)(_row_keys(rng, n + j, 1)),
                out_axes=1,
            )(jnp.arange(num_draft))               # (B, num_draft)
            accept = u * q_at < p_at               # strict <, as rectangular
            m = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)

            # 4. Slot-m token per row: draft if the row accepted past m
            #    (never happens per-row — m IS the row's prefix, so slot m
            #    always holds the residual/bonus sample), residual from
            #    norm(max(p - q, 0)); full acceptance makes it the bonus
            #    sample from p (q padded 0).
            q_pad = jnp.concatenate(
                [q_all, jnp.zeros_like(q_all[:, :1])], axis=1
            )

            def take_m(x):
                return jnp.take_along_axis(x, m[:, None, None], axis=1)[:, 0]

            p_m = take_m(p_all)                    # (B, V)
            q_m = take_m(q_pad)
            residual = jnp.maximum(p_m - q_m, 0.0)
            mass = jnp.sum(residual, axis=-1, keepdims=True)
            residual = jnp.where(mass > 0, residual / mass, p_m)
            token_m = jax.vmap(jax.random.categorical)(
                _row_keys(rng, n + m, 2), jnp.log(residual)
            ).astype(jnp.int32)

            # 5. Emit drafts[<m] then token_m at each row's offset.
            emitted = emit_vector(drafts, m, token_m)
            buffer = row_update_masked(
                buffer, emitted, n, live32 * (num_draft + 1), seq_dim=1
            )

            n_new = n + live32 * (1 + m)
            roll = lengths + n_new - 1
            t_cache = _rollback(t_cache, roll)
            d_cache = _rollback(d_cache, roll)
            t_cur = jnp.where(live, token_m, t_cur)
            return (
                n_new, t_cur, t_cache, d_cache, buffer,
                acc + live32 * m, rounds + 1,
            )

        n, _, _, _, buffer, acc, rounds = lax.while_loop(
            cond, body, (n, t_cur, t_cache, d_cache, buffer, acc, rounds)
        )
        stats = {"accepted": acc, "rounds": rounds, "emitted": n}
        return _assemble_ragged(prompt, lengths, buffer), stats

    if ragged:
        jitted = jax.jit(
            generate_ragged if temperature == 0.0 else generate_ragged_sampled
        )
    else:
        jitted = jax.jit(generate if temperature == 0.0 else generate_sampled)

    def run(
        t_params: Any, d_params: Any, prompt: jax.Array,
        rng: Optional[jax.Array] = None,
        lengths: Optional[jax.Array] = None,
        return_stats: bool = False,
    ):
        if ragged and lengths is None:
            raise ValueError(
                "ragged=True: pass lengths (B,) — each row's true prompt "
                "length in the right-padded prompt batch"
            )
        if not ragged and lengths is not None:
            raise ValueError(
                "lengths requires make_speculative_generate_fn(ragged=True)"
            )
        if return_stats and not ragged:
            raise ValueError("return_stats requires ragged=True")
        with activate(mesh, rules):
            args = [maybe_cast(t_params), maybe_cast(d_params), prompt]
            if ragged:
                args.append(jnp.asarray(lengths, jnp.int32))
            if temperature != 0.0:
                args.append(jax.random.key(0) if rng is None else rng)
            else:
                del rng  # greedy: deterministic, kept for signature symmetry
            result = jitted(*args)
            if ragged:
                out, stats = result
                return (out, stats) if return_stats else out
            return result

    run.jitted = jitted
    return run
