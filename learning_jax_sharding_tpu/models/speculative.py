"""Speculative decoding: a small draft model proposes, the target verifies.

Autoregressive decoding is latency-bound by one serialized target forward per
token. Speculative decoding breaks the serialization: a cheap draft model
greedily proposes ``num_draft`` tokens one-by-one, then the target scores the
whole proposal in ONE chunked forward (the same cache path that serves
prefill, `models/attention.py::_cached_attention` — chunk attention against
the KV cache at the current index). The longest prefix of draft tokens that
matches the target's own greedy choices is accepted, plus one bonus token
from the target's logits — so each round costs one target forward and yields
1..num_draft+1 tokens, and the output is EXACTLY what plain greedy decoding
of the target would produce (the oracle the tests pin).

Nothing like this exists in the reference (no inference path at all,
SURVEY.md §5); it composes the framework's own pieces:

* chunked verification reuses the cache-at-index attention;
* acceptance rollback is just rewinding each block's ``cache_index`` —
  stale K/V entries beyond the index are never attended (the causal mask is
  ``position < index + i``) and are overwritten by the next chunk write;
* batch handling takes the MINIMUM acceptance across rows each round: rows
  that matched further ahead re-derive the same tokens in later rounds (the
  bonus token equals their next draft match), so exactness is preserved and
  only the speedup varies with batch agreement;
* everything runs under mesh + rules — draft and target can use different
  shardings of the same mesh.

Greedy only (``temperature == 0``): that is where acceptance is a hard token
equality and the exactness guarantee is unconditional.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from learning_jax_sharding_tpu.models.decoding import (
    check_sequence_budget,
    derive_decode_config,
    make_cached_apply,
    make_param_caster,
)
from learning_jax_sharding_tpu.models.transformer import Transformer, TransformerConfig
from learning_jax_sharding_tpu.parallel.logical import Rules, activate


def _rollback(cache: Any, index: jax.Array) -> Any:
    """Rewind the decode position counters to ``index``: every attention
    block's ``cache_index`` AND the transformer's top-level ``position``
    (which drives positional embeddings). Stale K/V beyond the index are
    masked out by the causal-at-index attention and later overwritten."""

    def leaf(path, x):
        if getattr(path[-1], "key", None) in ("cache_index", "position"):
            return jnp.full_like(x, index)
        return x

    return jax.tree_util.tree_map_with_path(leaf, cache)


def _greedy(logits: jax.Array) -> jax.Array:
    return jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)


def make_speculative_generate_fn(
    target_config: TransformerConfig,
    draft_config: TransformerConfig,
    mesh: Mesh,
    rules: Rules,
    *,
    max_new_tokens: int,
    num_draft: int = 4,
    inference_dtype: Any | None = None,
):
    """Build ``generate(target_params, draft_params, prompt) -> tokens``.

    ``target_config``/``draft_config`` are TRAINING configs sharing a vocab;
    decode variants are derived here (as in ``make_generate_fn``). The result
    is bit-identical to greedy decoding of the target alone; the draft only
    changes how many serialized target passes it takes to get there.
    """
    if target_config.vocab_size != draft_config.vocab_size:
        raise ValueError(
            f"target vocab {target_config.vocab_size} != draft vocab "
            f"{draft_config.vocab_size}"
        )
    if num_draft < 1:
        raise ValueError(f"num_draft must be >= 1, got {num_draft}")

    t_cfg = derive_decode_config(target_config, inference_dtype)
    d_cfg = derive_decode_config(draft_config, inference_dtype)
    target, draft = Transformer(t_cfg), Transformer(d_cfg)
    t_apply, d_apply = make_cached_apply(target), make_cached_apply(draft)
    maybe_cast = make_param_caster(inference_dtype)

    def generate(t_params, d_params, prompt):
        b, prompt_len = prompt.shape
        # Verification writes up to num_draft+1 positions past the accepted
        # prefix before rolling back, so leave that much headroom.
        need = prompt_len + max_new_tokens + num_draft + 1
        for name, cfg in (("target", t_cfg), ("draft", d_cfg)):
            check_sequence_budget(
                need, cfg.max_seq_len, f"prompt+new+draft for {name}"
            )

        # Prefill both models on the prompt. The first new token comes from
        # the target's last-position logits — exactly as plain greedy.
        t_logits, t_cache = t_apply(t_params, None, prompt)
        _, d_cache = d_apply(d_params, None, prompt)
        t_cur = _greedy(t_logits[:, -1])

        buf_len = max_new_tokens + num_draft + 1
        buffer = jnp.zeros((b, buf_len), jnp.int32)
        buffer = lax.dynamic_update_slice(buffer, t_cur[:, None], (0, 0))

        def cond(carry):
            n, *_ = carry
            return n < max_new_tokens

        def body(carry):
            n, t_cur, t_cache, d_cache, buffer = carry
            # Invariant: both caches hold prompt + the n-1 accepted tokens
            # BEFORE t_cur (t_cur itself is pending, fed by this round).
            base = prompt_len + n - 1

            # 1. Draft proposes num_draft tokens greedily, one at a time;
            #    one extra feed pushes the last proposal's K/V into the draft
            #    cache so a full acceptance leaves the cache complete.
            def draft_step(carry, _):
                prev, cache = carry
                logits, cache = d_apply(d_params, cache, prev[:, None])
                nxt = _greedy(logits[:, -1])
                return (nxt, cache), nxt

            (last_d, d_cache), drafts = lax.scan(
                draft_step, (t_cur, d_cache), None, length=num_draft
            )
            drafts = drafts.T  # (num_draft, B) scan stack → (B, num_draft)
            _, d_cache = d_apply(d_params, d_cache, last_d[:, None])

            # 2. Target verifies the whole proposal in one chunked forward:
            #    [t_cur, d_1..d_num_draft] → greedy choice after each.
            chunk = jnp.concatenate([t_cur[:, None], drafts], axis=1)
            t_logits, t_cache = t_apply(t_params, t_cache, chunk)
            choices = _greedy(t_logits)  # (B, num_draft+1)

            # 3. Accept the longest prefix where draft == target choice;
            #    batch-min keeps a single scalar cache index.
            eq = drafts == choices[:, :-1]  # (B, num_draft)
            m_row = jnp.sum(jnp.cumprod(eq.astype(jnp.int32), axis=1), axis=1)
            m = jnp.min(m_row)  # scalar: accepted draft count this round

            # 4. Emit d_1..d_m then the bonus/correction token choices[:, m].
            #    Positions past m hold the bonus too — junk that later rounds
            #    overwrite (and the final slice drops).
            idx = jnp.arange(num_draft + 1)
            bonus = jnp.take_along_axis(choices, jnp.full((b, 1), m), axis=1)[:, 0]
            padded = jnp.pad(drafts, ((0, 0), (0, 1)))  # (B, num_draft+1)
            emitted = jnp.where(idx[None, :] < m, padded, bonus[:, None])
            # buffer[i] is the (i+1)-th generated token; t_cur sits at n-1,
            # so this round's tokens start at n.
            buffer = lax.dynamic_update_slice(buffer, emitted, (0, n))

            # 5. Roll both caches back to the accepted length. The target
            #    consumed base..base+num_draft; valid prefix is base + 1 + m
            #    (t_cur and the m accepted drafts). Same for the draft.
            accepted = base + 1 + m
            t_cache = _rollback(t_cache, accepted)
            d_cache = _rollback(d_cache, accepted)

            return (n + 1 + m, bonus, t_cache, d_cache, buffer)

        n, _, _, _, buffer = lax.while_loop(
            cond, body, (jnp.asarray(1, jnp.int32), t_cur, t_cache, d_cache, buffer)
        )
        return jnp.concatenate([prompt, buffer[:, :max_new_tokens]], axis=1)

    jitted = jax.jit(generate)

    def run(
        t_params: Any, d_params: Any, prompt: jax.Array,
        rng: Optional[jax.Array] = None,
    ):
        del rng  # greedy: deterministic, kept for signature symmetry
        with activate(mesh, rules):
            return jitted(maybe_cast(t_params), maybe_cast(d_params), prompt)

    run.jitted = jitted
    return run
