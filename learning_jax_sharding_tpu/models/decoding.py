"""Shared serving-path plumbing for the decoding entry points.

``make_generate_fn`` (sampling), ``make_beam_search_fn`` (beam search), and
``make_speculative_generate_fn`` (draft-verify) all need the same four
pieces; this module is their single copy, so policies like "how params are
cast for inference" or "how quantized trees are handled" cannot drift
between decoders:

* :func:`derive_decode_config` — turn a TRAINING config into its decode
  variant (KV caches on, dropout off, optional inference dtype swap);
* :func:`make_param_caster` — the eager params cast for ``inference_dtype``
  (eager on purpose: an in-program cast re-runs every scan step — measured
  20% slower on the v5e decode bench — and keeps the fp32 copies resident),
  quantization-aware: quantized ``{"q","scale"}`` / ``{"q4","scale"}``
  nodes pass through untouched;
* :func:`make_cached_apply` — the mutable-cache model apply every decoder
  loops over (prefill creates the caches, later calls thread them), with
  optional in-jit dequantization of int8/int4 weight trees;
* :func:`check_sequence_budget` — the prompt+new vs ``max_seq_len`` guard.

(The reference has no inference path at all, SURVEY.md §5 — these helpers
back the serving stack that replaces its timing-only ``apply_fn``,
`/root/reference/case6_attention.py:229-238`.)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from learning_jax_sharding_tpu.models.transformer import TransformerConfig


def derive_decode_config(
    config: TransformerConfig,
    inference_dtype: Any | None = None,
    *,
    mesh: Any | None = None,
    rules: Any | None = None,
) -> TransformerConfig:
    """Decode variant of a TRAINING config: KV caches on, dropout off, and —
    when ``inference_dtype`` is given — compute/param dtypes swapped to it,
    so train and serve share params verbatim.

    With ``mesh``/``rules`` and more than one device, the blocked decode
    backend gets its shard_map wrapper injected
    (``ops.decode_attention.make_decode_attn_fn``) — GSPMD cannot partition
    the Pallas cache kernel by itself, so multi-device serving needs the
    explicitly sharded call."""
    # Decode always runs the UNROLLED stack: scan_layers is a compile-time
    # lever for training depth; its stacked params are unstacked at serve
    # time (make_param_caster), so train-with-scan → generate just works.
    cfg = dataclasses.replace(
        config, decode=True, dropout_rate=0.0, scan_layers=False
    )
    if inference_dtype is not None:
        cfg = dataclasses.replace(
            cfg, dtype=inference_dtype, param_dtype=inference_dtype
        )
    if mesh is not None and rules is not None and cfg.decode_attn_fn is None:
        from learning_jax_sharding_tpu.models.attention import resolve_decode_backend

        if mesh.size > 1 and resolve_decode_backend(cfg.decode_attention) == "blocked":
            from learning_jax_sharding_tpu.ops.decode_attention import (
                make_decode_attn_fn,
            )

            # window/block_k are NOT baked: the attention module passes its
            # own on every call (single source of truth).
            cfg = dataclasses.replace(
                cfg, decode_attn_fn=make_decode_attn_fn(mesh, rules)
            )
    return cfg


def apply_dequantize_policy(
    cfg: TransformerConfig, dequantize: bool | str, mesh: Any, rules: Any
) -> tuple[TransformerConfig, bool]:
    """THE quantized-serving policy, shared by every decoder
    (``make_generate_fn``, the continuous engine) so it cannot drift:
    validates the ``dequantize`` mode, and for the fused modes sets the
    config's ``quantization`` so int4 trees apply VERBATIM through the
    fused dequant-matmul kernels (``models/quantize.py::Int4Dense``) — no
    in-jit dequantize_tree, no dequantized weights in HBM. On >1-device
    meshes the kernel runs under an injected shard_map matmul (GSPMD
    cannot partition the custom call and would gather the packed
    weights). ``"fused_w4a8"`` additionally quantizes activations per-row
    to int8 so the contraction runs int8×int4→int32 on the MXU.

    Returns ``(cfg, fused)`` — callers build their cached apply with
    ``dequantize=bool(dequantize) and not fused`` and their param caster
    with ``dequantize=bool(dequantize)``."""
    if isinstance(dequantize, str) and dequantize not in (
        "fused", "fused_w4a8"
    ):
        raise ValueError(
            f"dequantize must be False, True, 'fused', or 'fused_w4a8'; "
            f"got {dequantize!r}"
        )
    fused = dequantize in ("fused", "fused_w4a8")
    if fused:
        w4a8 = dequantize == "fused_w4a8"
        cfg = dataclasses.replace(
            cfg, quantization="int4_w4a8" if w4a8 else "int4"
        )
        if mesh.size > 1:
            from learning_jax_sharding_tpu.ops.int4_matmul import (
                make_int4_matmul_fn,
            )

            cfg = dataclasses.replace(
                cfg,
                quantized_matmul_fn=make_int4_matmul_fn(
                    mesh, rules, w4a8=w4a8
                ),
            )
    return cfg, fused


def make_param_caster(
    inference_dtype: Any | None, *, dequantize: bool = False
) -> Callable[[Any], Any]:
    """Eager ``maybe_cast(params)`` for serving.

    Casts floating leaves to ``inference_dtype`` (identity when ``None``).
    With ``dequantize`` the tree holds int8/int4 quantized nodes from
    ``models.quantize.quantize_tree``: those stay untouched (the in-jit
    dequant picks the target dtype) while everything else — embeddings,
    norms, biases, often the largest remaining fp32 blocks — still casts.
    """

    def maybe_cast(params: Any) -> Any:
        # Trees trained with scan_layers arrive in the stacked "blocks"
        # layout; decode always runs the unrolled stack (derive_decode_config
        # flips scan_layers off), so unstack here — eagerly, once per call,
        # like the dtype cast (slicing per decode step inside jit would
        # re-materialize every layer's weights each token).
        if isinstance(params, dict) and "blocks" in params:
            from learning_jax_sharding_tpu.models.convert import unstack_scan_params

            params = unstack_scan_params(params)
        if inference_dtype is None:
            return params

        def cast(x):
            return (
                x.astype(inference_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x
            )

        if not dequantize:
            return jax.tree.map(cast, params)
        from learning_jax_sharding_tpu.models.quantize import map_unquantized

        return map_unquantized(cast, params)

    return maybe_cast


def make_cached_apply(
    model: Any, *, dequantize: bool = False, dequant_dtype: Any | None = None
) -> Callable[[Any, Any, jax.Array], tuple[jax.Array, Any]]:
    """The decode-loop workhorse: ``apply(params, cache, tokens) ->
    (fp32 logits, new cache)``.

    With ``cache=None`` the mutable apply CREATES the (zeroed) caches — that
    is the prefill call; later calls thread the cache through. With
    ``dequantize`` the int8/int4 tree is dequantized INSIDE each apply so
    the decode scan holds only quantized weights in its carry/constants (the
    storage win); whether XLA streams them into the matmuls or materializes
    the upcast is its call — ``bench.py`` measures it.
    """

    def apply(params: Any, cache: Any, tokens: jax.Array, chunk_lengths=None):
        if dequantize:
            from learning_jax_sharding_tpu.models.quantize import dequantize_tree

            params = dequantize_tree(params, dequant_dtype)
        variables = {"params": params}
        if cache is not None:
            variables["cache"] = cache
        kwargs = {}
        if chunk_lengths is not None:  # ragged decode only (decode_ragged)
            kwargs["chunk_lengths"] = chunk_lengths
        logits, mut = model.apply(
            variables, tokens, mutable=("cache",), **kwargs
        )
        return logits.astype(jnp.float32), mut["cache"]

    return apply


def check_sequence_budget(needed: int, max_seq_len: int, what: str) -> None:
    """Raise if a decode plan would write past the KV caches."""
    if needed > max_seq_len:
        raise ValueError(f"{what} ({needed}) exceeds max_seq_len ({max_seq_len})")
