"""Beam search decoding over the sharded KV-cache stack.

Sampling (``models/generate.py``) explores one path; beam search keeps the
``beam_size`` highest-logprob prefixes at every step and returns the best
complete sequence — the standard serving decoder the greedy path can't
replace. Nothing like it exists in the reference (no inference path at all,
SURVEY.md §5).

TPU-shaped implementation:

* beams fold into the batch: all caches and forwards run at ``B·K`` rows, so
  each decode step is ONE chunked model apply — no per-beam loops;
* beam reordering is a batched ``jnp.take`` of every cache leaf along its
  leading dim inside the same jitted scan step (XLA lowers it to a gather
  that follows the cache's sharding — batch stays on the ``data`` axis);
* everything is static-shaped: ``lax.scan`` over ``max_new_tokens`` steps,
  top-2K over the flattened ``K·V`` continuation scores per batch row.

With ``eos_id`` set, hypotheses that emit EOS leave the live set for a
separate **finished pool** of size K (scores length-normalized by
``length**length_penalty`` at finishing time), and the live slots keep
exploring — a completed hypothesis can never be evicted by a live prefix
that later decays below it, the guarantee that makes beam search return the
best sequence it ever found (same pool discipline as t5x/flax beam search).
Expanding 2K candidates guarantees K live survivors: at most one candidate
per parent ends in EOS. Without an EOS every beam has equal length and the
length penalty cancels.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from learning_jax_sharding_tpu.models.decoding import (
    check_sequence_budget,
    derive_decode_config,
    make_cached_apply,
    make_param_caster,
)
from learning_jax_sharding_tpu.models.transformer import Transformer, TransformerConfig
from learning_jax_sharding_tpu.parallel.logical import Rules, activate

NEG_INF = -1e9


def _gather_beams(tree: Any, parent: jax.Array, batch: int, k: int) -> Any:
    """Reorder the leading ``B·K`` dim of every array leaf to follow
    ``parent`` (B, K) beam indices. Ragged counters (``cache_index`` /
    ``position`` at ``(B·K,)``) gather too — a no-op value-wise, since a
    row's beams always hold equal positions."""
    flat = (jnp.arange(batch)[:, None] * k + parent).reshape(-1)  # (B·K,)

    def leaf(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == batch * k:
            return jnp.take(x, flat, axis=0)
        return x  # scalars: rectangular cache_index / position

    return jax.tree.map(leaf, tree)


def make_beam_search_fn(
    config: TransformerConfig,
    mesh: Mesh,
    rules: Rules,
    *,
    beam_size: int,
    max_new_tokens: int,
    eos_id: Optional[int] = None,
    vocab_limit: Optional[int] = None,
    length_penalty: float = 1.0,
    inference_dtype: Any | None = None,
    dequantize: bool = False,
    ragged: bool = False,
):
    """Build ``search(params, prompt) -> (tokens, scores)``.

    ``tokens`` is the best hypothesis per row, ``(B, prompt+max_new)``, with
    everything after an EOS padded with EOS; ``scores`` its
    length-normalized sequence logprob, ``(B,)``. ``config`` is the TRAINING
    config; the decode variant is derived here. ``inference_dtype`` /
    ``dequantize`` follow ``make_generate_fn`` (eager cast; int8 trees
    dequantized in-jit).

    ``ragged``: mixed-length prompt batches. ``search(params, prompt,
    lengths)`` takes the right-padded prompt plus per-row true lengths;
    every row's beams expand from ITS last valid position over per-row
    cache positions (beams of one row always advance together, so the
    beam fold needs no freezing — only the prefill gather and the output
    placement are per-row). Output rows follow the ragged
    ``make_generate_fn`` convention: ``[prompt_b, best hypothesis...,
    fill]`` with the generated span starting at ``lengths[b]``. Per-row
    results are bit-identical to a rectangular search of each row alone
    at its true length (test-pinned, dense and blocked backends).
    """
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    if config.vocab_size < 2 * beam_size:
        raise ValueError(
            f"vocab_size ({config.vocab_size}) must be >= 2*beam_size "
            f"({2 * beam_size}) for the 2K candidate expansion"
        )
    if config.decode_paged:
        raise ValueError(
            "beam search over a paged cache is not supported: beams tile "
            "the batch, which would need per-beam block tables (use the "
            "continuous engine for paged serving)"
        )
    cfg = derive_decode_config(config, inference_dtype, mesh=mesh, rules=rules)
    if ragged:
        import dataclasses as _dc

        cfg = _dc.replace(cfg, decode_ragged=True)
    model = Transformer(cfg)
    maybe_cast = make_param_caster(inference_dtype, dequantize=dequantize)
    apply = make_cached_apply(
        model, dequantize=dequantize, dequant_dtype=cfg.param_dtype
    )
    k = beam_size

    def norm(length):
        return jnp.power(jnp.asarray(length, jnp.float32), length_penalty)

    def expand(scores_2k, tokens_2k, cand_buf, pos, fin_scores, fin_buf):
        """Split 2K candidates into EOS-finished (→ merge into the K-slot
        finished pool, normalized at their final length ``pos+1``) and live
        (→ top-K raw scores). Returns the updated pool and the live pick."""
        is_eos = (
            tokens_2k == eos_id if eos_id is not None
            else jnp.zeros_like(tokens_2k, bool)
        )
        # Finished candidates: freeze the suffix to EOS so the returned
        # sequence is cleanly padded, then keep the best K of pool ∪ new.
        if eos_id is not None:
            padded = jnp.where(
                jnp.arange(cand_buf.shape[-1])[None, None] > pos,
                eos_id, cand_buf,
            )
            cand_fin = jnp.where(is_eos, scores_2k / norm(pos + 1), NEG_INF)
            all_scores = jnp.concatenate([fin_scores, cand_fin], axis=1)
            all_buf = jnp.concatenate([fin_buf, padded], axis=1)
            fin_scores, fin_idx = lax.top_k(all_scores, k)
            fin_buf = jnp.take_along_axis(all_buf, fin_idx[:, :, None], axis=1)
        # Live candidates: EOS rows drop out (at most one per parent, so at
        # least K of 2K remain).
        live_scores, live_idx = lax.top_k(
            jnp.where(is_eos, NEG_INF, scores_2k), k
        )
        return fin_scores, fin_buf, live_scores, live_idx

    def search(params, prompt, lengths=None):
        b, prompt_len = prompt.shape
        check_sequence_budget(
            prompt_len + max_new_tokens, cfg.max_seq_len,
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens})",
        )
        # Prefill ONCE at batch B, then tile the caches to the (B·K) serving
        # shape inside the same jitted program — prefill FLOPs don't scale
        # with beam_size, and the decode loop still runs at a single static
        # B·K batch (row-major: a row's beams are adjacent).
        if vocab_limit is not None:
            from learning_jax_sharding_tpu.models.generate import vocab_limit_filter

            limit = lambda lg: vocab_limit_filter(lg, vocab_limit)
        else:
            limit = lambda lg: lg
        if ragged:
            # Ragged prefill: per-row cache positions; the seed logits come
            # from each row's own last VALID position, not column -1.
            logits_all, cache = apply(params, None, prompt, lengths)
            last_logits = jnp.take_along_axis(
                logits_all, (lengths - 1)[:, None, None], axis=1
            )[:, 0]
        else:
            logits, cache = apply(params, None, prompt)
            last_logits = logits[:, -1]
        cache = jax.tree.map(
            lambda x: jnp.repeat(x, k, axis=0)
            if getattr(x, "ndim", 0) >= 1 and x.shape[0] == b else x,
            cache,
        )
        logp0 = jax.nn.log_softmax(limit(last_logits))  # (B, V)
        vocab = logp0.shape[-1]

        fin_scores = jnp.full((b, k), NEG_INF)
        fin_buf = jnp.zeros((b, k, max_new_tokens), jnp.int32)

        # First expansion: the K beams of a row are identical here, so the
        # top-2K tokens of the single prefill row seed the pools (a K·V
        # top-k would K-fold duplicate each candidate).
        scores_2k, tok_2k = lax.top_k(logp0, 2 * k)  # (B, 2K) each
        cand_buf = jnp.zeros((b, 2 * k, max_new_tokens), jnp.int32)
        cand_buf = cand_buf.at[:, :, 0].set(tok_2k)
        fin_scores, fin_buf, scores, live_idx = expand(
            scores_2k, tok_2k, cand_buf, 0, fin_scores, fin_buf
        )
        tokens_buf = jnp.take_along_axis(cand_buf, live_idx[:, :, None], axis=1)
        # All K beams share the one prefill cache row — no gather needed.

        def step(carry, i):
            scores, tokens_buf, fin_scores, fin_buf, cache = carry
            last = lax.dynamic_index_in_dim(
                tokens_buf, i - 1, axis=2, keepdims=False
            )  # (B, K)
            logits, cache = apply(params, cache, last.reshape(b * k, 1))
            logp = jax.nn.log_softmax(limit(logits[:, -1])).reshape(b, k, vocab)
            total = scores[:, :, None] + logp  # (B, K, V)
            scores_2k, flat_idx = lax.top_k(total.reshape(b, k * vocab), 2 * k)
            parent_2k = flat_idx // vocab  # (B, 2K)
            tok_2k = (flat_idx % vocab).astype(jnp.int32)

            cand_buf = _gather_beams(
                tokens_buf.reshape(b * k, -1),
                parent_2k.reshape(b, 2 * k), b, k,
            ).reshape(b, 2 * k, -1)
            cand_buf = cand_buf.at[:, :, i].set(tok_2k)

            fin_scores, fin_buf, scores, live_idx = expand(
                scores_2k, tok_2k, cand_buf, i, fin_scores, fin_buf
            )
            tokens_buf = jnp.take_along_axis(
                cand_buf, live_idx[:, :, None], axis=1
            )
            parent = jnp.take_along_axis(parent_2k, live_idx, axis=1)
            cache = _gather_beams(cache, parent, b, k)
            return (scores, tokens_buf, fin_scores, fin_buf, cache), None

        (scores, tokens_buf, fin_scores, fin_buf, _), _ = lax.scan(
            step,
            (scores, tokens_buf, fin_scores, fin_buf, cache),
            jnp.arange(1, max_new_tokens),
        )

        # Final selection: live hypotheses (all at full length) join the
        # finished pool on normalized scores; with no EOS the pool is empty
        # (all NEG_INF) and the best live beam wins as before.
        live_final = scores / norm(max_new_tokens)
        all_scores = jnp.concatenate([fin_scores, live_final], axis=1)
        all_buf = jnp.concatenate([fin_buf, tokens_buf], axis=1)
        best = jnp.argmax(all_scores, axis=1)  # (B,)
        best_tokens = jnp.take_along_axis(
            all_buf, best[:, None, None], axis=1
        )[:, 0]
        best_score = jnp.take_along_axis(all_scores, best[:, None], axis=1)[:, 0]
        if not ragged:
            return (
                jnp.concatenate([prompt, best_tokens], axis=1),
                best_score,
            )
        # Ragged assembly: row b's hypothesis starts at ITS length; every
        # cell past it — including the caller's prompt padding — becomes
        # the fill value (eos when set), matching make_generate_fn.
        fill = 0 if eos_id is None else eos_id
        total = prompt_len + max_new_tokens
        col = jnp.arange(total)[None, :]
        outp = jnp.where(
            col < lengths[:, None],
            jnp.pad(prompt, ((0, 0), (0, max_new_tokens))),
            fill,
        )
        rows = jnp.arange(b)[:, None]
        cols = lengths[:, None] + jnp.arange(max_new_tokens)[None, :]
        return outp.at[rows, cols].set(best_tokens), best_score

    jitted = jax.jit(search)

    def run(params: Any, prompt: jax.Array, lengths=None):
        if ragged and lengths is None:
            raise ValueError(
                "ragged=True: pass lengths (B,) — each row's true prompt "
                "length in the right-padded prompt batch"
            )
        if not ragged and lengths is not None:
            raise ValueError("lengths requires make_beam_search_fn(ragged=True)")
        with activate(mesh, rules):
            if ragged:
                return jitted(
                    maybe_cast(params), prompt, jnp.asarray(lengths, jnp.int32)
                )
            return jitted(maybe_cast(params), prompt)

    run.jitted = jitted
    return run
