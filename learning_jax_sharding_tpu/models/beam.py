"""Beam search decoding over the sharded KV-cache stack.

Sampling (``models/generate.py``) explores one path; beam search keeps the
``beam_size`` highest-logprob prefixes at every step and returns the best
complete sequence — the standard serving decoder the greedy path can't
replace. Nothing like it exists in the reference (no inference path at all,
SURVEY.md §5).

TPU-shaped implementation:

* beams fold into the batch: all caches and forwards run at ``B·K`` rows, so
  each decode step is ONE chunked model apply — no per-beam loops;
* beam reordering is a batched ``jnp.take`` of every cache leaf along its
  leading dim inside the same jitted scan step (XLA lowers it to a gather
  that follows the cache's sharding — batch stays on the ``data`` axis);
* everything is static-shaped: ``lax.scan`` over ``max_new_tokens`` steps,
  top-k over the flattened ``K·V`` continuation scores per batch row.

Optional ``eos_id``: finished beams are frozen (their only continuation is a
repeated EOS at zero added logprob) and scores are length-normalized by
``(length)**length_penalty`` — without an EOS every beam has equal length
and the penalty cancels.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from learning_jax_sharding_tpu.models.transformer import Transformer, TransformerConfig
from learning_jax_sharding_tpu.parallel.logical import Rules, activate

NEG_INF = -1e9


def _gather_beams(tree: Any, parent: jax.Array, batch: int, k: int) -> Any:
    """Reorder the leading ``B·K`` dim of every array leaf to follow
    ``parent`` (B, K) beam indices."""
    flat = (jnp.arange(batch)[:, None] * k + parent).reshape(-1)  # (B·K,)

    def leaf(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == batch * k:
            return jnp.take(x, flat, axis=0)
        return x  # scalars: cache_index / position, shared across beams

    return jax.tree.map(leaf, tree)


def make_beam_search_fn(
    config: TransformerConfig,
    mesh: Mesh,
    rules: Rules,
    *,
    beam_size: int,
    max_new_tokens: int,
    eos_id: Optional[int] = None,
    length_penalty: float = 1.0,
    inference_dtype: Any | None = None,
):
    """Build ``search(params, prompt) -> (tokens, scores)``.

    ``tokens`` is the best beam per row, ``(B, prompt+max_new)``; ``scores``
    its length-normalized sequence logprob, ``(B,)``. ``config`` is the
    TRAINING config; the decode variant is derived here (as in
    ``make_generate_fn``).
    """
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    cfg = dataclasses.replace(config, decode=True, dropout_rate=0.0)
    if inference_dtype is not None:
        cfg = dataclasses.replace(
            cfg, dtype=inference_dtype, param_dtype=inference_dtype
        )
    model = Transformer(cfg)
    k = beam_size

    def apply(params, cache, tokens):
        variables = {"params": params}
        if cache is not None:
            variables["cache"] = cache
        logits, mut = model.apply(variables, tokens, mutable=("cache",))
        return logits.astype(jnp.float32), mut["cache"]

    def search(params, prompt):
        b, prompt_len = prompt.shape
        if prompt_len + max_new_tokens > cfg.max_seq_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_seq_len ({cfg.max_seq_len})"
            )
        # Prefill ONCE at batch B, then tile the caches to the (B·K) serving
        # shape inside the same jitted program — prefill FLOPs don't scale
        # with beam_size, and the decode loop still runs at a single static
        # B·K batch (row-major: a row's beams are adjacent).
        logits, cache = apply(params, None, prompt)
        cache = jax.tree.map(
            lambda x: jnp.repeat(x, k, axis=0)
            if getattr(x, "ndim", 0) >= 1 and x.shape[0] == b else x,
            cache,
        )
        logp0 = jax.nn.log_softmax(logits[:, -1])  # (B, V)
        vocab = logp0.shape[-1]

        # First expansion: the K beams of a row are identical here, so the
        # top-K tokens of the single prefill row seed the K beams (a K·V
        # top-k would K-fold duplicate each candidate).
        scores, first_tok = lax.top_k(logp0, k)  # (B, K) each
        tokens_buf = jnp.zeros((b, k, max_new_tokens), jnp.int32)
        tokens_buf = tokens_buf.at[:, :, 0].set(first_tok)
        finished = (
            first_tok == eos_id if eos_id is not None
            else jnp.zeros((b, k), bool)
        )
        lengths = jnp.ones((b, k), jnp.int32)

        def step(carry, i):
            scores, tokens_buf, finished, lengths, cache = carry
            last = lax.dynamic_index_in_dim(
                tokens_buf, i - 1, axis=2, keepdims=False
            )  # (B, K)
            logits, cache = apply(params, cache, last.reshape(b * k, 1))
            logp = jax.nn.log_softmax(logits[:, -1]).reshape(b, k, vocab)
            if eos_id is not None:
                # Frozen beams may only emit EOS again, at no cost — keeps
                # their score comparable while occupying one candidate slot.
                frozen = jnp.full((vocab,), NEG_INF).at[eos_id].set(0.0)
                logp = jnp.where(finished[:, :, None], frozen[None, None], logp)
            total = scores[:, :, None] + logp  # (B, K, V)
            scores, flat_idx = lax.top_k(total.reshape(b, k * vocab), k)
            parent = flat_idx // vocab  # (B, K)
            token = (flat_idx % vocab).astype(jnp.int32)

            tokens_buf = _gather_beams(
                tokens_buf.reshape(b * k, -1), parent, b, k
            ).reshape(b, k, -1)
            finished = jnp.take_along_axis(finished, parent, axis=1)
            lengths = jnp.take_along_axis(lengths, parent, axis=1)
            cache = _gather_beams(cache, parent, b, k)

            tokens_buf = tokens_buf.at[:, :, i].set(token)
            lengths = lengths + (~finished).astype(jnp.int32)
            if eos_id is not None:
                finished = finished | (token == eos_id)
            return (scores, tokens_buf, finished, lengths, cache), None

        (scores, tokens_buf, finished, lengths, _), _ = lax.scan(
            step,
            (scores, tokens_buf, finished, lengths, cache),
            jnp.arange(1, max_new_tokens),
        )

        norm = jnp.power(lengths.astype(jnp.float32), length_penalty)
        final = scores / norm
        best = jnp.argmax(final, axis=1)  # (B,)
        best_tokens = jnp.take_along_axis(
            tokens_buf, best[:, None, None], axis=1
        )[:, 0]
        best_score = jnp.take_along_axis(final, best[:, None], axis=1)[:, 0]
        return (
            jnp.concatenate([prompt, best_tokens], axis=1),
            best_score,
        )

    jitted = jax.jit(search)

    def maybe_cast(params):
        # Eager, like make_generate_fn: an in-program cast re-runs every
        # scan step (measured 20% slower there) and keeps fp32 copies
        # resident.
        if inference_dtype is None:
            return params
        return jax.tree.map(
            lambda x: x.astype(inference_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            params,
        )

    def run(params: Any, prompt: jax.Array):
        with activate(mesh, rules):
            return jitted(maybe_cast(params), prompt)

    run.jitted = jitted
    return run
