"""Weight hot-swap staging helpers: the thin operator-facing layer over
:mod:`parallel.resharding` and the engine's swap state machine.

The swap itself lives in :meth:`ContinuousEngine.swap_weights` /
``FleetRouter.rolling_swap`` (staging, drain, atomic commit, version
attribution). What belongs HERE is the part an operator script touches:
pre-staging a checkpointed tree into the serving layout before handing
it to the engine, and persisting the swap timeline artifact the cases
and dashboards read.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import jax

from learning_jax_sharding_tpu.parallel.resharding import reshard_tree


def serving_shardings(tree: Any) -> Any:
    """The sharding tree of live serving weights — the destination
    layout ``stage_params`` reshards a trained/restored tree into."""
    return jax.tree.map(lambda x: x.sharding, tree)


def stage_params(
    params: Any,
    dst_shardings: Any,
    *,
    plan_cache: dict | None = None,
    jit_cache: dict | None = None,
    mode: str = "auto",
) -> tuple[Any, dict]:
    """Reshard ``params`` into the serving layout OFF the dispatch hot
    path; returns ``(staged_tree, stats)`` with the moved bytes/segments
    telemetry. A training loop that swaps every N steps passes the same
    caches each time so the transfer plan (and the device path's
    compiled mover) is built once. ``engine.swap_weights`` runs this
    same resharding internally when handed an unstaged tree — calling
    it here first just moves the cost to the trainer's thread."""
    return reshard_tree(
        params, dst_shardings,
        plan_cache=plan_cache, jit_cache=jit_cache, mode=mode,
    )


def write_swap_timeline(path: str | Path, timeline: list[dict]) -> Path:
    """Persist a swap/rollout timeline (list of JSON-able event dicts —
    ``FleetRouter.rolling_swap`` returns one; a single-engine driver can
    assemble its own from the flight recorder) as the case artifact
    dashboards replay."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(timeline, indent=2, sort_keys=True) + "\n")
    return p
