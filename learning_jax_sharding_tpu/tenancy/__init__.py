"""Tenancy (round 12): serve while training — zero-downtime weight
hot-swap and multi-LoRA multi-tenant serving.

Two pillars, both riding the page-granular redistribution algebra in
:mod:`parallel.resharding`:

* **Hot-swap** — ``ContinuousEngine.swap_weights`` stages a new weight
  version into the serving layout off the hot path and commits it
  atomically between dispatches (in-flight requests finish on the old
  version or recompute bit-identically under the new one — never a
  silent mid-sequence change); ``FleetRouter.rolling_swap`` walks a
  fleet one replica at a time so aggregate serving never drops to zero.
* **Multi-LoRA** — :class:`.adapter_pool.AdapterPool` pages tenants'
  LoRA adapters into one stacked tree; the engine's fused
  ``adapter_mixed_step`` gathers each row's adapter by slot index on
  device, so ONE program serves every tenant in a batch, bit-identical
  to each tenant served solo against ``merge_lora``-folded weights.

This module is the import surface: the pool, and the thin staging /
artifact helpers in :mod:`.hot_swap`.
"""

from learning_jax_sharding_tpu.tenancy.adapter_pool import (  # noqa: F401
    DEFAULT_PAGE_BYTES,
    AdapterPool,
)
from learning_jax_sharding_tpu.tenancy.hot_swap import (  # noqa: F401
    serving_shardings,
    stage_params,
    write_swap_timeline,
)
