"""Multi-LoRA adapter pool: a stacked, paged bank of tenant adapters the
fused serving programs gather from per row.

One :class:`AdapterPool` holds up to ``slots`` tenants' LoRA adapters as
a SINGLE stacked pytree: every adapted kernel path carries
``{"lora_a": (S, d_in, r), "lora_b": (S, r, d_out), "scale": (S,)}``.
The engine passes the whole stack into ``adapter_mixed_step`` as an
ordinary argument (stable treedef → stable compile) together with a
per-row slot index; the program gathers each row's slice on device and
applies that row through its own tenant's merged weights — one fused
program serves every tenant in the batch, bit-identical to each tenant
served solo against ``merge_lora``-folded weights.

Slot 0 is RESERVED for the base model (the zero adapter,
:func:`training.lora.zero_lora` semantics): rows with no adapter gather
slot 0 and ``W + scale·(A@B)`` adds exact zero. Named tenants occupy
slots 1..S-1.

Paging here is an ACCOUNTING layer, deliberately unlike the engine's KV
page pool: the stacked tree is preallocated at construction (stable
shapes are what keep the fused program compile-stable), so "pages" are
not dynamically allocated buffers — they are the capacity ledger
(``ceil(per-slot bytes / page_bytes)`` pages per slot) that
``capacity_pages`` caps and the ``engine_adapter_pool_pages_in_use``
gauge reports. Admitting a tenant past the cap evicts the
least-recently-used adapter with ZERO in-flight requests; tenants with
live requests are never evicted (the engine holds a refcount per
admitted request via :meth:`acquire`/:meth:`release`).

Hot-add is functional: :meth:`add` writes the new tenant's factors with
``.at[slot].set`` — a fresh stacked tree of identical shape, so a
serving engine picks it up at its next dispatch with no recompile and
no pause.
"""

from __future__ import annotations

import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from learning_jax_sharding_tpu.training.lora import (
    LoraState,
    default_match,
)

DEFAULT_PAGE_BYTES = 1 << 20


def _is_pool_node(node: Any) -> bool:
    return (
        isinstance(node, dict)
        and set(node) == {"lora_a", "lora_b", "scale"}
    )


class AdapterPool:
    """Stacked multi-tenant LoRA bank; see module docstring.

    Built from the BASE param tree's structure: every leaf matched by
    ``match`` (default: 2D kernels) gets a stacked factor pair. ``rank``
    and ``slots`` fix the stack's shapes for the engine's lifetime.
    When the base leaves carry :class:`NamedSharding`, the stack
    inherits the adapters' serving placement (A row-sharded, B
    col-sharded — ``training.lora.lora_shardings`` with a replicated
    slot dim in front), so the on-device gather needs no resharding.
    """

    def __init__(
        self,
        params: Any,
        *,
        slots: int,
        rank: int,
        match: Callable = default_match,
        dtype: Any = None,
        mesh: Mesh | None = None,
        page_bytes: int = DEFAULT_PAGE_BYTES,
        capacity_pages: int | None = None,
    ):
        if slots < 2:
            raise ValueError(
                f"slots must be >= 2 (slot 0 is the reserved base "
                f"tenant), got {slots}"
            )
        self.slots = int(slots)
        self.rank = int(rank)
        self.page_bytes = int(page_bytes)

        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        tree: dict = {}
        slot_bytes = 0
        n_nodes = 0
        for keypath, leaf in flat:
            path = tuple(getattr(k, "key", str(k)) for k in keypath)
            if not match(path, leaf):
                continue
            d_in, d_out = leaf.shape
            dt = jnp.dtype(dtype or leaf.dtype)
            sh = getattr(leaf, "sharding", None)
            if mesh is not None and isinstance(sh, NamedSharding):
                spec = tuple(sh.spec) + (None,) * (2 - len(sh.spec))
                sh_a = NamedSharding(mesh, PartitionSpec(None, spec[0], None))
                sh_b = NamedSharding(mesh, PartitionSpec(None, None, spec[1]))
                sh_s = NamedSharding(mesh, PartitionSpec(None))
            elif mesh is not None:
                sh_a = sh_b = sh_s = NamedSharding(mesh, PartitionSpec())
            else:
                sh_a = sh_b = sh_s = None

            def zeros(shape, d, s):
                z = jnp.zeros(shape, d)
                return jax.device_put(z, s) if s is not None else z

            node = tree
            for k in path[:-1]:
                node = node.setdefault(k, {})
            node[path[-1]] = {
                "lora_a": zeros((slots, d_in, rank), dt, sh_a),
                "lora_b": zeros((slots, rank, d_out), dt, sh_b),
                "scale": zeros((slots,), jnp.float32, sh_s),
            }
            slot_bytes += (d_in + d_out) * rank * dt.itemsize + 4
            n_nodes += 1
        if not n_nodes:
            raise ValueError("no parameters matched — nothing to adapt")
        self._tree = tree
        self.pages_per_slot = max(1, math.ceil(slot_bytes / page_bytes))
        if capacity_pages is not None:
            self.max_live = max(
                1, min(slots - 1, capacity_pages // self.pages_per_slot)
            )
        else:
            self.max_live = slots - 1

        self._by_name: dict[str, int] = {}
        self._refs: dict[str, int] = {}
        self._last_used: dict[str, int] = {}
        self._clock = 0
        self._free = list(range(1, slots))
        self._registry = None
        self._recorder = None

    # --- wiring ------------------------------------------------------------

    def bind(self, registry, recorder=None) -> "AdapterPool":
        """Attach the engine's metrics registry (and flight recorder):
        pool adds/evictions become counters, residency becomes gauges.
        The engine calls this from its constructor."""
        self._registry = registry
        self._recorder = recorder
        self._c_adds = registry.counter(
            "engine_adapter_pool_adds_total",
            "adapter pool: tenants added (including hot updates)",
        )
        self._c_evict = registry.counter(
            "engine_adapter_pool_evictions_total",
            "adapter pool: refcount-0 tenants evicted for capacity",
        )
        self._g_pages = registry.gauge(
            "engine_adapter_pool_pages_in_use",
            "adapter pool: pages held by resident tenants",
        )
        self._g_live = registry.gauge(
            "engine_adapter_pool_slots_live",
            "adapter pool: resident named tenants",
        )
        self._update_gauges()
        return self

    def _update_gauges(self):
        if self._registry is None:
            return
        live = len(self._by_name)
        self._g_live.set(live)
        self._g_pages.set(live * self.pages_per_slot)

    def _record(self, event: str, **fields):
        if self._recorder is not None:
            self._recorder.record(event, **fields)

    # --- tenant lifecycle --------------------------------------------------

    @property
    def tree(self) -> Any:
        """The stacked pool pytree the fused program takes as an
        argument. Replaced wholesale by :meth:`add` — never mutated."""
        return self._tree

    def names(self) -> list[str]:
        return sorted(self._by_name)

    def slot_of(self, name: str) -> int:
        """Resident slot of ``name`` (KeyError if not resident); marks
        it recently used."""
        slot = self._by_name[name]
        self._clock += 1
        self._last_used[name] = self._clock
        return slot

    def refcount(self, name: str) -> int:
        return self._refs.get(name, 0)

    def add(self, name: str, adapters: Any, *, alpha: float = 16.0) -> int:
        """Make ``name`` resident with the given adapter tree (an
        ``init_lora``-shaped nested dict, or a :class:`LoraState` — then
        its trained alpha wins). Re-adding a resident name HOT-UPDATES
        its factors in place (same slot — in-flight requests of that
        tenant keep gathering the slot and see the new weights at the
        next dispatch, exactly like a weight hot-swap commit, so push
        updates between a tenant's requests, not during them: refcount 0
        is the safe window). Evicts an LRU refcount-0 tenant when past
        capacity; raises RuntimeError when every resident tenant has
        live requests."""
        if isinstance(adapters, LoraState):
            alpha = float(adapters.alpha)
            adapters = adapters.adapters
        update = name in self._by_name
        if update:
            slot = self._by_name[name]
        else:
            while not self._free or len(self._by_name) >= self.max_live:
                self._evict_lru()
            slot = self._free.pop(0)
        self._write_slot(slot, adapters, alpha)
        self._by_name[name] = slot
        self._refs.setdefault(name, 0)
        self._clock += 1
        self._last_used[name] = self._clock
        if self._registry is not None:
            self._c_adds.inc()
        self._update_gauges()
        self._record(
            "adapter.update" if update else "adapter.add",
            name=name, slot=slot, alpha=alpha,
            pages=self.pages_per_slot,
        )
        return slot

    def _evict_lru(self):
        victims = [
            n for n in self._by_name if self._refs.get(n, 0) == 0
        ]
        if not victims:
            raise RuntimeError(
                "adapter pool full and every resident tenant has live "
                "requests — nothing evictable"
            )
        victim = min(victims, key=lambda n: self._last_used.get(n, 0))
        slot = self._by_name.pop(victim)
        self._refs.pop(victim, None)
        self._last_used.pop(victim, None)
        self._free.append(slot)
        # The stacked factors stay in place: the slot is unreachable
        # (no name maps to it) until the next add overwrites it.
        if self._registry is not None:
            self._c_evict.inc()
        self._update_gauges()
        self._record("adapter.evict", name=victim, slot=slot)

    def acquire(self, name: str) -> int:
        """Refcount++ for one admitted request of ``name``; returns the
        slot. KeyError when the tenant is not resident — the engine
        rejects the request instead of silently serving base weights."""
        slot = self.slot_of(name)   # KeyError on unknown; bumps LRU
        self._refs[name] = self._refs.get(name, 0) + 1
        return slot

    def release(self, name: str) -> None:
        """Refcount-- when a request of ``name`` retires or fails."""
        if self._refs.get(name, 0) > 0:
            self._refs[name] -= 1

    def stats(self) -> dict:
        """JSON-able residency snapshot (cases and dashboards)."""
        return {
            "slots": self.slots,
            "max_live": self.max_live,
            "pages_per_slot": self.pages_per_slot,
            "pages_in_use": len(self._by_name) * self.pages_per_slot,
            "tenants": {
                n: {"slot": s, "refs": self._refs.get(n, 0)}
                for n, s in sorted(self._by_name.items())
            },
        }

    # --- stacked writes ----------------------------------------------------

    def _write_slot(self, slot: int, adapters: Any, alpha: float):
        scale = jnp.float32(alpha / self.rank)

        def walk(pnode, anode, path):
            if _is_pool_node(pnode):
                if not (
                    isinstance(anode, dict)
                    and set(anode) == {"lora_a", "lora_b"}
                ):
                    raise KeyError(
                        f"adapter tree missing factors at {'/'.join(path)}"
                    )
                a, b = anode["lora_a"], anode["lora_b"]
                if a.shape != pnode["lora_a"].shape[1:]:
                    raise ValueError(
                        f"{'/'.join(path)}: lora_a {a.shape} does not "
                        f"fit pool slice {pnode['lora_a'].shape[1:]} "
                        f"(rank={self.rank})"
                    )
                return {
                    "lora_a": pnode["lora_a"]
                    .at[slot].set(a.astype(pnode["lora_a"].dtype)),
                    "lora_b": pnode["lora_b"]
                    .at[slot].set(b.astype(pnode["lora_b"].dtype)),
                    "scale": pnode["scale"].at[slot].set(scale),
                }
            return {
                k: walk(
                    v,
                    anode.get(k) if isinstance(anode, dict) else None,
                    path + (k,),
                )
                for k, v in pnode.items()
            }

        self._tree = walk(self._tree, adapters, ())
