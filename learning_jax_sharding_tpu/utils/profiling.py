"""Tracing / profiling + the XLA-world "sanitizers" (SURVEY.md §5).

The reference's entire observability story is ``visualize_array_sharding``
plus one flawed timing loop (`/root/reference/case6_attention.py:234-238`).
The TPU-native equivalents:

* :func:`trace` — ``jax.profiler`` capture to an XPlane/Perfetto logdir
  (open in XProf/TensorBoard to see per-op device time, HBM traffic, and
  which collectives ride ICI);
* :func:`annotate` — named trace spans so framework phases (init, step,
  eval) are findable in the timeline;
* :func:`checking` — the nearest analogue of a race/memory sanitizer in the
  SPMD/XLA model, where user-level data races don't exist (SURVEY.md §5
  "Race detection"): NaN/Inf trapping (``jax_debug_nans``) and internal
  invariant checks (``jax_enable_checks``), scoped and restored on exit.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(logdir: str | os.PathLike, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a profiler trace of the enclosed block into ``logdir``.

    The capture includes device (TPU) activity, host Python/runtime activity
    at ``host_tracer_level``, and all :func:`annotate` spans.
    """
    os.makedirs(os.fspath(logdir), exist_ok=True)
    # ProfileOptions landed in newer jax; this runtime (0.4.x) captures
    # host activity by default — gate rather than pin the version.
    if hasattr(jax.profiler, "ProfileOptions"):
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = host_tracer_level
        with jax.profiler.trace(
            os.fspath(logdir), profiler_options=options
        ):
            yield
    else:
        with jax.profiler.trace(os.fspath(logdir)):
            yield


def annotate(name: str) -> jax.profiler.TraceAnnotation:
    """Named span visible in the profiler timeline::

        with annotate("train_step"):
            state, loss = step(state, batch)
    """
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def checking(*, nans: bool = True, checks: bool = True) -> Iterator[None]:
    """Scoped debug mode: trap NaN/Inf the moment a primitive produces one
    (``nans``) and enable JAX's internal invariant checks (``checks``).

    Costs recompilation and sync on entry/exit — a debugging tool, not a
    production setting.
    """
    prev_nans = jax.config.jax_debug_nans
    prev_checks = jax.config.jax_enable_checks
    try:
        jax.config.update("jax_debug_nans", nans)
        jax.config.update("jax_enable_checks", checks)
        # Executables compiled before the toggle can be replayed from the
        # dispatch cache WITHOUT the nan checks (observed: a warm cache from
        # unrelated prior compilations let a 0/0 divide through silently), so
        # force recompilation inside — and again outside, where check-laden
        # executables must not leak into production dispatch.
        jax.clear_caches()
        yield
    finally:
        # The block typically exits by RAISING (that is the tool's point:
        # FloatingPointError from a nan trap, or an invariant failure
        # mid-compile), so the restore path must itself be exception-safe:
        # drop the check-laden executables FIRST, then restore each flag
        # under its own finally — a failure in any one step must not
        # leave check-mode caches or flags live in production dispatch.
        try:
            jax.clear_caches()
        finally:
            try:
                jax.config.update("jax_debug_nans", prev_nans)
            finally:
                jax.config.update("jax_enable_checks", prev_checks)
