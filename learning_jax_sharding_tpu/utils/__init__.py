"""Benchmarking, profiling, checkpointing, and debug utilities (layer L6)."""
