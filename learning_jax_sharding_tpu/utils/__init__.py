"""Observability (layer L6): benchmarking, per-step metrics, profiling,
debug checks."""

from learning_jax_sharding_tpu.utils.bench import (  # noqa: F401
    BenchResult,
    compiled_flops,
    device_peak_flops,
    measure,
    time_fn,
)
from learning_jax_sharding_tpu.utils.memory import (  # noqa: F401
    HBM_BYTES,
    MemoryPlan,
    memory_plan,
)
from learning_jax_sharding_tpu.utils.metrics import MetricsLogger  # noqa: F401
from learning_jax_sharding_tpu.utils.profiling import (  # noqa: F401
    annotate,
    checking,
    trace,
)
