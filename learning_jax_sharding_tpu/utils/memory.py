"""HBM budget estimation for a transformer training configuration.

Answers "will this config fit a chip?" before paying a compile + OOM cycle
(measured on the v5e: the 125M model at b=16, s=1024 OOMs from stored dense
attention probabilities alone — exactly the term this planner surfaces).
Estimates, not measurements: XLA fusion changes the constants, but the big
terms (parameters, optimizer moments, per-layer saved activations, S² score
tensors, (B,S,V) logits) dominate and are shape-arithmetic.

Conventions: fp32 params/optimizer (the framework default), activations in
``cfg.dtype``. ``saved`` activations are what backward needs — the planner
models the three attention regimes (dense / remat / flash) and the fused
vs. unfused loss head explicitly, because those are the order-of-magnitude
levers (PERF.md).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp

#: Per-chip HBM, bytes. Public system specs, keyed by device_kind.
HBM_BYTES: dict[str, float] = {
    "TPU v4": 32e9,
    "TPU v5 lite": 16e9,   # v5e
    "TPU v5": 95e9,        # v5p
    "TPU v5p": 95e9,
    "TPU v6 lite": 32e9,   # v6e
}


def device_hbm_bytes(device: Any | None = None) -> float | None:
    """Spec HBM capacity for ``device`` (default: first local device), or
    None when unknown (emulated CPU). The static fallback for backends that
    report no ``bytes_limit`` — ``telemetry.devview.memory_report`` prefers
    the live limit when the runtime provides one."""
    if device is None:
        import jax

        device = jax.devices()[0]
    return HBM_BYTES.get(getattr(device, "device_kind", None))


@dataclasses.dataclass(frozen=True)
class MemoryPlan:
    """Byte estimates for one train step (single chip unless divided)."""

    params: float
    grads: float
    optimizer_state: float
    saved_activations: float
    loss_head: float
    total: float
    detail: dict

    def fits(self, hbm_bytes: float, *, headroom: float = 0.8) -> bool:
        """Conservative fit check: estimate under ``headroom`` × capacity
        (XLA scratch, fragmentation, and fusion temporaries take the rest)."""
        return self.total <= hbm_bytes * headroom


def memory_plan(
    cfg: Any,
    batch: int,
    seq: int,
    *,
    optimizer_slots: int = 2,       # adamw: m + v
    donate_state: bool = True,
    unfused_loss: bool = False,
    n_model_shards: int = 1,        # TP/FSDP degree dividing params & opt state
    n_data_shards: int = 1,         # DP degree dividing the batch dim
) -> MemoryPlan:
    """Estimate train-step HBM for a :class:`TransformerConfig`.

    Attention regime is read off the config: ``attn_fn`` set → flash-style
    (no S² saved); else ``remat_attention`` → q/k/v saved, scores recomputed;
    else dense → the S² softmax probabilities saved for backward (pre-softmax
    scores are fusion temporaries, not residuals).
    """
    act_bytes = jnp.dtype(cfg.dtype).itemsize
    param_bytes = jnp.dtype(cfg.param_dtype).itemsize
    b = batch / n_data_shards
    p = cfg.param_count / n_model_shards

    params = p * param_bytes
    grads = p * param_bytes
    opt = p * param_bytes * optimizer_slots
    if not donate_state:
        # Undonated input state stays alive next to the output state.
        params, opt = 2 * params, 2 * opt

    kv_heads = cfg.num_kv_heads if cfg.num_kv_heads is not None else cfg.num_heads
    nh = cfg.num_heads * cfg.head_dim / n_model_shards
    nkv = kv_heads * cfg.head_dim / n_model_shards
    tokens = b * seq

    # Saved-per-layer residuals the backward reads (block input, LN outputs,
    # q/k/v, attention output, FF up/GELU); coefficients from the block
    # structure, not measured constants.
    per_layer = tokens * act_bytes * (
        4 * cfg.features            # block in, 2×LN out, attn out
        + nh + 2 * nkv              # q, k, v
        + 2 * cfg.hidden / n_model_shards  # FF up pre/post-GELU
    )
    if cfg.attn_fn is not None:
        scores = 0.0                # flash: O(S·H) only, counted in q/k/v
    elif getattr(cfg, "remat_attention", False):
        scores = 0.0                # recomputed in backward
    else:
        heads = cfg.num_heads / n_model_shards
        # Saved probabilities (softmax backward reads only its OUTPUT, so the
        # fp32 pre-softmax scores are fusion temporaries, not residuals).
        scores = b * heads * seq * seq * act_bytes
    saved = cfg.num_layers * (per_layer + scores)

    if unfused_loss:
        # bf16 logits + the fp32 softmax upcast both live at peak.
        head = tokens * cfg.vocab_size / n_model_shards * (act_bytes + 4)
    else:
        chunk = min(seq, 128)  # fused_next_token_loss chunk size
        head = tokens * chunk / seq * cfg.vocab_size / n_model_shards * (act_bytes + 4)

    total = params + grads + opt + saved + head
    return MemoryPlan(
        params=params, grads=grads, optimizer_state=opt,
        saved_activations=saved, loss_head=head, total=total,
        detail={
            "per_layer_residuals": per_layer,
            "per_layer_scores": scores,
            "batch_per_shard": b,
        },
    )
