"""Structured per-step training metrics (SURVEY.md §5 "Metrics / logging").

The reference prints shard shapes but never a loss — its ``train_step``
returns only the new state (`/root/reference/case6_attention.py:208-215`).
This logger records what the survey says a training run must expose: loss,
step time, achieved TFLOP/s per chip and MFU, plus token throughput — as
one JSON object per step (machine-readable, `BENCH_r{N}.json`-style) mirrored
to a human-readable stderr line.

Timing is steady-state wall clock between ``log()`` calls. Reading the loss
back to host (``float(loss)``) inside ``log`` is the synchronization point:
it cannot complete before the step that produced it, so per-step wall time is
honest even though JAX dispatch is asynchronous (the flaw in the reference's
timing loop, `case6_attention.py:234-238`).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, IO

import jax

from learning_jax_sharding_tpu.utils.bench import device_peak_flops


class MetricsLogger:
    """Per-step metrics: wall time, throughput, MFU, arbitrary scalars.

    >>> metrics = MetricsLogger(flops_per_step=F, tokens_per_step=B*S)
    >>> for batch in data:
    ...     state, loss = step(state, batch)
    ...     metrics.log(int(state.step), loss=loss)

    Args:
        path: optional JSONL file; parent dirs are created.
        stream: human-readable mirror (default stderr); None to disable.
        flops_per_step: whole-program FLOPs per step (e.g. from
            ``utils.bench.compiled_flops``) — enables TFLOP/s and MFU.
        tokens_per_step: tokens consumed per step — enables tokens/s.
        n_devices: chips sharing the work (default: all devices in the
            global ``jax.devices()`` list — the right divisor for
            whole-program FLOPs on multi-host meshes too).
        registry: optional
            :class:`~learning_jax_sharding_tpu.telemetry.MetricsRegistry`
            — every record is mirrored as ``train_*`` metrics (steps
            counter, loss/rate gauges, step-time histogram), so training
            rides the same export surface (JSON snapshot / Prometheus
            text) as the serving engine.
    """

    def __init__(
        self,
        path: str | os.PathLike | None = None,
        *,
        stream: IO | None = sys.stderr,
        flops_per_step: float | None = None,
        tokens_per_step: int | None = None,
        n_devices: int | None = None,
        log_every: int = 1,
        registry: Any | None = None,
    ):
        self._file: IO | None = None
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(os.fspath(path))), exist_ok=True)
            self._file = open(path, "a")
        self._stream = stream
        self._flops = flops_per_step
        self._tokens = tokens_per_step
        self._n_devices = n_devices if n_devices is not None else len(jax.devices())
        self._peak = device_peak_flops()
        self._log_every = max(log_every, 1)
        self._last_t: float | None = None
        self._last_step: int | None = None
        self.history: list[dict[str, Any]] = []
        self._registry = registry
        if registry is not None:
            self._m_steps = registry.counter(
                "train_steps_total", "train steps logged")
            self._m_loss = registry.gauge("train_loss", "latest loss")
            self._m_sps = registry.gauge(
                "train_seconds_per_step", "steady-state step seconds")
            self._m_tps = registry.gauge(
                "train_tokens_per_second", "token throughput")
            self._m_mfu = registry.gauge(
                "train_mfu", "model FLOPs utilization [0,1]")
            self._m_step_hist = registry.histogram(
                "train_step_seconds", "per-step wall time")

    def log(self, step: int, loss: Any = None, **scalars: Any) -> dict[str, Any] | None:
        """Record one step. Returns the record, or None when skipped by
        ``log_every``. ``loss`` may be a device array — reading it is the
        step's sync point, so call this every step even if most are skipped."""
        rec: dict[str, Any] = {"step": int(step)}
        if loss is not None:
            rec["loss"] = float(loss)  # device→host readback: syncs the step
        now = time.perf_counter()
        if step % self._log_every:
            self._last_t, self._last_step = now, int(step)
            self._mirror(rec)
            return None

        if self._last_t is not None and step > self._last_step:
            dt = (now - self._last_t) / (step - self._last_step)
            rec["seconds_per_step"] = dt
            if self._tokens is not None:
                rec["tokens_per_second"] = self._tokens / dt
            if self._flops is not None:
                rec["tflops_per_chip"] = self._flops / dt / self._n_devices / 1e12
                if self._peak is not None:
                    rec["mfu"] = rec["tflops_per_chip"] * 1e12 / self._peak
        self._last_t, self._last_step = now, int(step)

        rec.update({k: float(v) for k, v in scalars.items()})
        self._mirror(rec)
        self.history.append(rec)
        if self._file is not None:
            self._file.write(json.dumps(rec) + "\n")
            self._file.flush()
        if self._stream is not None:
            parts = [f"step {rec['step']}"]
            if "loss" in rec:
                parts.append(f"loss {rec['loss']:.4f}")
            if "seconds_per_step" in rec:
                parts.append(f"{rec['seconds_per_step'] * 1e3:.1f} ms/step")
            if "tokens_per_second" in rec:
                parts.append(f"{rec['tokens_per_second']:,.0f} tok/s")
            if "mfu" in rec:
                parts.append(f"MFU {rec['mfu']:.1%}")
            parts += [f"{k} {rec[k]:.4g}" for k in scalars]
            print("  ".join(parts), file=self._stream, flush=True)
        return rec

    def _mirror(self, rec: dict[str, Any]) -> None:
        # Mirror a (possibly partial — skipped steps carry step+loss
        # only) record into the shared registry.
        if self._registry is None:
            return
        self._m_steps.inc()
        if "loss" in rec:
            self._m_loss.set(rec["loss"])
        if "seconds_per_step" in rec:
            self._m_sps.set(rec["seconds_per_step"])
            self._m_step_hist.observe(rec["seconds_per_step"])
        if "tokens_per_second" in rec:
            self._m_tps.set(rec["tokens_per_second"])
        if "mfu" in rec:
            self._m_mfu.set(rec["mfu"])

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
