"""Correct benchmarking: warmup, device sync, FLOP counting, MFU.

The reference's only benchmark is a 10-iteration wall-clock loop with two
flaws (`/root/reference/case6_attention.py:234-238`, SURVEY.md §3.4): iteration
0 includes compilation, and JAX's async dispatch is never synchronized, so the
measured time is neither pure-execution nor complete. This harness fixes both
and adds what the driver metric needs (`/root/repo/BASELINE.json`): FLOPs from
XLA's own cost analysis → TFLOP/s per chip → MFU against the chip's peak.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import jax

# Peak dense bf16 matmul throughput per chip, FLOP/s. Sources: public Google
# Cloud TPU system specs. Keyed by `jax.Device.device_kind`.
PEAK_BF16_FLOPS: dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
}


def device_peak_flops(device: jax.Device | None = None) -> float | None:
    """Peak bf16 FLOP/s for ``device`` (default: first local device), or None
    if unknown (e.g. emulated CPU)."""
    device = device or jax.devices()[0]
    return PEAK_BF16_FLOPS.get(device.device_kind)


# Peak HBM bandwidth per chip, bytes/s. Sources: public Google Cloud TPU
# system specs. The roofline for bandwidth-bound programs (decode!) the way
# PEAK_BF16_FLOPS is for matmul-bound ones.
PEAK_HBM_BYTES: dict[str, float] = {
    "TPU v4": 1.2e12,
    "TPU v5 lite": 819e9,    # v5e
    "TPU v5": 2.765e12,      # v5p
    "TPU v5p": 2.765e12,
    "TPU v6 lite": 1.64e12,  # v6e / Trillium
}


def device_peak_hbm_bw(device: jax.Device | None = None) -> float | None:
    """Peak HBM bytes/s for ``device``, or None if unknown."""
    device = device or jax.devices()[0]
    return PEAK_HBM_BYTES.get(device.device_kind)


def mbu(
    bytes_per_iter: float,
    seconds_per_iter: float,
    device: jax.Device | None = None,
) -> float | None:
    """Memory-bandwidth utilization in [0, 1]: achieved bytes/s over the
    chip's peak HBM bandwidth.

    The roofline metric for DECODE — each generated token must stream the
    served weights plus the valid KV cache through HBM, so
    ``bytes_per_iter`` is (weight bytes + mean valid cache bytes) per token
    step and an MBU near 1 means the step is running at the memory-system
    limit (MFU is near-meaningless there: decode matmuls are thin). None on
    unknown devices (e.g. emulated CPU)."""
    peak = device_peak_hbm_bw(device)
    if peak is None or seconds_per_iter <= 0:
        return None
    return bytes_per_iter / seconds_per_iter / peak


def compiled_flops(fn: Callable, *args, **kwargs) -> float | None:
    """Total FLOPs of one execution, from the compiled program's own cost
    analysis — no hand-derived formulas to drift out of sync with the model."""
    jitted = fn if isinstance(fn, jax.stages.Wrapped) else jax.jit(fn)
    analysis = jitted.lower(*args, **kwargs).compile().cost_analysis()
    if isinstance(analysis, (list, tuple)):   # older jax: one dict/device
        analysis = analysis[0] if analysis else None
    if not analysis:
        return None
    flops = analysis.get("flops")
    return float(flops) if flops and flops > 0 else None


@dataclasses.dataclass(frozen=True)
class BenchResult:
    """One measurement. ``flops`` is per-execution (whole program, all chips);
    throughput fields are per chip."""

    seconds_per_iter: float
    iters: int | None  # fixed iteration count, or None if chosen adaptively
    flops: float | None = None
    n_devices: int = 1
    peak_flops_per_chip: float | None = None

    @property
    def tflops_per_chip(self) -> float | None:
        if self.flops is None:
            return None
        return self.flops / self.seconds_per_iter / self.n_devices / 1e12

    @property
    def mfu(self) -> float | None:
        """Model FLOPs utilization in [0,1] — the BASELINE.json north-star
        metric ("≥45% MFU")."""
        t = self.tflops_per_chip
        if t is None or self.peak_flops_per_chip is None:
            return None
        return t * 1e12 / self.peak_flops_per_chip

    def summary(self) -> dict[str, Any]:
        return {
            "seconds_per_iter": self.seconds_per_iter,
            "iters": self.iters,
            "flops_per_iter": self.flops,
            "n_devices": self.n_devices,
            "tflops_per_chip": self.tflops_per_chip,
            "mfu": self.mfu,
        }


def _sync(out: Any) -> None:
    """Force completion of ``out`` by reading one element back to host.

    ``jax.block_until_ready`` alone is not trustworthy behind remote-device
    transports (verified in this environment: a tunneled TPU returns from
    ``block_until_ready`` immediately and an 8192³ matmul "finishes" in 30 µs).
    A host readback of a single element cannot complete before every program
    it depends on has run.
    """
    import numpy as np

    leaf = jax.tree_util.tree_leaves(out)[0]
    elem = leaf[(0,) * getattr(leaf, "ndim", 0)] if getattr(leaf, "ndim", 0) else leaf
    np.asarray(elem)


def _timed_run(fn: Callable, n: int, *args, **kwargs) -> float:
    start = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn(*args, **kwargs)
    _sync(out)
    return time.perf_counter() - start


def time_fn(
    fn: Callable,
    *args,
    iters: int | None = None,
    warmup: int = 2,
    min_time: float = 1.0,
    repeats: int = 3,
    **kwargs,
) -> float:
    """Seconds per iteration of ``fn(*args)``: compile/warmup excluded, fixed
    dispatch/transport latency cancelled out.

    The corrected form of the reference's timing loop
    (`/root/reference/case6_attention.py:234-238`, which excludes neither
    compile time nor async dispatch). Method: enqueued programs execute
    serially on the device, so a run of ``k`` calls followed by one host
    readback costs ``L + k·c`` (L = fixed transport/readback latency, c =
    per-iteration device time). Two runs at ``k`` and ``2k`` give
    ``c = (t₂ - t₁) / k`` with L eliminated. Behind this environment's
    tunneled TPU, L is ~100 ms with ~±20 ms jitter, so ``k`` is grown until a
    run takes ≥ ``min_time`` (device time ≫ jitter) and the diff is taken as
    the median of ``repeats`` pairs.

    Args:
        iters: fixed k; None (default) picks k adaptively from ``min_time``.
    """
    out = None
    for _ in range(max(warmup, 1)):
        out = fn(*args, **kwargs)
    _sync(out)

    if iters is None:
        iters = 1
        while True:
            t = _timed_run(fn, iters, *args, **kwargs)
            if t >= min_time or iters >= 1_000_000:
                break
            # Aim past min_time in one hop using the (latency-inflated, hence
            # conservative) current estimate.
            iters = max(2 * iters, int(iters * 1.5 * min_time / max(t, 1e-9)))

    diffs = []
    for _ in range(max(repeats, 1)):
        t1 = _timed_run(fn, iters, *args, **kwargs)
        t2 = _timed_run(fn, 2 * iters, *args, **kwargs)
        diffs.append(t2 - t1)
    diffs.sort()
    per_iter = diffs[len(diffs) // 2] / iters
    if per_iter <= 0:
        # Noise floor: bound from above with the single-run estimate.
        per_iter = t2 / (2 * iters)
    return per_iter


def measure(
    fn: Callable,
    *args,
    iters: int | None = None,
    warmup: int = 2,
    min_time: float = 1.0,
    repeats: int = 3,
    flops: float | None = None,
    n_devices: int | None = None,
    **kwargs,
) -> BenchResult:
    """Time ``fn`` and derive per-chip throughput / MFU.

    Args:
        flops: per-execution FLOPs; if None, read from XLA cost analysis.
        n_devices: chips sharing the work (default: all local devices).
        repeats: latency-cancelled pairs to median over (see ``time_fn``);
            raise together with ``min_time`` for drift-robust headline
            numbers — the tunneled TPU here drifts ±30% across seconds-scale
            windows, so short chains sample one drift state while long
            chains average it.
    """
    if flops is None:
        flops = compiled_flops(fn, *args, **kwargs)
    secs = time_fn(
        fn, *args, iters=iters, warmup=warmup, min_time=min_time,
        repeats=repeats, **kwargs,
    )
    return BenchResult(
        seconds_per_iter=secs,
        iters=iters,
        flops=flops,
        n_devices=n_devices if n_devices is not None else len(jax.devices()),
        peak_flops_per_chip=device_peak_flops(),
    )
