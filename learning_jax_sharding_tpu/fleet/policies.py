"""Fleet routing and shedding policy: where a request goes, and when the
FLEET (not just one replica) says no.

Each replica already defends itself — bounded queue (``max_queue``),
TTL deadlines, and the round-10 burn-rate :class:`~..robustness.policies.
DegradationLadder` whose last level sheds that replica's admits. The
fleet layer sits ABOVE those:

* **placement** — :meth:`FleetPolicy.rank` orders eligible replicas by a
  load score (queued + active work) plus the replica's worst SLO burn
  rate, weighted: a replica burning error budget is avoided BEFORE its
  own ladder has to degrade it, so burn-rate skew steers traffic instead
  of tripping per-replica alarms;
* **eligibility** — a dead replica, or one whose ladder reached its
  shedding level, takes no new work (its own admits would raise
  ``AdmissionError`` anyway; the router just doesn't bother it);
* **fleet shedding** — ``max_inflight`` bounds the TOTAL unfinished
  requests across the fleet: past it the router rejects the arrival
  outright (``AdmissionError``, ``fleet_shed_total``), because K
  replicas' queues all missing their SLO together is the same failure
  the round-10 bounded queue prevents for one.

Pure policy, no engine imports at module top — unit-testable like the
degradation ladder it layers above.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class FleetPolicy:
    """Scoring + shedding knobs for :class:`~.router.FleetRouter`.

    ``score = depth_weight · (queued + occupied) + burn_weight · burn``
    — occupied counts every slot holding a request, mid-PREFILL
    included (a prefill replica's load lives almost entirely in that
    state; counting only decoding slots would make a just-filled
    replica look idle). The default weights make one unit of burn rate
    (consuming error budget exactly) as repellent as ``burn_weight``
    queued requests, so a replica at burn 2–3× (a real incident) loses
    ties decisively while healthy replicas are balanced purely by load.
    Ties break on replica name: routing is deterministic, so a fleet
    replay routes identically.

    PREFIX-AWARE placement (round 15): when the router supplies
    predicted prefix-hit tokens (from :class:`~.kv_economy.KvEconomy`
    digest queries), the score SUBTRACTS ``prefix_weight × hit_tokens``
    — a replica already holding a request's prefix skips that much
    prefill, so cached tokens are negative load. The default makes a
    50-token cached prefix worth one queued request: enough to steer
    overlapping traffic onto warm replicas, not enough to pile every
    request onto one replica past its queue. With no hints the policy
    is exactly the prefix-blind round-11 behaviour.

    TOPOLOGY-AWARE placement (round 21): when the router carries a
    :class:`~..analysis.topology.TopologyProfile` it prices each
    candidate's cross-domain traffic (the KV handoff that would ride
    DCN) in seconds and the score ADDS ``dcn_weight × dcn_s``. The
    default weight makes 1 ms of priced DCN time as repellent as one
    queued request — on a healthy profile a megabyte-scale handoff
    (~0.3 ms at the reference 3.1 GB/s) loses ties but cannot override
    real load skew, while a DEGRADED cross-domain link (the
    ``dcn_degrade`` matrix cell: β collapses mid-run) inflates dcn_s
    a thousandfold and placement visibly shifts intra-domain. With no
    profile the policy is exactly the round-15 behaviour.
    """

    depth_weight: float = 1.0
    burn_weight: float = 4.0
    prefix_weight: float = 0.02
    dcn_weight: float = 1000.0
    max_inflight: int | None = None

    def __post_init__(self):
        if self.max_inflight is not None and self.max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {self.max_inflight}"
            )

    def burn_rate(self, replica) -> float:
        """The replica's worst current SLO burn rate (0 when it has no
        monitor — an unmonitored replica competes on load alone)."""
        slo = replica.engine.slo
        if slo is None or not slo.targets:
            return 0.0
        return max(slo.burn_rate(t.name) for t in slo.targets)

    def eligible(self, replica) -> bool:
        """Can this replica take NEW work right now?"""
        return replica.alive and replica.engine.degradation_level < 3

    def score(
        self, replica, *, hit_tokens: float = 0.0, dcn_s: float = 0.0,
    ) -> float:
        eng = replica.engine
        depth = eng.queue_depth() + eng.occupied_slots()
        return (
            self.depth_weight * depth
            + self.burn_weight * self.burn_rate(replica)
            - self.prefix_weight * hit_tokens
            + self.dcn_weight * dcn_s
        )

    def rank(self, replicas, hits: dict | None = None) -> list:
        """Eligible replicas, best placement first (deterministic).
        ``hits`` maps replica name → predicted prefix-hit tokens for the
        request being placed; absent names score no bonus, and ``None``
        (no KV economy attached) is exactly prefix-blind ranking."""
        hits = hits or {}
        return sorted(
            (r for r in replicas if self.eligible(r)),
            key=lambda r: (
                self.score(r, hit_tokens=hits.get(r.name, 0.0)),
                r.name,
            ),
        )

    def should_shed(self, inflight: int) -> bool:
        """Fleet-level admission control: reject when the whole fleet
        already carries ``max_inflight`` unfinished requests."""
        return (
            self.max_inflight is not None and inflight >= self.max_inflight
        )
