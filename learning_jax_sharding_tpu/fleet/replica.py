"""One fleet replica: a ContinuousEngine on its sub-mesh, plus the
boilerplate of standing K of them up.

A replica is the unit the router reasons about — an engine, the params
it serves (placed on ITS sub-mesh), a role, and liveness. Roles:

* ``"unified"`` — the ordinary colocated engine: prefills and decodes
  its own requests (the round-5..10 engine, unchanged);
* ``"prefill"`` — disaggregated prefill: built with
  ``max_new_tokens=1``, it runs prompts to their FIRST token and hands
  the KV row off (``export_kv`` → ``fleet.kv_transfer`` →
  a decode replica's ``ingest_kv``);
* ``"decode"`` — disaggregated decode: receives ingested rows only (the
  router never ``add_request``s to it) and streams the remaining
  tokens.

:func:`sub_meshes` carves the device list into disjoint consecutive
groups (sub-meshes of the emulated 8-device mesh in tests/cases; slices
of a pod in production) and :func:`make_replicas` builds K identical
replicas over them. Params are placed FULLY REPLICATED on each sub-mesh
by default (:func:`replicated_params`) — bit-identity across replicas
and against a single-engine baseline needs every replica to run the
same program on the same mesh SHAPE, and replicated weights keep that
trivially true; serve TP-sharded weights by placing them yourself and
passing ``place_params=False``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from learning_jax_sharding_tpu.models.serving import ContinuousEngine
from learning_jax_sharding_tpu.parallel import DEFAULT_AXIS_NAMES, build_mesh

ROLES = ("unified", "prefill", "decode")


def replicated_params(params: Any, mesh: Mesh) -> Any:
    """The served tree, fully replicated on ``mesh`` — every replica of
    the same mesh shape then compiles the identical program, the
    precondition for the fleet's bit-identity guarantees."""
    return jax.device_put(params, NamedSharding(mesh, PartitionSpec()))


@dataclasses.dataclass
class EngineReplica:
    """One engine + its served params under a fleet name/role."""

    name: str
    engine: ContinuousEngine
    params: Any
    draft_params: Any = None
    role: str = "unified"
    alive: bool = True
    #: Spot semantics: a preemptible replica may receive an eviction
    #: notice (the ``fleet.preempt`` chaos seam) at any step. The router
    #: then runs the GRACEFUL drain-and-migrate path within the grace
    #: window instead of the crash path — capacity is cheaper, work is
    #: never silently dropped. On-demand replicas never see the seam.
    preemptible: bool = False

    def __post_init__(self):
        if self.role not in ROLES:
            raise ValueError(
                f"unknown replica role {self.role!r}; expected one of "
                f"{ROLES}"
            )
        if self.role == "prefill" and self.engine._max_new != 1:
            raise ValueError(
                f"prefill replica {self.name!r} needs "
                f"max_new_tokens=1 (it runs prompts to their first token "
                f"and hands off), got {self.engine._max_new}"
            )

    def step(self):
        return self.engine.step(self.params, self.draft_params)

    def pop_finished(self):
        return self.engine.pop_finished()

    def has_work(self) -> bool:
        return self.engine.has_work()


def sub_meshes(
    count: int,
    shape: Sequence[int] = (1, 2),
    axis_names: Sequence[str] = DEFAULT_AXIS_NAMES,
    *,
    devices: Sequence[jax.Device] | None = None,
    offset: int = 0,
    topology: Any = None,
) -> list[Mesh]:
    """``count`` disjoint consecutive sub-meshes of ``shape`` carved out
    of ``devices`` (default: all), starting ``offset`` devices in.

    With ``topology`` (an ``analysis.topology.TopologyProfile``), the
    carve is hierarchy-aware: every sub-mesh lands entirely inside one
    ICI domain (``topology.domain_of_id``), so a replica's internal
    collectives never cross DCN — only the router's explicit KV
    handoffs do. The flat carve can straddle a domain boundary whenever
    ``offset + i*per`` isn't domain-aligned; with the profile in hand
    that's a placement bug, so a shape too big for one domain raises
    instead of silently paying DCN on every decode step."""
    import math

    devices = list(jax.devices()) if devices is None else list(devices)
    per = math.prod(int(s) for s in shape)
    need = offset + count * per
    if need > len(devices):
        raise ValueError(
            f"{count} sub-meshes of shape {tuple(shape)} from offset "
            f"{offset} need {need} devices, have {len(devices)}"
        )
    if topology is not None:
        dom = int(topology.ici_domain_devices)
        if per > dom:
            raise ValueError(
                f"sub-mesh shape {tuple(shape)} needs {per} devices but "
                f"one ICI domain holds {dom}: a single replica would "
                "straddle DCN on every collective; shrink the shape or "
                "carve without a topology"
            )
        by_dom: dict[int, list[jax.Device]] = {}
        for d in devices[offset:]:
            by_dom.setdefault(int(topology.domain_of_id(d.id)), []).append(d)
        groups: list[list[jax.Device]] = []
        for _, members in sorted(by_dom.items()):
            while len(members) >= per and len(groups) < count:
                groups.append(members[:per])
                members = members[per:]
        if len(groups) < count:
            raise ValueError(
                f"{count} intra-domain sub-meshes of shape {tuple(shape)} "
                f"don't fit: {len(devices) - offset} devices past offset "
                f"{offset} in domains of {dom} yield only "
                f"{len(groups)} whole groups"
            )
        return [build_mesh(shape, axis_names, devices=g) for g in groups]
    return [
        build_mesh(
            shape, axis_names,
            devices=devices[offset + i * per: offset + (i + 1) * per],
        )
        for i in range(count)
    ]


def make_replicas(
    config: Any,
    rules: Any,
    params: Any,
    *,
    count: int,
    mesh_shape: Sequence[int] = (1, 2),
    role: str = "unified",
    prefix: str | None = None,
    offset: int = 0,
    topology: Any = None,
    devices: Sequence[jax.Device] | None = None,
    draft_params: Any = None,
    place_params: bool = True,
    preemptible: bool = False,
    **engine_kwargs: Any,
) -> list[EngineReplica]:
    """Build ``count`` identical replicas on disjoint sub-meshes.

    ``engine_kwargs`` go to each :class:`ContinuousEngine` verbatim
    (batch_size, max_new_tokens, refill_chunk, recorder, slo, ...).
    ``place_params=True`` replicates ``params`` (and ``draft_params``)
    onto each sub-mesh; pass ``False`` when the trees are already placed.
    ``topology`` makes the carve ICI-domain-aware (see
    :func:`sub_meshes`).
    """
    prefix = role if prefix is None else prefix
    out = []
    for i, mesh in enumerate(
        sub_meshes(count, mesh_shape, devices=devices, offset=offset,
                   topology=topology)
    ):
        p = replicated_params(params, mesh) if place_params else params
        d = (
            replicated_params(draft_params, mesh)
            if (place_params and draft_params is not None) else draft_params
        )
        out.append(EngineReplica(
            name=f"{prefix}{i}",
            engine=ContinuousEngine(config, mesh, rules, **engine_kwargs),
            params=p, draft_params=d, role=role, preemptible=preemptible,
        ))
    return out
