"""The KV economy (round 15): prefix-aware placement + the KV tier
ladder — ROADMAP item 3, the difference between K independent engines
and ONE coherent serving system.

Millions of users means massive SHARED prefixes (system prompts,
few-shot headers, per-tenant tool schemas) and far more warm KV than
HBM. Two mechanisms, one module:

* **Prefix-aware placement** — every paged+prefix replica exports a
  queryable digest of its prefix registry
  (``ContinuousEngine.prefix_digest``: one 8-byte hash per page-aligned
  retained token prefix, epoch-invalidated on any registry change).
  :meth:`KvEconomy.predicted_hits` hashes an arriving prompt's page
  chain and walks it against each replica's digest AND its host tier,
  predicting the longest LOCALLY-servable prefix per replica in tokens;
  :class:`~.policies.FleetPolicy` subtracts ``prefix_weight ×
  hit_tokens`` from the placement score, so the router lands a request
  where its prefix already lives instead of re-prefilling it somewhere
  idle. The prediction is recorded on the trace and compared against
  the REALIZED hit at admission — a page evicted mid-route is a counted
  graceful miss (the request just re-prefills), never a wrong token.

* **The tier ladder, HBM → host RAM → peer replica** — each replica
  gets a :class:`TierStore` (host-RAM LRU with a byte budget).
  :meth:`KvEconomy.maintain` (called from every ``FleetRouter.step``)
  DEMOTES: when a replica retains more reference-free prefix pages than
  its HBM watermark — or is burning SLO budget, which demotes
  aggressively to free pages for live work — the coldest pages spill to
  its host tier (``engine.spill_page`` → the counted
  ``parallel.resharding`` host plan; every byte priced, booked to the
  ledger's ``kv_handoff`` bucket). :meth:`KvEconomy.promote` (called by
  the router at placement) PROMOTES: the placed prompt's missing chain
  pages fill back from the local host tier, a live peer's host tier, or
  a NON-DESTRUCTIVE read of a peer's HBM (``spill_page(drop=False)``)
  — stopping at the first page no tier holds, because a prefix chain is
  only usable contiguously. Tier entries are stamped with the spilling
  engine's ``weights_version``; a version mismatch is a MISS and drops
  the entry (stale K/V is never served — the swap-commit registry flush
  invalidates digests the same way). Stale entries DO still earn their
  RAM once before dropping: a re-demotion passes them to
  ``spill_page(base_rows=...)`` as the delta codec's base, so engines
  built with ``comm_compression`` ship only the blocks the version
  bump actually changed.

Host-side policy only: nothing here dispatches device code — the
engine's golden-pinned ``kv_page_spill``/``kv_page_fill`` programs and
the counted host plans do all the moving.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any


class TierStore:
    """One replica's HOST-RAM KV tier: page-key → spilled host rows,
    LRU-ordered under a byte budget.

    Entries carry the ``weights_version`` the K/V was computed under;
    :meth:`get`/:meth:`peek` return rows only on a version match (a
    mismatch can never become valid again — versions are monotone — so
    :meth:`get` drops it). The store holds ``numpy`` buffers only: a
    replica death takes its host tier with it
    (:meth:`KvEconomy.on_replica_death`), exactly like a real process
    exit would."""

    def __init__(self, capacity_bytes: int = 64 << 20):
        if capacity_bytes < 1:
            raise ValueError("TierStore needs a positive byte budget")
        self.capacity_bytes = int(capacity_bytes)
        self._pages: OrderedDict[bytes, dict] = OrderedDict()
        self.bytes_held = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._pages)

    def __contains__(self, key: bytes) -> bool:
        return key in self._pages

    def has(self, key: bytes, *, version: int) -> bool:
        ent = self._pages.get(key)
        return ent is not None and ent["version"] == version

    def put(self, key: bytes, rows, *, version: int, nbytes: int) -> int:
        """Insert (or refresh) an entry, then evict LRU-oldest entries
        past the byte budget. Returns the bytes evicted making room."""
        old = self._pages.pop(key, None)
        if old is not None:
            self.bytes_held -= old["bytes"]
        self._pages[key] = {
            "rows": rows, "version": int(version), "bytes": int(nbytes),
        }
        self.bytes_held += int(nbytes)
        evicted = 0
        while self.bytes_held > self.capacity_bytes and len(self._pages) > 1:
            _, ent = self._pages.popitem(last=False)
            self.bytes_held -= ent["bytes"]
            self.evictions += 1
            evicted += ent["bytes"]
        return evicted

    def base_rows(self, key: bytes):
        """Rows for ``key`` at ANY version, no LRU refresh — the delta
        codec's version-stamped base. A stale entry is useless to serve
        (:meth:`get` drops it) but perfect to diff against: a page
        re-spilled after a weights bump shares most of its blocks with
        the copy the tier already holds, so ``spill_page(...,
        base_rows=...)`` ships only the changed blocks over the wire."""
        ent = self._pages.get(key)
        return None if ent is None else ent["rows"]

    def get(self, key: bytes, *, version: int):
        """Rows for ``key`` at ``version``, LRU-refreshed — or ``None``.
        A version mismatch drops the entry (stale K/V is dead weight)."""
        ent = self._pages.get(key)
        if ent is None:
            return None
        if ent["version"] != version:
            self._pages.pop(key)
            self.bytes_held -= ent["bytes"]
            return None
        self._pages.move_to_end(key)
        return ent["rows"]

    def peek(self, key: bytes, *, version: int):
        """Non-destructive :meth:`get` for PEER reads: no LRU refresh,
        and a version mismatch leaves the entry alone — it may still be
        valid for the owning replica (mid-rolling-swap fleets run mixed
        versions)."""
        ent = self._pages.get(key)
        if ent is None or ent["version"] != version:
            return None
        return ent["rows"]

    def drop_all(self) -> int:
        dropped = self.bytes_held
        self._pages.clear()
        self.bytes_held = 0
        return dropped


class KvEconomy:
    """Fleet-wide KV-economy coordinator: owns one :class:`TierStore`
    per eligible replica and the demotion/promotion policy knobs.

    Attach via ``FleetRouter(..., kv_economy=KvEconomy(...))`` — the
    router calls :meth:`predicted_hits`/:meth:`promote` at placement,
    :meth:`maintain` each step, :meth:`on_replica_death` at failover,
    and :meth:`on_finish` at retirement (predicted-vs-realized books).

    Knobs:

    * ``host_bytes_per_replica`` — each host tier's byte budget.
    * ``hbm_retained_target`` — retained reference-free pages a replica
      may keep in HBM before :meth:`maintain` demotes the coldest
      (default: half its page pool).
    * ``burn_threshold`` — a replica whose worst SLO burn rate exceeds
      this demotes EVERYTHING reference-free: error budget buys HBM
      headroom for live work before the degradation ladder has to act.
    * ``peer_fill`` — whether promotion may read a peer replica's host
      tier or HBM (the third tier rung) when local tiers miss.

    Eligibility: paged + prefix-cache, non-speculative replicas (the
    engine enforces the same for spill/fill). A mixed fleet is fine —
    ineligible replicas simply score no prefix bonus and hold no tier.
    """

    def __init__(
        self,
        *,
        host_bytes_per_replica: int = 64 << 20,
        hbm_retained_target: int | None = None,
        burn_threshold: float = 2.0,
        peer_fill: bool = True,
        demote_min_reuse: int = 1,
    ):
        self.host_bytes_per_replica = int(host_bytes_per_replica)
        self.hbm_retained_target = hbm_retained_target
        self.burn_threshold = float(burn_threshold)
        self.peer_fill = bool(peer_fill)
        # Only pay the device→host copy for chain keys that arrivals
        # have named at least this many times (demonstrated reuse): at
        # the default 1 every cold chain is backed up; at 2+ one-shot
        # prompts ride the free HBM LRU and never cost a transfer.
        self.demote_min_reuse = int(demote_min_reuse)
        self._router = None
        self._tiers: dict[str, TierStore] = {}
        self._page_size: int | None = None
        self._chain_refs: dict[bytes, int] = {}   # key → arrival count

    # --- wiring -----------------------------------------------------------

    @staticmethod
    def eligible(rep) -> bool:
        eng = rep.engine
        return bool(
            getattr(eng, "_paged", False)
            and getattr(eng, "_prefix", False)
            and not getattr(eng, "_speculative", False)
        )

    def attach(self, router) -> None:
        """Bind to a router: one host tier per eligible replica, and the
        economy's counters/gauges on the ROUTER registry (fleet-scoped
        metrics live with the fleet, per-engine spill/fill bytes with
        each engine)."""
        if self._router is not None and self._router is not router:
            raise RuntimeError("KvEconomy is already attached to a router")
        self._router = router
        sizes = set()
        for name, rep in router.replicas.items():
            if self.eligible(rep):
                self._tiers[name] = TierStore(self.host_bytes_per_replica)
                sizes.add(rep.engine._page_size)
        if len(sizes) > 1:
            # One prompt → one page chain: mixed page sizes would make
            # the same prefix hash to different keys per replica.
            raise ValueError(
                f"tiered replicas disagree on page_size: {sorted(sizes)}"
            )
        self._page_size = sizes.pop() if sizes else None
        r = router.registry
        self._c_demotions = r.counter(
            "fleet_tier_demotions_total",
            "prefix pages demoted HBM → host tier")
        self._c_promotions = r.counter(
            "fleet_tier_promotions_total",
            "prefix pages promoted into HBM from any tier")
        self._c_peer = r.counter(
            "fleet_tier_peer_promotions_total",
            "promoted pages sourced from a PEER replica (host or HBM)")
        self._c_peer_dcn_bytes = r.counter(
            "fleet_tier_peer_dcn_bytes_total",
            "peer-promotion bytes whose source replica sits in a "
            "different ICI domain (a DCN hop under router.topology; "
            "always 0 without a profile)")
        self._c_evictions = r.counter(
            "fleet_tier_evictions_total",
            "host-tier entries LRU-evicted past the byte budget")
        self._c_migrated_pages = r.counter(
            "fleet_tier_migrated_pages_total",
            "host-tier pages moved whole to a survivor's tier by "
            "graceful scale-in (migrate_tier)")
        self._c_migrated_bytes = r.counter(
            "fleet_tier_migrated_bytes_total",
            "bytes those migrated pages carried (host→host, no device "
            "transfer — the write-back spills are counted separately)")
        self._c_spill_bytes = r.counter(
            "fleet_tier_spill_bytes_total",
            "WIRE bytes moved HBM → host by demotion sweeps (post-codec "
            "when the engines carry a comm_compression KV codec)")
        self._c_fill_bytes = r.counter(
            "fleet_tier_fill_bytes_total",
            "WIRE bytes moved into HBM by promotions")
        self._c_raw_bytes = r.counter(
            "fleet_tier_raw_bytes_total",
            "pre-codec bytes the tier transfers represented — the "
            "compression denominator; equals wire bytes on "
            "uncompressed fleets")
        self._g_ratio = r.gauge(
            "fleet_tier_compression_ratio",
            "raw/wire ratio of the last tier transfer (1.0 when the "
            "engines ship uncompressed)")
        self._g_ratio.set(1.0)
        self._c_pred_tokens = r.counter(
            "fleet_prefix_predicted_tokens_total",
            "prefix-hit tokens the placement score predicted")
        self._c_real_tokens = r.counter(
            "fleet_prefix_realized_tokens_total",
            "prefix-hit tokens admissions actually realized")
        self._c_misroutes = r.counter(
            "fleet_prefix_misroutes_total",
            "finished requests whose realized hit fell short of the "
            "routing prediction (tier race — graceful re-prefill)")
        self._g_host_pages = r.gauge(
            "fleet_tier_host_pages", "pages held across all host tiers")
        self._g_host_bytes = r.gauge(
            "fleet_tier_host_bytes", "bytes held across all host tiers")

    def tier_of(self, name: str) -> TierStore | None:
        return self._tiers.get(name)

    # --- prefix-aware placement -------------------------------------------

    def _chain(self, prompt) -> list[bytes]:
        # Page-aligned prefix keys, shallowest first — the engine's own
        # admission bound: the LAST prompt token always recomputes (its
        # logits seed generation), so a full-length prompt of exactly k
        # pages chains k-1 deep, not k.
        ps = self._page_size
        if ps is None or prompt.size <= ps:
            return []
        return [
            prompt[: k * ps].tobytes()
            for k in range(1, (int(prompt.size) - 1) // ps + 1)
        ]

    def predicted_hits(self, prompt) -> dict[str, int]:
        """Replica name → predicted prefix-hit TOKENS for ``prompt``,
        counting only what the replica can serve LOCALLY (HBM digest +
        its own host tier). Peer pages are deliberately excluded: every
        replica can reach them, so they carry no placement signal —
        they are promotion's fallback, not routing's.

        The router calls this exactly once per arrival, so it doubles
        as the economy's demand census: each chain key's arrival count
        feeds the ``demote_min_reuse`` admission filter (bounded by the
        number of distinct chain keys the fleet has ever seen)."""
        out: dict[str, int] = {}
        chain = self._chain(prompt)
        for key in chain:
            self._chain_refs[key] = self._chain_refs.get(key, 0) + 1
        for name, rep in self._router.replicas.items():
            tier = self._tiers.get(name)
            if tier is None or not rep.alive:
                continue
            eng = rep.engine
            _, digest = eng.prefix_digest()
            version = eng.weights_version
            depth = 0
            for k, key in enumerate(chain, start=1):
                if (
                    eng.prefix_hash(key) in digest
                    or tier.has(key, version=version)
                ):
                    depth = k
                else:
                    break
            out[name] = depth * self._page_size
        return out

    def promote(self, rep, prompt) -> int:
        """ON-ADMISSION PROMOTION: fill ``prompt``'s missing chain pages
        into ``rep``'s HBM — local host tier first, then (``peer_fill``)
        a live peer's host tier or a non-destructive read of its HBM —
        stopping at the first page no tier holds. Resident ancestors are
        LRU-touched first so promoting a descendant cannot evict the
        chain out from under itself. Returns pages promoted; a page-pool
        exhaustion stops quietly (promotion yields to live work — the
        admission simply realizes a shorter hit)."""
        name = rep.name
        tier = self._tiers.get(name)
        if tier is None or not rep.alive:
            return 0
        eng = rep.engine
        chain = self._chain(prompt)
        if not chain:
            return 0
        version = eng.weights_version
        _, digest = eng.prefix_digest()
        resident = [k for k in chain if eng.prefix_hash(k) in digest]
        missing = len(resident) < len(chain)
        if missing and eng._cache is None:
            eng.ensure_cache(rep.params)
        for key in resident:
            eng.touch_prefix(key)
        promoted = 0
        for key in chain:
            if eng.prefix_hash(key) in digest:
                continue
            rows, src, peer = tier.get(key, version=version), "host", None
            if rows is None and self.peer_fill:
                rows, src, peer = self._peer_read(name, key, version)
            if rows is None:
                break          # chain broken: deeper pages are unusable
            try:
                st = eng.fill_page(key, rows)
            except RuntimeError:
                break          # page pool exhausted: yield to live work
            promoted += 1
            self._c_promotions.inc()
            self._c_fill_bytes.inc(st["bytes"])
            raw = st.get("raw_bytes", st["bytes"])
            self._c_raw_bytes.inc(raw)
            if st["bytes"]:
                self._g_ratio.set(raw / st["bytes"])
            extra = {}
            if src == "peer":
                self._c_peer.inc()
                if peer is not None and self._peer_is_dcn(name, peer):
                    self._c_peer_dcn_bytes.inc(st["bytes"])
                    extra = {
                        "peer": peer, "dcn": True,
                        "priced_s": self._router.topology.dcn_seconds(
                            st["bytes"]),
                    }
            self._router.recorder.record(
                "fleet.kv_promote", replica=name, src=src,
                bytes=st["bytes"], raw_bytes=raw, **extra,
            )
        return promoted

    def _peer_is_dcn(self, name: str, peer_name: str) -> bool:
        """Does a ``peer_name`` → ``name`` page read cross an ICI
        domain? Replicas carved by ``sub_meshes(topology=)`` each live
        inside one domain, so the test is whether the two engines'
        device sets share any domain at all — disjoint domains means
        the page rode DCN."""
        topo = getattr(self._router, "topology", None)
        if topo is None:
            return False
        def domains(rep):
            return {
                int(topo.domain_of(d))
                for d in rep.engine._mesh.devices.flat
            }
        a = self._router.replicas.get(name)
        b = self._router.replicas.get(peer_name)
        if a is None or b is None:
            return False
        return not (domains(a) & domains(b))

    def _peer_read(self, name: str, key: bytes, version: int):
        """The third tier rung: a live peer's host tier, else a
        non-destructive spill of the peer's OWN resident page — the
        peer keeps serving its copy; we pay the (counted) wire bytes.
        Returns ``(rows, src, peer_name)``. With ``router.topology``
        set, SAME-DOMAIN peers are tried first: a page on a neighbor's
        ICI rail beats the identical page across DCN, so the sort key —
        not a filter — keeps the cross-domain copy as the fallback it
        should be."""
        cands = [p for p in sorted(self._tiers) if p != name]
        topo = getattr(self._router, "topology", None)
        if topo is not None:
            cands.sort(key=lambda p: (self._peer_is_dcn(name, p), p))
        for peer_name in cands:
            peer = self._router.replicas.get(peer_name)
            if peer is None or not peer.alive:
                continue
            if peer.engine.weights_version != version:
                continue       # mixed-version fleet: never cross-fill
            rows = self._tiers[peer_name].peek(key, version=version)
            if rows is not None:
                return rows, "peer", peer_name
            if peer.engine.prefix_hash(key) in peer.engine.prefix_digest()[1]:
                try:
                    rows, _ = peer.engine.spill_page(key, drop=False)
                except (KeyError, RuntimeError):
                    continue   # raced away / not readable — next peer
                return rows, "peer", peer_name
        return None, "none", None

    # --- demotion ---------------------------------------------------------

    def _retained_target(self, eng) -> int:
        if self.hbm_retained_target is not None:
            return int(self.hbm_retained_target)
        return max(1, (eng._paged_pages - 1) // 2)

    def maintain(self) -> int:
        """One DEMOTION sweep (the router calls this every step): each
        replica spills its LRU-coldest reference-free pages to its host
        tier while it retains more than its HBM watermark — or ALL of
        them while its SLO burn exceeds ``burn_threshold`` (error
        budget buys page-pool headroom before the ladder degrades).
        Pages the tier ALREADY holds at the live weights version are
        skipped, not re-spilled: their HBM copy is pure cache that the
        engine's own LRU can evict for free, so repeating the
        device→host transfer every sweep would be pure churn.
        Returns pages demoted fleet-wide."""
        demoted = 0
        for name in sorted(self._tiers):
            rep = self._router.replicas.get(name)
            if rep is None or not rep.alive:
                continue
            eng = rep.engine
            tier = self._tiers[name]
            retained = eng.retained_prefixes()        # LRU-oldest first
            target = self._retained_target(eng)
            # Steady state demotes by WRITE-BACK (copy to host, leave
            # the HBM page as evict-for-free cache — the engine's own
            # LRU reclaims it under genuine pool pressure, and a page
            # the tier backs is lossless to drop). Only a burning SLO
            # budget force-drops, buying pool headroom immediately.
            hot = self._router.policy.burn_rate(rep) > self.burn_threshold
            if hot:
                target = 0
            for key in retained[: max(0, len(retained) - target)]:
                if not hot and (
                    tier.has(key, version=eng.weights_version)
                    or self._chain_refs.get(key, 0) < self.demote_min_reuse
                ):
                    continue
                try:
                    # A stale same-key tier entry (version bump since the
                    # last demotion) is the delta codec's base: only the
                    # blocks the new version changed ride the wire.
                    rows, st = eng.spill_page(
                        key, drop=hot, base_rows=tier.base_rows(key),
                    )
                except (KeyError, RuntimeError):
                    continue   # became shared/unregistered since listing
                raw = st.get("raw_bytes", st["bytes"])
                # The tier budgets what host RAM actually HOLDS — the
                # decoded rows — not the wire bytes the transfer paid.
                evicted = tier.put(
                    key, rows,
                    version=eng.weights_version, nbytes=raw,
                )
                demoted += 1
                self._c_demotions.inc()
                self._c_spill_bytes.inc(st["bytes"])
                self._c_raw_bytes.inc(raw)
                if st["bytes"]:
                    self._g_ratio.set(raw / st["bytes"])
                if evicted:
                    self._c_evictions.inc()
                self._router.recorder.record(
                    "fleet.kv_demote", replica=name, bytes=st["bytes"],
                    raw_bytes=raw, host_evicted_bytes=evicted,
                )
        self._g_host_pages.set(sum(len(t) for t in self._tiers.values()))
        self._g_host_bytes.set(
            sum(t.bytes_held for t in self._tiers.values())
        )
        return demoted

    # --- lifecycle hooks ---------------------------------------------------

    def on_replica_death(self, name: str) -> None:
        """A replica's host tier dies with its process: drop it whole —
        peers must recompute from the prompt, NEVER serve KV whose owner
        can no longer vouch for it (stale/partial pages are the one
        thing the tier ladder must not produce)."""
        tier = self._tiers.pop(name, None)
        if tier is None:
            return
        dropped = tier.drop_all()
        self._g_host_pages.set(sum(len(t) for t in self._tiers.values()))
        self._g_host_bytes.set(
            sum(t.bytes_held for t in self._tiers.values())
        )
        self._router.recorder.record(
            "fleet.tier_dropped", replica=name, bytes=dropped,
        )

    def on_replica_adopt(self, rep) -> None:
        """A replica joining (or rejoining) the fleet gets an EMPTY
        host tier: entries it could inherit were either migrated to a
        survivor at its graceful exit or died with its process — a
        tier must never hold KV its owner cannot vouch for. Page-size
        agreement is enforced exactly like :meth:`attach` does."""
        if not self.eligible(rep):
            return
        ps = rep.engine._page_size
        if self._page_size is not None and ps != self._page_size:
            raise ValueError(
                f"adopted replica {rep.name!r} disagrees on page_size "
                f"({ps} != {self._page_size})"
            )
        if self._page_size is None:
            self._page_size = ps
        self._tiers.setdefault(
            rep.name, TierStore(self.host_bytes_per_replica)
        )

    def migrate_tier(self, rep) -> tuple[int, int]:
        """GRACEFUL scale-in's KV half: hand the retiring replica's
        warm pages to a survivor instead of letting them die with it.

        Two movements, both counted:

        * retained HBM prefix pages WRITE BACK into the retiring
          replica's own host tier first (the counted, codec-compressed
          ``spill_page`` plan — same wire as every demotion sweep), so
          the migration carries the full warm set, not just whatever
          earlier sweeps happened to demote;
        * the assembled host tier then moves WHOLE to the best live
          survivor's tier — version stamps ride along, and the
          destination's LRU byte budget applies (the coldest migrated
          pages may evict; counted).

        Returns ``(pages_migrated, bytes_migrated)``. No live survivor
        tier → the entries drop (recorded), exactly the
        :meth:`on_replica_death` outcome."""
        name = rep.name
        tier = self._tiers.pop(name, None)
        if tier is None:
            return (0, 0)
        eng = rep.engine
        for key in eng.retained_prefixes():
            if tier.has(key, version=eng.weights_version):
                continue
            try:
                rows, st = eng.spill_page(
                    key, drop=False, base_rows=tier.base_rows(key),
                )
            except (KeyError, RuntimeError):
                continue   # became shared/unregistered since listing
            raw = st.get("raw_bytes", st["bytes"])
            tier.put(key, rows, version=eng.weights_version, nbytes=raw)
            self._c_demotions.inc()
            self._c_spill_bytes.inc(st["bytes"])
            self._c_raw_bytes.inc(raw)
        dest_name = None
        router = self._router
        for peer in sorted(self._tiers):
            r = router.replicas.get(peer)
            if (
                r is not None and r.alive
                and peer not in router._draining
            ):
                dest_name = peer
                break
        pages = moved = 0
        if dest_name is None:
            dropped = tier.drop_all()
            router.recorder.record(
                "fleet.tier_dropped", replica=name, bytes=dropped,
            )
        else:
            dest = self._tiers[dest_name]
            for key, ent in list(tier._pages.items()):
                evicted = dest.put(
                    key, ent["rows"], version=ent["version"],
                    nbytes=ent["bytes"],
                )
                pages += 1
                moved += ent["bytes"]
                if evicted:
                    self._c_evictions.inc()
            tier.drop_all()
            self._c_migrated_pages.inc(pages)
            self._c_migrated_bytes.inc(moved)
            router.recorder.record(
                "fleet.tier_migrated", src=name, dst=dest_name,
                pages=pages, bytes=moved,
            )
        self._g_host_pages.set(sum(len(t) for t in self._tiers.values()))
        self._g_host_bytes.set(
            sum(t.bytes_held for t in self._tiers.values())
        )
        return (pages, moved)

    def on_finish(self, predicted: int, realized: int | None) -> None:
        """Predicted-vs-realized books, fed by ``FleetRouter._finish``."""
        self._c_pred_tokens.inc(int(predicted))
        if realized is not None:
            self._c_real_tokens.inc(int(realized))
            if realized < predicted:
                self._c_misroutes.inc()

    # --- reporting ---------------------------------------------------------

    def tier_report(self) -> dict:
        """JSON-able per-replica tier occupancy + fleet movement totals
        — the ``case26`` artifact and the bench's bytes-moved-per-tier
        breakdown."""
        per: dict[str, dict] = {}
        for name in sorted(self._tiers):
            rep = self._router.replicas.get(name)
            tier = self._tiers[name]
            eng = rep.engine if rep is not None else None
            per[name] = {
                "alive": bool(rep is not None and rep.alive),
                "hbm_retained_pages": (
                    len(eng.retained_prefixes()) if eng is not None else 0
                ),
                "host_pages": len(tier),
                "host_bytes": tier.bytes_held,
                "host_evictions": tier.evictions,
            }
        return {
            "replicas": per,
            "page_size": self._page_size,
            "host_bytes_per_replica": self.host_bytes_per_replica,
            "demotions": int(self._c_demotions.value),
            "promotions": int(self._c_promotions.value),
            "peer_promotions": int(self._c_peer.value),
            "host_evictions": int(self._c_evictions.value),
            "migrated_pages": int(self._c_migrated_pages.value),
            "migrated_bytes": int(self._c_migrated_bytes.value),
            "spill_bytes": int(self._c_spill_bytes.value),
            "fill_bytes": int(self._c_fill_bytes.value),
            "raw_bytes": int(self._c_raw_bytes.value),
            "compression_ratio": float(self._g_ratio.value),
            "predicted_tokens": int(self._c_pred_tokens.value),
            "realized_tokens": int(self._c_real_tokens.value),
            "misroutes": int(self._c_misroutes.value),
        }
