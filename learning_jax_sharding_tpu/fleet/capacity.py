"""The static capacity planner: "what fleet shape does this trace
need?" — answered OFFLINE, before a single device is provisioned.

The autoscaler (:mod:`.autoscaler`) reacts to live signals; this module
is its yardstick. It composes the repo's existing models —
:mod:`..analysis.costmodel` rooflines for per-replica throughput,
:mod:`..analysis.topology` for carve feasibility, the engine's KV
geometry for HBM fit, and KV-economy stats for the prefix discount —
into a windowed demand plan over a load trace:

* **demand** — the trace's arrivals bucket into fixed windows; each
  request contributes its decode budget plus its prompt tokens
  discounted by the measured prefix-hit ratio (warm KV is prefill the
  fleet never pays for — the round-15 economy, priced into planning);
* **supply** — one replica's token throughput from the roofline: the
  max of the compute term (2·P FLOPs/token against the profile's
  effective peak) and the memory term (the decode step streams the
  whole parameter tree once per batch) over the sub-mesh's devices.
  Replays on the emulated CPU fleet pass a MEASURED ``replica_tok_s``
  instead — the plan's shape logic is identical, only the supply
  number changes;
* **feasibility** — the plan refuses shapes that cannot exist: a
  replica must fit HBM (params + KV page pool) and, under a topology
  profile, fit inside one ICI domain with enough whole domains for
  ``max_replicas`` (the :func:`~.replica.sub_meshes` rule, checked
  before money is spent instead of at boot);
* **pricing** — replica-seconds × devices × the economics rate
  (:class:`~..telemetry.economics.CostRates`); the ELASTIC cost
  integrates K(t) over the windows, each STATIC cost holds K flat, and
  the best static fleet is the cheapest one that still covers peak
  demand — the bar the autoscaler must beat.

:func:`score_timeline` closes the loop: the autoscaler's live decision
timeline replays into the same K(t) integral and the planner-vs-live
gap (in provisioned replica-seconds) is reported — and bench-gated, so
a regression in EITHER the planner's model or the controller's
judgement shows up as the gap widening.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

from learning_jax_sharding_tpu.analysis.costmodel import (
    Profile,
    table_profile,
)


@dataclasses.dataclass(frozen=True)
class PlannerAssumptions:
    """What the offline plan takes as given. Defaults line up with the
    rest of the repo: the ``TPU v5 lite`` pricing profile the cost
    model tables carry and the ``CostRates`` device-hour rate the
    economics roll-ups price with."""

    profile: str = "TPU v5 lite"
    usd_per_device_hour: float = 1.20
    hbm_bytes_per_device: float = 16e9
    #: Demand-window width in trace seconds.
    window_s: float = 2.0
    #: Plan to run replicas at most this fraction of roofline — the
    #: slack that absorbs within-window burstiness without queueing.
    headroom: float = 0.7
    #: Prefill tokens already warm in a KV tier cost nothing; this is
    #: the measured (or assumed) hit ratio applied as a discount.
    prefix_hit_ratio: float = 0.0


def _param_count(config: Any) -> int:
    """Parameters of the repo's transformer from its config alone —
    the planner must not need an initialized tree to size a fleet.
    Exact for the dense model family (``test_zautoscaler`` pins it
    against a real initialized tree): untied ``lm_head``, layernorm
    carrying scale+bias (rmsnorm scale only), optional dense biases."""
    f = int(config.features)
    h = int(config.num_heads) * int(config.head_dim)
    kv_h = int(config.num_kv_heads or config.num_heads)
    kv = kv_h * int(config.head_dim)
    hidden = int(config.hidden)
    norm = (
        2 * f if str(getattr(config, "norm", "layernorm")) == "layernorm"
        else f
    )
    bias_attn = (h + 2 * kv + f) if config.use_bias else 0
    bias_mlp = (hidden + f) if config.use_bias else 0
    per_layer = (
        f * h + 2 * f * kv        # q + k + v projections
        + h * f + bias_attn       # output projection
        + 2 * f * hidden + bias_mlp   # mlp up + down
        + 2 * norm                # the two layer norms
    )
    embed = int(config.vocab_size) * f
    unembed = f * int(config.vocab_size)   # lm_head is NOT tied
    pos = 0 if config.rope else int(config.max_seq_len) * f
    return (
        embed + unembed + pos + int(config.num_layers) * per_layer + norm
    )


def _kv_bytes_per_token(config: Any, dtype_bytes: int) -> int:
    kv_h = int(config.num_kv_heads or config.num_heads)
    return 2 * int(config.num_layers) * kv_h * int(config.head_dim) * (
        dtype_bytes
    )


def replica_throughput(
    config: Any,
    *,
    mesh_shape: Sequence[int] = (1, 2),
    batch_size: int = 4,
    dtype_bytes: int = 4,
    profile: Profile | None = None,
) -> dict:
    """Roofline tokens/second for ONE replica serving decode on its
    sub-mesh: per step the batch pays ``batch·2P`` FLOPs against the
    profile's effective compute peak while streaming the parameter
    tree once from HBM (decode's classic memory bound; the batch
    amortizes the stream). The step estimate is the max of the two
    terms — same discipline as ``costmodel.price``."""
    if profile is None:
        profile = table_profile("TPU v5 lite")
    n_dev = max(1, math.prod(int(s) for s in mesh_shape))
    p = _param_count(config)
    flops_per_tok = 2.0 * p
    compute_s = (batch_size * flops_per_tok / n_dev) / max(
        profile.peak_flops * profile.mfu_eff, 1.0
    )
    param_bytes = p * dtype_bytes
    memory_s = (param_bytes / n_dev) / max(
        profile.hbm_bw * profile.mbu_eff, 1.0
    )
    step_s = max(compute_s, memory_s)
    return {
        "params": p,
        "param_bytes": param_bytes,
        "n_dev": n_dev,
        "step_s": step_s,
        "bound": "compute" if compute_s >= memory_s else "memory",
        "tok_s": batch_size / step_s if step_s > 0 else float("inf"),
    }


def check_fit(
    config: Any,
    *,
    mesh_shape: Sequence[int] = (1, 2),
    batch_size: int = 4,
    paged_pages: int | None = None,
    page_size: int = 4,
    dtype_bytes: int = 4,
    max_replicas: int = 4,
    assumptions: PlannerAssumptions | None = None,
    topology: Any = None,
    total_devices: int | None = None,
) -> dict:
    """Static feasibility of one fleet shape: HBM fit per replica
    (params + the KV page pool, or ``batch·max_seq`` rows unpaged) and
    the topology carve (``max_replicas`` whole sub-meshes, each inside
    one ICI domain). Returns the audit dict; ``ok`` gates the plan."""
    a = assumptions or PlannerAssumptions()
    n_dev = max(1, math.prod(int(s) for s in mesh_shape))
    p_bytes = _param_count(config) * dtype_bytes
    per_tok = _kv_bytes_per_token(config, dtype_bytes)
    if paged_pages is not None:
        kv_bytes = paged_pages * page_size * per_tok
    else:
        kv_bytes = batch_size * int(config.max_seq_len) * per_tok
    need = p_bytes + kv_bytes
    have = a.hbm_bytes_per_device * n_dev
    hbm_ok = need <= have
    carve_ok = True
    carve_why = None
    if total_devices is not None and max_replicas * n_dev > total_devices:
        carve_ok = False
        carve_why = (
            f"{max_replicas} replicas × {n_dev} devices exceed the "
            f"{total_devices} available"
        )
    if topology is not None and carve_ok:
        dom = int(topology.ici_domain_devices)
        if n_dev > dom:
            carve_ok = False
            carve_why = (
                f"sub-mesh of {n_dev} devices straddles the "
                f"{dom}-device ICI domain (every collective would ride "
                "DCN)"
            )
        elif total_devices is not None:
            whole = (total_devices // dom) * (dom // n_dev)
            if whole < max_replicas:
                carve_ok = False
                carve_why = (
                    f"only {whole} intra-domain sub-meshes of {n_dev} "
                    f"fit; {max_replicas} wanted"
                )
    return {
        "hbm_ok": bool(hbm_ok),
        "hbm_need_bytes": float(need),
        "hbm_have_bytes": float(have),
        "carve_ok": bool(carve_ok),
        "carve_why": carve_why,
        "ok": bool(hbm_ok and carve_ok),
    }


def plan_capacity(
    events: Sequence[dict],
    config: Any,
    *,
    max_new_tokens: int,
    mesh_shape: Sequence[int] = (1, 2),
    batch_size: int = 4,
    min_replicas: int = 1,
    max_replicas: int = 4,
    assumptions: PlannerAssumptions | None = None,
    replica_tok_s: float | None = None,
    topology: Any = None,
    total_devices: int | None = None,
    paged_pages: int | None = None,
    page_size: int = 4,
    dtype_bytes: int = 4,
) -> dict:
    """The offline answer: K(t) over ``events`` (trace-event dicts with
    ``t`` and ``prompt_len``), each window's demand divided by one
    replica's deliverable throughput (roofline × headroom, or the
    caller's measured ``replica_tok_s``), clamped to the fleet bounds.

    The returned plan prices every static fleet size against the
    elastic K(t) and names the BEST STATIC fleet — the smallest K
    covering peak demand; smaller fleets are priced but flagged
    infeasible (they queue without bound at peak, so their "cost" buys
    an SLO breach). ``scripts/replay.py --autoscale`` persists this as
    ``capacity_plan.json`` and scores the live controller against it.
    """
    a = assumptions or PlannerAssumptions()
    if not events:
        raise ValueError("cannot plan capacity over an empty trace")
    profile = table_profile(a.profile)
    tput = replica_throughput(
        config, mesh_shape=mesh_shape, batch_size=batch_size,
        dtype_bytes=dtype_bytes, profile=profile,
    )
    supply = (
        replica_tok_s if replica_tok_s is not None else tput["tok_s"]
    )
    deliverable = supply * a.headroom
    if deliverable <= 0:
        raise ValueError(f"non-positive deliverable throughput {supply}")
    fit = check_fit(
        config, mesh_shape=mesh_shape, batch_size=batch_size,
        paged_pages=paged_pages, page_size=page_size,
        dtype_bytes=dtype_bytes, max_replicas=max_replicas,
        assumptions=a, topology=topology, total_devices=total_devices,
    )
    duration = max(float(e["t"]) for e in events)
    n_windows = max(1, math.ceil(duration / a.window_s))
    demand = [0.0] * n_windows
    total_tokens = 0.0
    for e in events:
        w = min(n_windows - 1, int(float(e["t"]) // a.window_s))
        toks = (
            float(e["prompt_len"]) * (1.0 - a.prefix_hit_ratio)
            + float(max_new_tokens)
        )
        demand[w] += toks
        total_tokens += toks
    windows = []
    elastic_replica_s = 0.0
    peak_k = min_replicas
    for w, toks in enumerate(demand):
        w_s = a.window_s
        need = toks / w_s / deliverable
        k = min(max_replicas, max(min_replicas, math.ceil(need)))
        peak_k = max(peak_k, k)
        elastic_replica_s += k * w_s
        windows.append({
            "t0": w * a.window_s,
            "t1": (w + 1) * a.window_s,
            "demand_tok_s": toks / w_s,
            "k": k,
        })
    n_dev = tput["n_dev"]
    rate_s = a.usd_per_device_hour / 3600.0
    horizon_s = n_windows * a.window_s
    statics = {}
    for k in range(min_replicas, max_replicas + 1):
        statics[str(k)] = {
            "replica_s": k * horizon_s,
            "cost_usd": k * horizon_s * n_dev * rate_s,
            "covers_peak": k >= peak_k,
        }
    elastic_cost = elastic_replica_s * n_dev * rate_s
    best_static = str(peak_k)
    return {
        "assumptions": dataclasses.asdict(a),
        "throughput": {**tput, "profile": profile.name,
                       "measured_tok_s": replica_tok_s,
                       "deliverable_tok_s": deliverable},
        "fit": fit,
        "windows": windows,
        "horizon_s": horizon_s,
        "total_tokens": total_tokens,
        "peak_k": peak_k,
        "elastic": {
            "replica_s": elastic_replica_s,
            "cost_usd": elastic_cost,
        },
        "static": statics,
        "best_static_k": best_static,
        "elastic_vs_best_static_saving_pct": (
            100.0 * (1.0 - elastic_replica_s / (peak_k * horizon_s))
            if peak_k * horizon_s > 0 else 0.0
        ),
    }


def timeline_replica_seconds(
    timeline: Sequence[dict], *, k0: int, duration_s: float,
) -> float:
    """Integrate K(t) from an autoscaler decision timeline: ``k0``
    replicas at t=0, each grow/shrink entry (``t``, ``k``) steps the
    count, held to ``duration_s``. Decisions that move no capacity
    (canary, rebalance, preempt, holds) do not change K."""
    k = k0
    t = 0.0
    total = 0.0
    for e in timeline:
        if e.get("action") not in ("grow", "shrink") or "k" not in e:
            continue
        et = min(max(float(e.get("t", 0.0)), t), duration_s)
        total += k * (et - t)
        t, k = et, int(e["k"])
    total += k * max(0.0, duration_s - t)
    return total


def score_timeline(
    plan: dict, timeline: Sequence[dict], *, k0: int,
    duration_s: float,
) -> dict:
    """Planner vs live: both sides reduce to provisioned
    replica-seconds over the SAME horizon, so the gap is a single
    percentage — how far the live controller's provisioning landed
    from the offline optimum (either direction is a miss: over is
    money, under is queued SLO risk)."""
    horizon = float(plan["horizon_s"])
    scale = horizon / duration_s if duration_s > 0 else 1.0
    live = timeline_replica_seconds(
        timeline, k0=k0, duration_s=duration_s,
    ) * scale
    planned = float(plan["elastic"]["replica_s"])
    gap = (
        abs(live - planned) / planned * 100.0 if planned > 0 else 0.0
    )
    return {
        "planned_replica_s": planned,
        "live_replica_s": live,
        "live_raw_replica_s": live / scale if scale else live,
        "time_scale": scale,
        "gap_pct": gap,
    }
