"""Deterministic trace-driven load generation (round 20).

The chaos matrix proves the fleet SURVIVES; nothing before this module
proved its ECONOMICS — because nothing could drive the fleet with a
realistic, repeatable traffic shape. This is the missing arrival
process, in three pieces:

* **generation** — :func:`generate_trace` turns a :class:`TraceSpec`
  (per-tenant diurnal rate curves, bursty cluster arrivals, heavy-tail
  Pareto prompt lengths, flash-crowd spikes) into a sorted list of
  arrival events. Everything derives from ``numpy`` Generators seeded
  by ``(spec.seed, tenant index)``, so the same spec always produces
  the same trace, byte for byte.
* **the versioned JSONL trace format** — :func:`write_trace` /
  :func:`read_trace`. Line 1 is a header record (``trace_version``,
  seed, event count, the full spec); every following line is one
  arrival ``{"rid", "t", "tenant", "prompt_len"}`` with sorted keys and
  compact separators, so regeneration is BYTE-identical and a trace
  diff is a line diff. Prompt token CONTENT is never stored — it is
  resynthesized from ``(seed, rid, prompt_len)`` by
  :func:`synth_prompt`, which keeps the canonical trace small and the
  replay exact.
* **replay** — :func:`replay_trace` feeds the events to a
  :class:`~.router.FleetRouter` through ``add_request(arrival_t=...)``,
  so queue-wait accounting measures the request's TRUE age under the
  trace's clock, not its age at the Python line that admitted it.
  Paced mode sleeps the offered-load gaps (wall-clock realistic,
  measured seconds); unpaced mode admits everything up front
  (deterministic admission/shed order — the determinism tests' mode).
  Every arrival passes the ``"loadgen.arrival"`` chaos seam, where a
  ``mutate`` fault may amplify one event into ``copies`` simultaneous
  clones — the flash-crowd injection the ``flash_crowd`` matrix cell
  drives.

The checked-in canonical trace (:func:`canonical_trace_path`) is one
virtual DAY compressed to 24 replay-seconds (1 s ≙ 1 h): an
``interactive`` tenant peaking midday with an evening flash crowd, a
night-heavy ``batch`` tenant with long heavy-tail prompts, and a calm
``free-tier`` — the fixed workload ``bench.py bench_economics`` and
``scripts/replay.py`` price.
"""

from __future__ import annotations

import dataclasses
import json
import math
import pathlib
import time
from typing import Any, Callable, Sequence

import numpy as np

#: Version stamp of the JSONL trace format; bumped on any change to the
#: header/event schema so a replayer can refuse traces it cannot honor.
TRACE_VERSION = 1

#: rid base for chaos-cloned arrivals (``copies`` > 1): far above any
#: plausible trace rid, so clones never collide with not-yet-admitted
#: trace events (rid = 1_000_000 + source_rid * 1000 + copy_index).
_CLONE_RID_BASE = 1_000_000


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's arrival process + prompt-length distribution.

    Arrivals are a bursty (clustered) Poisson process thinned by a
    diurnal sine: clusters arrive at rate ``rate_rps × m(t) /
    burstiness`` with ``m(t) = 1 + diurnal_amplitude · sin(2π(t/T −
    diurnal_phase))`` (T = the trace duration, one virtual day), each
    cluster holds a geometric number of arrivals (mean ``burstiness``)
    jittered by Exponential(``burst_jitter_s``) gaps — so ``rate_rps``
    stays the mean offered rate while ``burstiness`` controls how
    clumped it is. Prompt lengths are ``prompt_len_min`` plus a Pareto
    tail with index ``prompt_len_alpha`` scaled so the mean excess is
    ``prompt_len_tail`` tokens, clipped at ``prompt_len_max``.
    """

    name: str
    rate_rps: float
    burstiness: float = 1.0
    burst_jitter_s: float = 0.02
    diurnal_amplitude: float = 0.0
    diurnal_phase: float = 0.0
    prompt_len_min: int = 4
    prompt_len_tail: float = 6.0
    prompt_len_alpha: float = 2.5
    prompt_len_max: int = 64

    def __post_init__(self):
        if self.rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {self.rate_rps}")
        if self.burstiness < 1.0:
            raise ValueError(
                f"burstiness must be >= 1, got {self.burstiness}"
            )
        if not 0.0 <= self.diurnal_amplitude <= 1.0:
            raise ValueError(
                "diurnal_amplitude must be in [0, 1], got "
                f"{self.diurnal_amplitude}"
            )
        if self.prompt_len_alpha <= 1.0:
            raise ValueError(    # mean of a Pareto tail diverges at <= 1
                f"prompt_len_alpha must be > 1, got {self.prompt_len_alpha}"
            )
        if not 1 <= self.prompt_len_min <= self.prompt_len_max:
            raise ValueError(
                f"need 1 <= prompt_len_min <= prompt_len_max, got "
                f"[{self.prompt_len_min}, {self.prompt_len_max}]"
            )


@dataclasses.dataclass(frozen=True)
class FlashCrowd:
    """A spike window: extra Poisson arrivals for one tenant at
    ``multiplier ×`` its base rate over ``[t_s, t_s + duration_s)`` —
    ON TOP of the base process (a flash crowd adds traffic, it does not
    reshape the day)."""

    tenant: str
    t_s: float
    duration_s: float
    multiplier: float = 10.0


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """The full generation recipe — everything :func:`generate_trace`
    needs, and exactly what the trace header records."""

    duration_s: float
    seed: int = 0
    tenants: tuple[TenantSpec, ...] = ()
    flash_crowds: tuple[FlashCrowd, ...] = ()

    def __post_init__(self):
        if self.duration_s <= 0:
            raise ValueError(
                f"duration_s must be > 0, got {self.duration_s}"
            )
        if not self.tenants:
            raise ValueError("a trace needs at least one tenant")
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"tenant names must be unique: {names}")
        known = set(names)
        for fc in self.flash_crowds:
            if fc.tenant not in known:
                raise ValueError(
                    f"flash crowd names unknown tenant {fc.tenant!r}"
                )


def _diurnal(t: float, spec: TraceSpec, ten: TenantSpec) -> float:
    return max(0.0, 1.0 + ten.diurnal_amplitude * math.sin(
        2.0 * math.pi * (t / spec.duration_s - ten.diurnal_phase)
    ))


def _lengths(rng, ten: TenantSpec, n: int) -> np.ndarray:
    # Pareto(α) has mean 1/(α−1); scaling by tail·(α−1) makes the mean
    # excess over prompt_len_min exactly prompt_len_tail.
    excess = rng.pareto(ten.prompt_len_alpha, size=n) * (
        ten.prompt_len_tail * (ten.prompt_len_alpha - 1.0)
    )
    return np.clip(
        ten.prompt_len_min + excess.astype(np.int64),
        ten.prompt_len_min, ten.prompt_len_max,
    )


def generate_trace(spec: TraceSpec) -> list[dict]:
    """Generate the arrival events of ``spec`` — sorted by time, rids
    assigned in that order. Deterministic: per-tenant Generators seeded
    by ``(spec.seed, tenant index)``, fixed draw order."""
    arrivals: list[tuple[float, str, int]] = []   # (t, tenant, length)
    for ti, ten in enumerate(spec.tenants):
        rng = np.random.default_rng([int(spec.seed), 7919, ti])
        # Bursty base process: candidate clusters at the PEAK rate,
        # thinned down to the diurnal curve (standard thinning — the
        # accepted clusters are exactly inhomogeneous-Poisson).
        peak = 1.0 + ten.diurnal_amplitude
        cluster_rate = ten.rate_rps * peak / ten.burstiness
        t = 0.0
        times: list[float] = []
        while True:
            t += rng.exponential(1.0 / cluster_rate)
            if t >= spec.duration_s:
                break
            keep = rng.random() < _diurnal(t, spec, ten) / peak
            size = int(rng.geometric(1.0 / ten.burstiness))
            jitter = np.cumsum(
                rng.exponential(ten.burst_jitter_s, size=size)
            )
            if not keep:
                continue     # draws above happen either way: one stream
            for off in (0.0, *jitter[:-1]):
                if t + off < spec.duration_s:
                    times.append(t + off)
        # Flash crowds: additive homogeneous Poisson inside the window.
        for fi, fc in enumerate(spec.flash_crowds):
            if fc.tenant != ten.name:
                continue
            crng = np.random.default_rng(
                [int(spec.seed), 104659, ti, fi]
            )
            rate = ten.rate_rps * fc.multiplier
            t = fc.t_s
            while True:
                t += crng.exponential(1.0 / rate)
                if t >= min(fc.t_s + fc.duration_s, spec.duration_s):
                    break
                times.append(t)
        times.sort()
        for t, ln in zip(times, _lengths(rng, ten, len(times))):
            arrivals.append((round(float(t), 6), ten.name, int(ln)))
    arrivals.sort()
    return [
        {"rid": i, "t": t, "tenant": name, "prompt_len": ln}
        for i, (t, name, ln) in enumerate(arrivals)
    ]


def synth_prompt(
    seed: int, rid: int, length: int, vocab_size: int,
) -> np.ndarray:
    """The deterministic prompt content of one trace event: tokens in
    ``[1, vocab_size)`` keyed by ``(trace seed, rid)`` — the trace file
    stores only the length, the replayer resynthesizes the bytes."""
    rng = np.random.default_rng([int(seed), 104729, int(rid)])
    return rng.integers(
        1, max(2, int(vocab_size)), size=int(length), dtype=np.int64
    ).astype(np.int32)


# --- the versioned JSONL trace format -----------------------------------


def _dump(obj: dict) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def write_trace(
    path, spec: TraceSpec, events: list[dict] | None = None,
) -> list[dict]:
    """Write ``spec``'s trace (generating it unless ``events`` is
    given) as versioned JSONL. Byte-identical across runs for the same
    spec — the regeneration identity the tier-1 tests pin."""
    if events is None:
        events = generate_trace(spec)
    header = {
        "kind": "ljst.loadgen.trace",
        "trace_version": TRACE_VERSION,
        "seed": int(spec.seed),
        "duration_s": spec.duration_s,
        "events": len(events),
        "spec": dataclasses.asdict(spec),
    }
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        f.write(_dump(header) + "\n")
        for ev in events:
            f.write(_dump(ev) + "\n")
    return events


def read_trace(path) -> tuple[dict, list[dict]]:
    """Read a JSONL trace → ``(header, events)``; refuses unknown
    versions (the format is a contract, not a suggestion)."""
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"empty trace file: {path}")
    header = json.loads(lines[0])
    ver = header.get("trace_version")
    if ver != TRACE_VERSION:
        raise ValueError(
            f"trace {path}: version {ver!r}, this replayer speaks "
            f"{TRACE_VERSION}"
        )
    events = [json.loads(ln) for ln in lines[1:]]
    if len(events) != header.get("events"):
        raise ValueError(
            f"trace {path}: header promises {header.get('events')} "
            f"events, file holds {len(events)}"
        )
    return header, events


# --- the canonical 24h-compressed day -----------------------------------


def canonical_day_spec() -> TraceSpec:
    """One virtual day compressed to 24 replay-seconds (1 s ≙ 1 h):
    midday-peaking interactive traffic with an evening flash crowd,
    night-heavy batch with long heavy-tail prompts, a calm free tier.
    Prompt lengths stay ≤ 40 so CONFIG_TINY (max_seq_len 64) can decode
    16 fresh tokens on top."""
    return TraceSpec(
        duration_s=24.0,
        seed=20,
        tenants=(
            TenantSpec(
                "interactive", rate_rps=1.1, burstiness=2.0,
                diurnal_amplitude=0.7, diurnal_phase=0.25,
                prompt_len_min=4, prompt_len_tail=5.0,
                prompt_len_alpha=2.5, prompt_len_max=24,
            ),
            TenantSpec(
                "batch", rate_rps=0.7, burstiness=3.0,
                diurnal_amplitude=0.5, diurnal_phase=0.75,
                prompt_len_min=8, prompt_len_tail=10.0,
                prompt_len_alpha=1.8, prompt_len_max=40,
            ),
            TenantSpec(
                "free-tier", rate_rps=0.5, burstiness=1.0,
                diurnal_amplitude=0.3, diurnal_phase=0.25,
                prompt_len_min=3, prompt_len_tail=3.0,
                prompt_len_alpha=3.0, prompt_len_max=12,
            ),
        ),
        flash_crowds=(
            FlashCrowd(
                tenant="interactive", t_s=18.5, duration_s=1.5,
                multiplier=8.0,
            ),
        ),
    )


def canonical_trace_path() -> pathlib.Path:
    """The checked-in canonical trace (regenerate with
    ``scripts/replay.py --regen``)."""
    return (
        pathlib.Path(__file__).resolve().parent.parent
        / "data" / "traces" / "canonical_day.jsonl"
    )


# --- replay --------------------------------------------------------------


def replay_trace(
    router,
    events: Sequence[dict],
    *,
    seed: int,
    vocab_size: int,
    speed: float = 1.0,
    pace: bool = True,
    on_tick: Callable[[float], None] | None = None,
    step_hz: float | None = None,
    max_iters: int = 500_000,
) -> dict:
    """Drive ``router`` with a generated/loaded trace.

    Arrivals admit strictly in trace order through
    ``FleetRouter.add_request(arrival_t=...)`` — paced mode stamps each
    event's scheduled instant (``t0 + t/speed``) as its arrival, so
    queue-wait telemetry measures offered-load truth; unpaced mode
    (``pace=False``) admits every event immediately, which makes the
    admission AND shed order a pure function of the trace (the
    determinism tests' mode). Each event passes the
    ``"loadgen.arrival"`` chaos seam first; a mutate fault may set
    ``"copies": n`` to clone the arrival n-fold (clone rids offset by
    ``_CLONE_RID_BASE`` — collision-free with trace rids). Fleet-level
    sheds (:class:`AdmissionError`) are tallied, never raised.

    ``step_hz`` (paced mode) is a SERVICE-RATE throttle: at most that
    many ``router.step()`` calls per wall second. One router step steps
    every live replica once, so under the throttle fleet throughput is
    proportional to live replica count — on hosts whose emulated
    engines outrun the compressed trace this restores the resource
    model the capacity planner prices (K is the binding resource), and
    it is what makes the elastic replay's scale decisions load-bearing.
    Token streams stay bit-identical (recompute-exact engines; only the
    step *schedule* changes).

    Returns ``{"results", "admission_order", "tenant_of", "source_of",
    "shed", "offered", "wall_s"}`` — results keyed by rid;
    ``source_of`` maps every admitted rid (clones included) back to the
    trace event that caused it. ``on_tick(elapsed_s)`` fires once per
    replay loop iteration (the burn-timeline sampler's hook).
    """
    from learning_jax_sharding_tpu.models.serving import AdmissionError
    from learning_jax_sharding_tpu.robustness.chaos import chaos_hook

    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    if step_hz is not None and (not pace or step_hz <= 0):
        raise ValueError(
            f"step_hz needs paced mode and a positive rate, got "
            f"step_hz={step_hz} pace={pace}"
        )
    events = sorted(events, key=lambda e: (e["t"], e["rid"]))
    results: dict[int, Any] = {}
    admission_order: list[int] = []
    tenant_of: dict[int, str | None] = {}
    source_of: dict[int, int] = {}
    shed: list[dict] = []
    t0 = time.perf_counter()
    i = iters = steps = 0
    while i < len(events) or router.has_work():
        while i < len(events):
            ev = events[i]
            due = ev["t"] / speed
            if pace and due > time.perf_counter() - t0:
                break
            i += 1
            ev = chaos_hook(
                "loadgen.arrival", dict(ev),
                rid=ev.get("rid"), tenant=ev.get("tenant"),
            )
            prompt = synth_prompt(
                seed, ev["rid"], ev["prompt_len"], vocab_size
            )
            for c in range(max(1, int(ev.get("copies", 1)))):
                rid = (
                    ev["rid"] if c == 0
                    else _CLONE_RID_BASE + ev["rid"] * 1000 + c
                )
                try:
                    got = router.add_request(
                        prompt, rid=rid, tenant=ev.get("tenant"),
                        deadline_s=ev.get("deadline_s"),
                        arrival_t=t0 + due if pace else None,
                    )
                except AdmissionError:
                    shed.append({
                        "rid": rid, "source_rid": ev["rid"],
                        "tenant": ev.get("tenant"),
                        "prompt_len": int(ev["prompt_len"]),
                    })
                    continue
                admission_order.append(got)
                tenant_of[got] = ev.get("tenant")
                source_of[got] = ev["rid"]
        if router.has_work():
            if step_hz is not None and steps >= (
                time.perf_counter() - t0
            ) * step_hz:
                # Over the service-rate budget: hold the step (the
                # queue builds — that IS the signal) but keep polling
                # admissions and ticking the control loop.
                time.sleep(min(2e-3, 1.0 / step_hz))
            else:
                router.step()
                steps += 1
                results.update(router.pop_finished())
        elif pace and i < len(events):
            # Idle gap before the next scheduled arrival: sleep a
            # sliver of it instead of busy-spinning the admission poll.
            time.sleep(min(2e-3, max(0.0, (
                events[i]["t"] / speed - (time.perf_counter() - t0)
            ))))
        if on_tick is not None:
            on_tick(time.perf_counter() - t0)
        iters += 1
        if iters > max_iters:
            raise RuntimeError(
                f"replay wedged: {iters} iterations, work remains"
            )
    results.update(router.pop_finished())
    return {
        "results": results,
        "admission_order": admission_order,
        "tenant_of": tenant_of,
        "source_of": source_of,
        "shed": shed,
        "offered": len(events),
        "wall_s": time.perf_counter() - t0,
    }
