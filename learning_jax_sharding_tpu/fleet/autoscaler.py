"""The SLO-burn autoscaler: the fleet reshapes itself under load.

Round 23's tentpole (ROADMAP item 5's open half): the chaos matrix
proves the fleet *survives* faults and the trace replayer proves we can
*offer* a realistic day of traffic — this module closes the loop by
letting the fleet GROW, SHRINK, REBALANCE, and CANARY in response to
that traffic, without ever dropping or corrupting work:

* **signals** — each :meth:`Autoscaler.step` (one control evaluation,
  driven from the replay tick or any outer loop) reads two numbers:
  the fleet's worst SLO burn rate (error budget consumed per unit
  budgeted — the round-10 currency) and slot OCCUPANCY (unfinished
  requests over live decode slots; >1 means queues are building).
  Burn alone never moves the fleet: it is windowed breach *history*,
  so it is trusted only when standing queues corroborate it
  (``occ_corroborate``) — uncorroborated burn neither buys machines
  nor blocks their return. The burn signal passes through the
  ``fleet.scale_signal`` chaos seam so the matrix can replay a
  flapping sensor deterministically.
* **hysteresis, not a thermostat** — a scale action needs
  ``hot_evals`` consecutive hot readings (grow) or ``cold_evals``
  consecutive cold readings (shrink), plus a wall-clock ``cooldown_s``
  since the last action. Growing is deliberately easier than
  shrinking: adding capacity costs money, flapping costs correctness
  risk and drain churn. The ``autoscaler_flap`` matrix cell pins this:
  an oscillating burn signal produces ZERO churn, only counted holds.
* **grow** — prefer REVIVING a standby replica the router retired
  earlier (compiled, warm, ledger history intact — the spot
  re-admission path, gated by exponential backoff per preemption);
  otherwise build one through the caller's ``factory``. A fresh
  replica is admitted ONLY after the CANARY: a probe request runs to
  completion on the engine *before* :meth:`~.router.FleetRouter.
  adopt_replica` lets real traffic near it, and the probe's compute is
  reset out of the serving books.
* **shrink** — the victim (preemptible first, then least-loaded)
  retires through the router's graceful drain-and-migrate:
  in-flight work requeues on survivors bit-identically, warm KV
  migrates through the counted tier plans. Scale-in is the ONE
  elastic action with a latency tail, so every drain's wall-ms lands
  in ``router.drain_ms`` (bench gates the p99).
* **rebalance** — sustained heat with nowhere to grow (at
  ``max_replicas``) forces a KV demotion sweep instead: error budget
  buys HBM headroom for live work (the round-15 burn-demote lever,
  now a logged decision).

**Every action is a logged decision**: the ``_decision`` context
manager wraps each one — flight-recorder event (``fleet.scale_decision``),
timeline entry (the ``scale_timeline.json`` artifact), counter. The
``unguarded-scale-decision`` lint rule fails the build on any scale
action an autoscaler takes outside such a frame, so the decision log
is complete by construction, not by discipline.

The loop holds NO clock of its own: the caller passes ``now`` (replay
wall seconds, or a synthetic step index in tests), which keeps every
run — including chaos-matrix cells — deterministic and replayable.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable

import numpy as np

from learning_jax_sharding_tpu.fleet.replica import EngineReplica
from learning_jax_sharding_tpu.models.serving import RequestFailure
from learning_jax_sharding_tpu.robustness.chaos import chaos_hook

#: rid space for canary probes — far above trace rids (< 1e6) and the
#: flash-crowd clones (1e6+), so a probe can never collide with work.
_PROBE_RID_BASE = 900_000_000


@dataclasses.dataclass
class AutoscalerConfig:
    """Control-loop knobs. Defaults are tuned for the canonical-day
    replay (24 h compressed into ~12 wall seconds at speed 2): react
    within a flash crowd's rise, never flap on its ripples."""

    #: Worst-tenant burn above this reads HOT (error budget burning).
    burn_high: float = 1.0
    #: ... and below this (with low occupancy) reads COLD. The wide gap
    #: between the two thresholds is the first hysteresis stage.
    burn_low: float = 0.25
    #: Unfinished requests per live decode slot above this reads HOT
    #: (queues building faster than slots retire).
    occ_high: float = 1.5
    #: ... and below this reads COLD (paying for idle slots).
    occ_low: float = 0.5
    #: The burn signal is TRUSTED only when occupancy corroborates it
    #: (at least this many requests per slot): the burn window holds
    #: breach *history*, and history without standing queues is
    #: yesterday's pain — it neither buys machines (grow) nor blocks
    #: their return (shrink). Uncorroborated burn reads as 0.
    occ_corroborate: float = 1.0
    #: Consecutive hot evaluations before a grow fires.
    hot_evals: int = 3
    #: Consecutive cold evaluations before a shrink fires — harder than
    #: growing on purpose (drain churn is the expensive direction).
    cold_evals: int = 8
    #: Minimum wall seconds between ANY two scale actions.
    cooldown_s: float = 1.0
    min_replicas: int = 1
    max_replicas: int = 4
    #: Grace window (fleet steps) a preemption notice grants.
    grace_steps: int = 2
    #: First re-admission delay after a spot preemption; each further
    #: preemption of the same replica multiplies it (anti-flap).
    spot_backoff_s: float = 0.5
    spot_backoff_mult: float = 2.0
    #: Probe prompt the canary runs end-to-end on a fresh replica.
    probe_tokens: int = 4

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) < min_replicas "
                f"({self.min_replicas})"
            )
        if self.burn_low > self.burn_high or self.occ_low > self.occ_high:
            raise ValueError(
                "hysteresis thresholds must satisfy low <= high "
                f"(burn {self.burn_low}/{self.burn_high}, "
                f"occ {self.occ_low}/{self.occ_high})"
            )


class Autoscaler:
    """The control loop over one :class:`~.router.FleetRouter`.

    ``factory(slot, generation) -> EngineReplica`` builds a brand-new
    replica when no standby exists (may be ``None``: then growth is
    revive-only — the replay's pre-warmed-pool mode, which never pays
    a mid-traffic compile). Drive it by calling :meth:`step` once per
    router step / replay tick with a monotone ``now`` in seconds.
    """

    def __init__(
        self,
        router: Any,
        factory: Callable[[int, int], EngineReplica] | None = None,
        *,
        config: AutoscalerConfig | None = None,
        recorder: Any | None = None,
    ):
        self.router = router
        self.factory = factory
        self.config = config or AutoscalerConfig()
        self.recorder = recorder if recorder is not None else router.recorder
        r = router.registry
        self._c_decisions = r.counter(
            "fleet_scale_decisions_total",
            "scale decisions committed (grow/shrink/rebalance/canary)")
        self._c_holds = r.counter(
            "fleet_scale_holds_total",
            "hot/cold evaluations held by hysteresis, cooldown, or "
            "fleet-size bounds (the anti-flap evidence)")
        self._g_target = r.gauge(
            "fleet_scale_target",
            "live replica count after the last evaluation")
        self._g_burn = r.gauge(
            "fleet_scale_signal_burn",
            "worst SLO burn rate the last evaluation read")
        self._g_occ = r.gauge(
            "fleet_scale_signal_occupancy",
            "requests-per-live-slot the last evaluation read")
        #: Every committed decision, in order — ``scale_timeline.json``.
        self.timeline: list[dict] = []
        self._hot = 0
        self._cold = 0
        self._last_action_t: float | None = None
        self._generation = 0
        self._probes = 0
        self._decision_depth = 0
        self._down: set[str] = set()
        # name → (earliest re-admission t, current delay) — the delay
        # doubles on every further preemption of the same replica.
        self._spot_backoff: dict[str, tuple[float, float]] = {}

    # --- the decision frame -------------------------------------------------

    @contextlib.contextmanager
    def _decision(self, action: str, **attrs: Any):
        """EVERY scale action runs inside one of these frames: the
        yielded dict is the timeline entry (mutate it to attach
        outcomes), and on exit — exceptional or not — the entry is
        counted, appended, and flight-recorded. The
        ``unguarded-scale-decision`` lint rule enforces the wrapping."""
        entry = {"action": action, **attrs}
        self._decision_depth += 1
        try:
            yield entry
        except BaseException as e:
            entry["error"] = str(e)
            raise
        finally:
            self._decision_depth -= 1
            self._c_decisions.inc()
            self.timeline.append(entry)
            self.recorder.record("fleet.scale_decision", **entry)

    # --- signals ------------------------------------------------------------

    def _alive(self) -> list[EngineReplica]:
        return [
            r for r in self.router.replicas.values()
            if r.alive and r.name not in self.router._draining
        ]

    def signals(self) -> tuple[float, float, int]:
        """(worst burn, occupancy, live count) — one read of the fleet.
        Burn routes through the ``fleet.scale_signal`` seam so chaos
        can replay a flapping sensor against the real hysteresis."""
        alive = self._alive()
        burn = max(
            (self.router.policy.burn_rate(r) for r in alive),
            default=0.0,
        )
        burn = float(chaos_hook("fleet.scale_signal", burn))
        slots = sum(r.engine._b for r in alive)
        occ = self.router.inflight() / slots if slots > 0 else float("inf")
        return burn, occ, len(alive)

    # --- the control loop ---------------------------------------------------

    def step(self, now: float, *, floor: int | None = None) -> dict | None:
        """One control evaluation at wall/trace time ``now`` (seconds,
        monotone). Returns the committed decision entry, or ``None``
        when the loop held.

        ``floor`` is the FEED-FORWARD minimum fleet size — typically
        the capacity plan's k for the current window. Below it the
        loop grows immediately (no hysteresis, no cooldown: the plan
        already priced this burst in, waiting for burn to confirm it
        is how a reactive loop loses the crowd's front), and scale-in
        never drops under it. The reactive burn/occupancy loop owns
        everything ABOVE the floor."""
        self._observe(now)
        cfg = self.config
        burn, occ, k = self.signals()
        self._g_burn.set(burn)
        self._g_occ.set(occ)
        self._g_target.set(k)
        # Burn without standing queues is history, not load: trust it
        # only when occupancy corroborates (see ``occ_corroborate``).
        trusted = burn if occ >= cfg.occ_corroborate else 0.0
        hot = occ > cfg.occ_high or trusted > cfg.burn_high
        cold = trusted < cfg.burn_low and occ < cfg.occ_low
        self._hot = self._hot + 1 if hot else 0
        self._cold = self._cold + 1 if cold else 0
        fmin = min(
            max(cfg.min_replicas, int(floor or 0)), cfg.max_replicas,
        )
        if k < fmin:
            decided = self._grow(now, burn=burn, occ=occ, floor=fmin)
            if decided is not None:
                self._hot = self._cold = 0
                self._last_action_t = now
                return decided
            self._c_holds.inc()   # floor wants a replica; none adoptable
            return None
        if not (hot or cold):
            return None
        cooling = (
            self._last_action_t is not None
            and now - self._last_action_t < cfg.cooldown_s
        )
        decided: dict | None = None
        if hot and self._hot >= cfg.hot_evals and not cooling:
            if k < cfg.max_replicas:
                decided = self._grow(now, burn=burn, occ=occ)
            elif self.router.kv_economy is not None:
                decided = self._rebalance(now, burn=burn, occ=occ)
        elif cold and self._cold >= cfg.cold_evals and not cooling:
            if k > fmin:
                decided = self._shrink(now, burn=burn, occ=occ, floor=fmin)
        if decided is None:
            # A hot/cold reading the loop deliberately sat on — the
            # hysteresis/cooldown evidence the flap cell asserts.
            self._c_holds.inc()
            return None
        self._hot = self._cold = 0
        self._last_action_t = now
        return decided

    def _observe(self, now: float) -> None:
        """Track replica deaths; a PREEMPTIBLE death arms (or doubles)
        that replica's re-admission backoff — the spot anti-flap."""
        for name in sorted(self.router.replicas):
            rep = self.router.replicas[name]
            if not rep.alive and name not in self._down:
                self._down.add(name)
                if rep.preemptible:
                    prev = self._spot_backoff.get(name)
                    delay = (
                        self.config.spot_backoff_s if prev is None
                        else prev[1] * self.config.spot_backoff_mult
                    )
                    self._spot_backoff[name] = (now + delay, delay)
                    self.recorder.record(
                        "fleet.spot_backoff", replica=name,
                        delay_s=delay,
                    )
            elif rep.alive:
                self._down.discard(name)

    # --- actions ------------------------------------------------------------

    def _standby(self, now: float) -> EngineReplica | None:
        """Best revival candidate: a retired replica whose engine ran
        dry (drained — clean by construction) and whose spot backoff,
        if armed, has expired."""
        for name in sorted(self.router.replicas):
            rep = self.router.replicas[name]
            if rep.alive or rep.engine.has_work():
                continue
            gate = self._spot_backoff.get(name)
            if gate is not None and now < gate[0]:
                continue
            return rep
        return None

    def _grow(
        self, now: float, *, burn: float, occ: float,
        floor: int | None = None,
    ) -> dict | None:
        rep = self._standby(now)
        revived = rep is not None
        if rep is None and self.factory is not None:
            self._generation += 1
            rep = self.factory(len(self.router.replicas), self._generation)
        if rep is None:
            return None            # nothing to adopt: the loop holds
        if not revived:
            with self._decision(
                "canary", t=now, replica=rep.name, burn=burn, occ=occ,
            ) as entry:
                entry["probe_steps"] = self._warm_probe(rep)
        with self._decision(
            "grow", t=now, replica=rep.name, revived=revived,
            preemptible=rep.preemptible, burn=burn, occ=occ,
            floor=floor,
        ) as entry:
            self.router.adopt_replica(rep)
            entry["k"] = len(self._alive())
        return entry

    def _shrink(
        self, now: float, *, burn: float, occ: float,
        floor: int | None = None,
    ) -> dict | None:
        keep = self.config.min_replicas if floor is None else floor
        cands = [r for r in self._alive() if r.role == "unified"]
        if len(cands) <= keep:
            return None
        victim = min(cands, key=lambda r: (
            not r.preemptible,     # spot capacity goes first
            r.engine.queue_depth() + r.engine.occupied_slots(),
            r.name,
        ))
        with self._decision(
            "shrink", t=now, replica=victim.name, burn=burn, occ=occ,
        ) as entry:
            info = self.router.retire_replica(
                victim.name, reason="scale_in",
            )
            entry["drain_ms"] = info["drain_ms"]
            entry["rerouted"] = len(info["rerouted"])
            entry["migrated_pages"] = info["migrated_pages"]
            entry["k"] = len(self._alive())
        return entry

    def _rebalance(self, now: float, *, burn: float, occ: float) -> dict:
        """Hot with nowhere to grow: force one KV demotion sweep —
        reference-free warm pages spill to host tiers, buying the live
        work HBM headroom (pages come back through the counted
        promotion path on their next hit)."""
        with self._decision(
            "rebalance", t=now, burn=burn, occ=occ,
        ) as entry:
            entry["demoted_pages"] = self.router.kv_economy.maintain()
            entry["k"] = len(self._alive())
        return entry

    def preempt(self, name: str, *, grace_steps: int | None = None) -> None:
        """Operator/provider entry for an eviction notice — the same
        graceful countdown the ``fleet.preempt`` seam triggers, logged
        as a decision (the provider decided, but the fleet's response
        is ours to account for)."""
        grace = (
            self.config.grace_steps if grace_steps is None
            else grace_steps
        )
        with self._decision(
            "preempt", replica=name, grace_steps=grace,
        ):
            self.router.preempt_replica(name, grace_steps=grace)

    # --- the canary ---------------------------------------------------------

    def _warm_probe(self, rep: EngineReplica) -> int:
        """Run one probe request END-TO-END on the candidate before any
        real traffic touches it: compiles the engine's programs, proves
        the replica answers, and then resets the engine's stats window
        so the canary's compute never books into serving economics.
        Raises on any failure — a replica that cannot answer a probe is
        not adopted."""
        eng = rep.engine
        rid = _PROBE_RID_BASE + self._probes
        self._probes += 1
        prompt = np.arange(
            1, 1 + self.config.probe_tokens, dtype=np.int32,
        )
        eng.add_request(prompt, rid=rid)
        steps = 0
        while eng.has_work():
            rep.step()
            steps += 1
            if steps > 500:
                raise RuntimeError(
                    f"warm probe wedged on replica {rep.name!r}"
                )
        res = eng.pop_finished().get(rid)
        if res is None or isinstance(res, RequestFailure):
            raise RuntimeError(
                f"warm probe failed on replica {rep.name!r}: {res}"
            )
        eng.reset_stats()
        return steps

    # --- reporting ----------------------------------------------------------

    def report(self) -> dict:
        """JSON-able summary — the replay artifact's ``autoscaler``
        block."""
        actions: dict[str, int] = {}
        for e in self.timeline:
            actions[e["action"]] = actions.get(e["action"], 0) + 1
        drains = self.router.drain_ms
        return {
            "decisions": len(self.timeline),
            "actions": actions,
            "holds": int(self._c_holds.value),
            "drain_ms_p99": (
                float(np.percentile(np.asarray(drains), 99))
                if drains else 0.0
            ),
            "spot_backoffs": {
                n: {"delay_s": d} for n, (_, d) in
                sorted(self._spot_backoff.items())
            },
            "config": dataclasses.asdict(self.config),
        }
