"""Fleet serving (round 11): a multi-replica router over engine
replicas on sub-meshes, with disaggregated prefill/decode and a streamed,
plan-checked KV handoff — ROADMAP item 2.

Layers: :mod:`.policies` (placement + fleet shedding policy),
:mod:`.replica` (one engine on its sub-mesh; builders), :mod:`.router`
(admission, handoff, failover, fleet telemetry), :mod:`.kv_transfer`
(the arXiv-2112.01075-style resharding transfer plan the KV handoff
rides), :mod:`.kv_economy` (round 15: prefix-aware placement + the
HBM → host → peer KV tier ladder), :mod:`.loadgen` (round 20: the
deterministic trace-driven load generator + replay harness behind the
workload observatory), :mod:`.autoscaler` + :mod:`.capacity` (round 23:
the SLO-burn control loop that grows/shrinks the fleet through graceful
drain-and-migrate, and the static planner it is scored against).
"""

from learning_jax_sharding_tpu.fleet.autoscaler import (  # noqa: F401
    Autoscaler,
    AutoscalerConfig,
)
from learning_jax_sharding_tpu.fleet.capacity import (  # noqa: F401
    PlannerAssumptions,
    check_fit,
    plan_capacity,
    replica_throughput,
    score_timeline,
    timeline_replica_seconds,
)
from learning_jax_sharding_tpu.fleet.kv_economy import (  # noqa: F401
    KvEconomy,
    TierStore,
)
from learning_jax_sharding_tpu.fleet.kv_transfer import (  # noqa: F401
    DEFAULT_PAGE_TOKENS,
    Segment,
    TransferPlan,
    execute_transfer,
    plan_transfer,
    transfer_tree,
)
from learning_jax_sharding_tpu.fleet.loadgen import (  # noqa: F401
    TRACE_VERSION,
    FlashCrowd,
    TenantSpec,
    TraceSpec,
    canonical_day_spec,
    canonical_trace_path,
    generate_trace,
    read_trace,
    replay_trace,
    synth_prompt,
    write_trace,
)
from learning_jax_sharding_tpu.fleet.policies import (  # noqa: F401
    FleetPolicy,
)
from learning_jax_sharding_tpu.fleet.replica import (  # noqa: F401
    EngineReplica,
    make_replicas,
    replicated_params,
    sub_meshes,
)
from learning_jax_sharding_tpu.fleet.router import (  # noqa: F401
    FleetRouter,
)
