"""Streamed KV handoff between engine replicas: an explicit resharding
transfer plan.

A disaggregated fleet moves a finished prefill's KV cache row from a
prefill replica's sub-mesh to a decode replica's sub-mesh. Those are
DIFFERENT device sets with (possibly) different shardings, so the move
is an array REDISTRIBUTION — the shared decomposition now lives in
:mod:`learning_jax_sharding_tpu.parallel.resharding` (it also powers
the tenancy subsystem's weight hot-swap); this module re-exports it
under its original fleet-facing names so the router and tests keep one
import surface:

* :func:`plan_transfer` / :class:`TransferPlan` / :class:`Segment` —
  the page-granular block-copy decomposition (replicated sources
  deduplicated, destination replication honestly priced).
* :func:`execute_transfer` — host-side per-shard assembly committed via
  ``jax.make_array_from_callback``, with ``stop`` clipping so bytes the
  causal-at-index masks can never read don't cross the wire.
* :func:`transfer_tree` — the whole exported cache-row tree, with the
  summed bytes/segment telemetry the router's
  ``fleet_kv_transfer_bytes_total`` counters feed on.

All three take the ``codec=`` seam from ``parallel/compression.py``:
``FleetRouter(kv_codec="int8")`` ships prefill→decode handoffs as
block-scaled int8 (``"int8_delta"`` additionally diffs against a
version-stamped base), and the returned stats split ``bytes`` (wire)
from ``raw_bytes`` (pre-codec) so the fleet counters report what
actually crossed DCN, not what the arrays weighed.

The plan moves HOST-VISIBLE bytes on purpose: the two DEVICE-side
programs of the handoff (``ContinuousEngine``'s ``kv_export`` gather and
``kv_ingest`` update) each carry a shardcheck golden pinning ZERO
surprise collectives, so every byte of the handoff is either in those
audited programs or in this explicit, counted plan — never in an XLA
resharding the operator can't see.
"""

from __future__ import annotations

from learning_jax_sharding_tpu.parallel.resharding import (
    DEFAULT_PAGE_TOKENS,
    Box,
    Segment,
    TransferPlan,
    execute_transfer,
    plan_transfer,
    transfer_tree,
)

__all__ = [
    "DEFAULT_PAGE_TOKENS",
    "Box",
    "Segment",
    "TransferPlan",
    "execute_transfer",
    "plan_transfer",
    "transfer_tree",
]
