"""The fleet router: one admission surface over K engine replicas.

ROADMAP item 2 made concrete (round 11): the millions-of-users story
needs more than one ``ContinuousEngine``, and this module is the layer
that makes K of them ONE service —

* **routing** — ``add_request`` places each arrival on the best replica
  by load + SLO burn rate (:class:`~.policies.FleetPolicy`), shedding at
  the FLEET level when the whole fleet is saturated; each replica keeps
  its own round-10 defenses (bounded queue, deadlines, degradation
  ladder) and the router simply routes around a replica that is
  degraded to shedding;
* **disaggregated prefill/decode** — with ``"prefill"`` and ``"decode"``
  replicas, prompts prefill on dedicated engines (``max_new_tokens=1``),
  and each finished prefill's KV row STREAMS to a decode replica through
  the explicit resharding transfer plan (:mod:`.kv_transfer` — counted
  host bytes, golden-pinned device programs) where decode continues
  bit-identically to a single engine of the same mesh shape;
* **failover** — a replica death (the ``fleet.step`` chaos seam, or
  ``kill_replica``) drains its queued AND in-flight requests with
  terminal status ``"rerouted"`` (visible in the dead replica's
  ``pop_finished``/``latency_stats`` — never disguised as fresh
  admissions) and requeues them on survivors, where they RECOMPUTE
  BIT-IDENTICALLY (every sampling draw is keyed by (request id,
  generated position), so a replica swap cannot change a token —
  the round-10 ``_unadmit`` guarantee, now fleet-wide and exercised by
  the ``replica_kill`` chaos-matrix cell);
* **elastic scale** — the fleet's shape is mutable at runtime:
  :meth:`adopt_replica` admits a warmed replica (scale-out / spot
  re-admission), :meth:`retire_replica` runs the GRACEFUL inverse —
  drain-and-migrate: in-flight requests requeue on survivors
  (recompute-exact, like failover) while the retiring replica's warm
  KV migrates through the counted tier plans instead of dying with it;
  a ``preemptible`` replica's eviction notice (the ``fleet.preempt``
  chaos seam, or :meth:`preempt_replica`) starts a grace-window
  countdown that ends in the same graceful retire — spot capacity
  never silently drops work (:mod:`.autoscaler` drives all of this);
* **fleet telemetry** — per-replica registries merge through
  ``parallel.multihost.merge_registry_snapshots(labels=...)`` into one
  snapshot/Prometheus exposition with ``{replica="..."}`` labels, and
  every routing/handoff/failover decision lands in the flight recorder.

The router is a HOST-side scheduler like the engine's own loop: one
``step()`` flushes pending handoffs, steps every live replica once, and
collects retirements. Nothing here dispatches device code of its own —
the engines (and the audited kv programs) do.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Sequence

import jax
import numpy as np

from learning_jax_sharding_tpu.fleet.kv_transfer import transfer_tree
from learning_jax_sharding_tpu.fleet.policies import FleetPolicy
from learning_jax_sharding_tpu.fleet.replica import EngineReplica
from learning_jax_sharding_tpu.models.serving import (
    AdmissionError,
    RequestFailure,
)
from learning_jax_sharding_tpu.robustness.chaos import (
    InjectedFault,
    chaos_hook,
)
from learning_jax_sharding_tpu.telemetry import (
    MetricsRegistry,
    TraceStore,
    merge_tracers,
)


class _FleetRequest:
    """Router-side bookkeeping for one request — the CANONICAL record
    (rid, prompt, deadline, true arrival time) that survives replica
    death, because the replica that held the engine-side copy may not."""

    __slots__ = (
        "rid", "prompt", "deadline_s", "arrival_t", "tenant", "replica",
        "stage", "reroutes", "predicted_hit",
    )

    def __init__(self, rid, prompt, deadline_s, arrival_t, tenant=None):
        self.rid = rid
        self.prompt = prompt
        self.deadline_s = deadline_s
        self.arrival_t = arrival_t
        self.tenant = tenant         # cost-attribution label, hop-stable
        self.replica: str | None = None
        self.stage = "queued"        # prefill|handoff|decode|done
        self.reroutes = 0
        self.predicted_hit = 0       # prefix tokens the placement predicted


class FleetRouter:
    """Admit, route, hand off, and fail over across engine replicas.

    ``replicas``: :class:`~.replica.EngineReplica` records. All
    ``"unified"`` → colocated fleet; any ``"prefill"``/``"decode"`` →
    DISAGGREGATED (then at least one of each is required, prefill
    engines carry ``max_new_tokens=1``, and every decode engine shares
    one ``max_new_tokens`` — the fleet's generation budget). Handoff
    uses ``export_kv``/``ingest_kv``, so disaggregated replicas must be
    unpaged and non-speculative (the engines enforce it).

    The router meters into its own ``registry`` (fleet_* counters) and
    records every decision to ``recorder`` (default: the process flight
    ring). ``kv_page_tokens`` sets the streaming granularity of the
    transfer plans.
    """

    def __init__(
        self,
        replicas: Sequence[EngineReplica],
        *,
        policy: FleetPolicy | None = None,
        recorder: Any | None = None,
        registry: MetricsRegistry | None = None,
        kv_page_tokens: int = 64,
        max_pending_handoffs: int | None = None,
        kv_economy: Any | None = None,
        topology: Any | None = None,
        kv_codec: Any | None = None,
        preempt_grace_steps: int = 2,
    ):
        reps = list(replicas)
        if not reps:
            raise ValueError("a fleet needs at least one replica")
        names = [r.name for r in reps]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        self.replicas: dict[str, EngineReplica] = {r.name: r for r in reps}
        self.policy = policy or FleetPolicy()
        if recorder is None:
            from learning_jax_sharding_tpu.telemetry import (
                default_flight_recorder,
            )

            recorder = default_flight_recorder()
        self.recorder = recorder
        self.registry = registry if registry is not None else MetricsRegistry()
        self.disaggregated = any(r.role != "unified" for r in reps)
        if self.disaggregated:
            if not self._by_role("prefill") or not self._by_role("decode"):
                raise ValueError(
                    "a disaggregated fleet needs >= 1 'prefill' AND >= 1 "
                    "'decode' replica"
                )
            budgets = {
                r.engine._max_new for r in self._by_role("decode")
            }
            if len(budgets) != 1:
                raise ValueError(
                    f"decode replicas disagree on max_new_tokens: {budgets}"
                )
            (self.max_new_tokens,) = budgets
        else:
            # Unified replicas are interchangeable under failover — the
            # bit-identical requeue guarantee needs every one to finish
            # a request at the same budget.
            budgets = {r.engine._max_new for r in reps}
            if len(budgets) != 1:
                raise ValueError(
                    f"replicas disagree on max_new_tokens: {budgets} — "
                    "failover requeue could not recompute bit-identically"
                )
            (self.max_new_tokens,) = budgets
        # EOS drives control flow fleet-wide (the handoff short-circuit,
        # retirement) — replicas of one fleet must agree on it like they
        # agree on the budget (build them from ONE engine config).
        eos = {r.engine._eos for r in reps}
        if len(eos) != 1:
            raise ValueError(f"replicas disagree on eos_id: {eos}")
        (self.eos_id,) = eos
        self.kv_page_tokens = kv_page_tokens
        # Interconnect hierarchy (analysis.topology.TopologyProfile):
        # when set, every KV movement that crosses an ICI domain is
        # counted (and kv_economy-priced) as a DCN hop.
        self.topology = topology
        # KV wire codec (comm-compression layer): a codec name
        # ("int8" / "int8_delta") or instance threaded into every
        # prefill→decode handoff's transfer plan — the fleet_kv_*
        # byte counters then report WIRE bytes, with the raw total and
        # the realized ratio alongside. None ships raw (exact) bytes.
        from learning_jax_sharding_tpu.parallel.compression import get_codec

        self._kv_codec = (
            get_codec(kv_codec) if isinstance(kv_codec, str) or kv_codec is None
            else kv_codec
        )
        # Backpressure on the handoff stage: each parked entry pins one
        # exported KV-row tree, so the queue is bounded (default: two
        # waves of the fleet's decode slots) — at the bound the router
        # stops STEPPING prefill replicas, which stops new exports
        # while their own queues keep holding the (cheap) prompts.
        if max_pending_handoffs is None and self.disaggregated:
            max_pending_handoffs = 2 * sum(
                r.engine._b for r in self._by_role("decode")
            )
        self.max_pending_handoffs = max_pending_handoffs
        r = self.registry
        self._c_requests = r.counter(
            "fleet_requests_total", "requests admitted to the fleet")
        self._c_shed = r.counter(
            "fleet_shed_total",
            "arrivals rejected by fleet-level admission control")
        self._c_failovers = r.counter(
            "fleet_failovers_total", "replica deaths failed over")
        self._c_reroutes = r.counter(
            "fleet_reroutes_total",
            "requests requeued onto a survivor after a replica death")
        self._c_handoffs = r.counter(
            "fleet_handoffs_total",
            "prefill→decode KV handoffs completed")
        self._c_kv_bytes = r.counter(
            "fleet_kv_transfer_bytes_total",
            "bytes moved by the KV resharding transfer plans")
        self._c_kv_segments = r.counter(
            "fleet_kv_transfer_segments_total",
            "page-granular transfer-plan segments copied")
        self._c_kv_dcn_bytes = r.counter(
            "fleet_kv_dcn_bytes_total",
            "cross-ICI-domain (DCN) share of the KV handoff bytes — "
            "always 0 without a topology profile")
        self._c_kv_raw_bytes = r.counter(
            "fleet_kv_raw_bytes_total",
            "pre-codec bytes of the KV handoffs (equal to "
            "fleet_kv_transfer_bytes_total when no kv_codec is set)")
        self._g_kv_ratio = r.gauge(
            "fleet_kv_compression_ratio",
            "raw/wire byte ratio of the most recent KV handoff")
        self._g_kv_ratio.set(1.0)
        self._c_swaps = r.counter(
            "fleet_swaps_total",
            "replica weight swaps committed by rolling_swap")
        self._c_scale_outs = r.counter(
            "fleet_scale_outs_total",
            "replicas adopted into the fleet (scale-out and spot "
            "re-admission, adopt_replica)")
        self._c_scale_ins = r.counter(
            "fleet_scale_ins_total",
            "replicas retired by graceful drain-and-migrate scale-in")
        self._c_preempts = r.counter(
            "fleet_preemptions_total",
            "eviction notices honored on preemptible replicas")
        self._g_alive = r.gauge(
            "fleet_replicas_alive", "replicas currently taking work")
        self._g_inflight = r.gauge(
            "fleet_inflight", "unfinished requests across the fleet")
        self._g_alive.set(len(reps))
        # Request-scoped fleet tracing (round 14): ONE TraceStore for the
        # whole routing domain. The trace id is minted at admission and
        # every replica engine appends its legs to the same record
        # (engine.trace_sink below). auto_complete=False — in a
        # disaggregated fleet a prefill replica also "retires" its
        # one-token pass, which must append legs, not close the trace;
        # only the router's _finish does.
        self.traces = TraceStore(
            registry=self.registry, auto_complete=False,
        )
        for rep in reps:
            rep.engine.trace_sink = self.traces
            rep.engine.trace_replica = rep.name
        # Replicas mid-swap: excluded from placement (admission AND
        # handoff destinations) so they drain — rolling_swap's lever.
        self._swapping: set[str] = set()
        # Replicas draining toward a graceful exit (a preemption grace
        # window): placement-excluded like _swapping, but the countdown
        # ends in retire_replica (drain-and-migrate), not a weight
        # commit.
        self._draining: set[str] = set()
        # Preemption notices in flight: name → grace steps remaining
        # before the router force-retires the replica. The window lets
        # near-done decodes finish in place; everything still unfinished
        # at expiry drains and requeues (recompute-exact).
        self._preempting: dict[str, int] = {}
        self.preempt_grace_steps = int(preempt_grace_steps)
        # Wall-clock cost of every graceful scale-in drain (ms) — the
        # elastic story's tail-latency evidence (bench gates its p99).
        self.drain_ms: list[float] = []
        self._requests: dict[int, _FleetRequest] = {}
        self._finished: dict[int, Any] = {}
        self._next_rid = 0
        self._handoffs: deque[dict] = deque()
        self._plan_cache: dict = {}
        # Destination row layout per decode replica — constant for an
        # engine's lifetime, so two cache-tree traversals per handoff
        # would be pure hot-path waste.
        self._row_layouts: dict[str, tuple] = {}
        # The KV economy (round 15): prefix-aware placement hints +
        # the HBM→host→peer tier ladder. Optional — without it the
        # router is exactly the round-11 prefix-blind fleet.
        self.kv_economy = kv_economy
        if kv_economy is not None:
            kv_economy.attach(self)
        self.reset_stats()

    # --- introspection -----------------------------------------------------

    def _by_role(self, role: str) -> list[EngineReplica]:
        return [r for r in self.replicas.values() if r.role == role]

    def _admission_pool(self) -> list[EngineReplica]:
        # Where NEW prompts go: prefill replicas in a disaggregated
        # fleet, unified replicas otherwise (decode replicas only ever
        # receive ingested rows). A replica mid-rolling-swap takes no
        # new placements — it is draining toward its commit.
        return [
            r
            for r in self._by_role(
                "prefill" if self.disaggregated else "unified"
            )
            if r.name not in self._swapping
            and r.name not in self._draining
        ]

    def inflight(self) -> int:
        """Unfinished requests across the fleet (the fleet-shedding
        measure — includes requests parked in the handoff queue).
        ``_requests`` holds ONLY live work (``_finish`` pops records),
        so this is O(1)."""
        return len(self._requests)

    def has_work(self) -> bool:
        # A pending preemption grace window is fleet work: the drain
        # loop must keep stepping until the countdown resolves, or an
        # idle fleet would strand the eviction half-delivered.
        return self.inflight() > 0 or bool(self._preempting)

    def reset_stats(self):
        """Start a router-side latency window (``latency_stats``) and a
        fresh goodput-ledger window on every replica engine, so
        ``goodput_report`` covers the same interval the latency numbers
        do. Engine stats windows reset too, so the fleet-level TTFT and
        prefix/tier rates in ``latency_stats`` aggregate the same
        interval as the router's own percentiles."""
        self._completed: list[dict] = []
        for rep in self.replicas.values():
            rep.engine.reset_stats()

    # --- admission / routing ----------------------------------------------

    def add_request(
        self, prompt, *, rid: int | None = None,
        deadline_s: float | None = None,
        arrival_t: float | None = None,
        tenant: str | None = None,
    ) -> int:
        """Admit one request to the fleet: fleet-level shedding first
        (``FleetPolicy.max_inflight``), then placement on the
        best-scoring eligible replica — a replica whose OWN admission
        sheds (bounded queue, ladder) is skipped for the next-best; only
        when every replica refuses does the arrival shed at fleet level.
        Raises :class:`AdmissionError` with nothing enqueued either way.

        ``arrival_t`` (a ``perf_counter`` stamp) overrides the arrival
        clock — the trace replayer stamps each event's SCHEDULED
        instant so queue-wait telemetry measures offered-load truth,
        not the Python admission loop's position. ``tenant`` labels the
        request for per-tenant cost attribution and SLO burn accounting;
        the label rides the canonical fleet record, so it survives
        handoffs and failover requeues.
        """
        p = np.asarray(prompt, np.int32).reshape(-1)
        if rid is None:
            rid = self._next_rid
            self._next_rid += 1
        elif rid in self._requests or rid in self._finished:
            raise ValueError(f"request id {rid} already in use")
        self._next_rid = max(self._next_rid, rid + 1)
        if self.policy.should_shed(self.inflight()):
            self._shed(rid, f"fleet at max_inflight "
                            f"({self.policy.max_inflight})")
        freq = _FleetRequest(
            rid, p, deadline_s,
            time.perf_counter() if arrival_t is None else arrival_t,
            tenant,
        )
        self._route(freq)
        self._requests[rid] = freq
        # The trace id is born HERE — router admission — and every hop
        # (placement, handoff, reroute, swap pin, retirement) appends to
        # it. _route's instant may have minted implicitly; this backfills
        # the canonical arrival stamp either way.
        self.traces.mint(rid, arrival_t=freq.arrival_t, tenant=tenant)
        self._c_requests.inc()
        self._g_inflight.set(self.inflight())
        return rid

    def _shed(self, rid, why: str):
        self._c_shed.inc()
        self.recorder.record("fleet.shed", rid=rid, reason=why)
        raise AdmissionError(f"fleet shed request {rid}: {why}")

    def _route(self, freq: _FleetRequest, *, requeue: bool = False):
        last_err = "no eligible replica"
        # Prefix-aware placement: predicted hit tokens per replica
        # (digest + local host tier) become a score BONUS in rank().
        hits = (
            self.kv_economy.predicted_hits(freq.prompt)
            if self.kv_economy is not None else {}
        )
        for rep in self.policy.rank(self._admission_pool(), hits=hits):
            predicted = int(hits.get(rep.name, 0))
            if self.kv_economy is not None and predicted:
                # ON-ADMISSION PROMOTION, before the engine sees the
                # request: host/peer-tier chain pages fill back into
                # HBM so the admission's registry walk can hit them.
                self.kv_economy.promote(rep, freq.prompt)
            try:
                rep.engine.add_request(
                    freq.prompt, rid=freq.rid,
                    deadline_s=freq.deadline_s, arrival_t=freq.arrival_t,
                    tenant=freq.tenant,
                )
            except AdmissionError as e:   # replica-level shed: next best
                last_err = str(e)
                continue
            freq.replica = rep.name
            freq.stage = "prefill" if self.disaggregated else "decode"
            freq.predicted_hit = predicted
            if self.kv_economy is not None:
                # The engine compares this against the REALIZED hit at
                # admission: a page evicted mid-route becomes a counted
                # tier miss + graceful re-prefill, never a wrong token.
                rep.engine.expected_prefix[freq.rid] = predicted
            self.traces.instant(
                freq.rid, "route", replica=rep.name, requeue=requeue,
                predicted_prefix_tokens=predicted,
            )
            self.recorder.record(
                "fleet.route", rid=freq.rid, replica=rep.name,
                requeue=requeue, queue_depth=rep.engine.queue_depth(),
                burn_rate=self.policy.burn_rate(rep),
                predicted_prefix_tokens=predicted,
            )
            return
        why = f"every replica refused (last: {last_err})"
        if requeue:
            # A failover requeue that finds no home is a LOST request
            # (_fail_over terminalizes it as "failover_failed"), not an
            # admission-control rejection — it must not inflate
            # fleet_shed_total or a shed-rate dashboard.
            raise AdmissionError(
                f"failover requeue for request {freq.rid}: {why}"
            )
        self._shed(freq.rid, why)

    # --- the fleet scheduler ------------------------------------------------

    def step(self) -> list[int]:
        """ONE fleet iteration: flush pending handoffs into free decode
        slots, step every live replica that has work (each step is one
        engine scheduler iteration), and collect retirements — handing
        finished prefills off and surfacing final results. Returns the
        rids that FINISHED during this step (``pop_finished`` holds
        them). A replica whose ``fleet.step`` seam raises an
        :class:`~..robustness.chaos.InjectedFault` is declared dead and
        failed over; real infrastructure errors propagate — recovery
        must never guess."""
        before = set(self._finished)
        self._tick_preemptions()
        self._flush_handoffs()
        for name in sorted(self.replicas):
            rep = self.replicas[name]
            if (
                rep.alive and rep.preemptible
                and name not in self._preempting
            ):
                # The spot eviction seam: an InjectedFault here is the
                # provider's notice, not a crash — the replica keeps
                # stepping through its grace window while placement
                # routes around it, then retires gracefully.
                try:
                    chaos_hook(
                        "fleet.preempt", replica=name,
                        rids=[q for q in rep.engine._req if q >= 0],
                    )
                except InjectedFault as e:
                    self.preempt_replica(name, error=str(e))
            if not rep.alive or not rep.has_work():
                continue
            if (
                rep.role == "prefill"
                and self.max_pending_handoffs is not None
                and len(self._handoffs) >= self.max_pending_handoffs
            ):
                # Handoff backpressure: every new prefill retirement
                # would pin another exported KV-row tree — hold this
                # replica's (cheap, host-side) queue instead.
                continue
            try:
                chaos_hook(
                    "fleet.step", replica=name,
                    rids=[q for q in rep.engine._req if q >= 0],
                )
                rep.step()
            except InjectedFault as e:
                self._fail_over(rep, e)
                continue
            self._collect(rep)
        self._flush_handoffs()
        # Collect from EVERY live replica, stepped or not: ingest_kv can
        # retire a request immediately (handed-off first token == eos),
        # leaving the result on an otherwise-idle engine a stepped-only
        # sweep would never read.
        for name in sorted(self.replicas):
            if self.replicas[name].alive:
                self._collect(self.replicas[name])
        if self.kv_economy is not None:
            # One demotion sweep per fleet iteration, AFTER the engines
            # stepped: admissions have pinned their chain pages (ref>0,
            # not demotable), so the sweep only spills genuinely cold
            # pages — demoting first would race promote() for the very
            # pages this step's admissions were routed toward.
            self.kv_economy.maintain()
        self._g_inflight.set(self.inflight())
        return [rid for rid in self._finished if rid not in before]

    def drain(self, max_steps: int = 10_000) -> dict[int, Any]:
        """Step until the fleet is idle; returns every result collected
        (``max_steps`` bounds the loop — a wedged fleet raises instead
        of hanging the caller)."""
        out: dict[int, Any] = {}
        steps = 0
        while self.has_work():
            self.step()
            out.update(self.pop_finished())
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"fleet wedged: {steps} steps, work remains"
                )
        out.update(self.pop_finished())
        return out

    def pop_finished(self) -> dict[int, Any]:
        """Every request finished since the last pop: token arrays, or
        :class:`RequestFailure` for terminal policy outcomes (deadline,
        poisoned, ...) — per-replica ``"rerouted"`` failures are
        internal (the request completes elsewhere) and never surface
        here; a request NO survivor could take back surfaces as
        ``"failover_failed"``, the fleet's own terminal status."""
        fin, self._finished = self._finished, {}
        return fin

    def _collect(self, rep: EngineReplica):
        for rid, res in rep.pop_finished().items():
            freq = self._requests.get(rid)
            if freq is None:      # finished records are popped at _finish
                continue
            if isinstance(res, RequestFailure):
                if res.status == "rerouted":
                    # Failover visibility ends at the dead replica's
                    # stats; the router already requeued the request.
                    continue
                self._finish(freq, res)
            elif rep.role == "prefill":
                self._begin_handoff(rep, freq, np.asarray(res))
            else:
                self._finish(freq, np.asarray(res))

    def _finish(self, freq: _FleetRequest, result: Any):
        freq.stage = "done"
        # Drop the canonical record NOW: _requests must hold only live
        # work, or inflight() (scanned on every admission and step) and
        # the retained prompt arrays grow with every request the fleet
        # has ever served. A straggler engine retirement for a dropped
        # rid is skipped by _collect's None-check; the rid becomes
        # reusable once the caller pops the result (the engine's own
        # convention).
        self._requests.pop(freq.rid, None)
        self._finished[freq.rid] = result
        now = time.perf_counter()
        ok = not isinstance(result, RequestFailure)
        # Close the trace at the ROUTER — the one place that knows the
        # request's final verdict across every hop it took.
        realized = None
        if self.kv_economy is not None:
            rep = self.replicas.get(freq.replica)
            if rep is not None:
                realized = rep.engine.prefix_realized.pop(freq.rid, None)
                rep.engine.expected_prefix.pop(freq.rid, None)
            self.kv_economy.on_finish(freq.predicted_hit, realized)
            if freq.predicted_hit or realized:
                # The trace records PREDICTED vs REALIZED hit — the
                # router's placement bet and what admission delivered.
                self.traces.instant(
                    freq.rid, "prefix",
                    predicted_tokens=freq.predicted_hit,
                    realized_tokens=realized,
                )
        self.traces.complete(
            freq.rid, status="ok" if ok else result.status, finish_t=now,
        )
        self._completed.append({
            "rid": freq.rid,
            "tenant": freq.tenant,
            "e2e": now - freq.arrival_t,
            "generated": (
                int(len(result) - freq.prompt.size) if ok else 0
            ),
            "ok": ok,
            "status": "ok" if ok else result.status,
            "reroutes": freq.reroutes,
            "prompt_tokens": int(freq.prompt.size),
            "prefix_predicted": freq.predicted_hit,
            "prefix_realized": realized,
        })
        self.recorder.record(
            "fleet.finish", rid=freq.rid, replica=freq.replica, ok=ok,
            reroutes=freq.reroutes,
        )

    # --- disaggregated handoff ----------------------------------------------

    def _row_layout(self, rep: EngineReplica) -> tuple:
        """(dst row shardings, seq dims) for one decode replica —
        memoized: both are fixed by the engine's cache layout."""
        cached = self._row_layouts.get(rep.name)
        if cached is None:
            rep.engine.ensure_cache(rep.params)
            cached = self._row_layouts[rep.name] = (
                rep.engine.kv_row_shardings(),
                rep.engine.kv_row_seq_dims(),
            )
        return cached

    def _begin_handoff(self, rep: EngineReplica, freq, tokens: np.ndarray):
        first = int(tokens[-1])
        eos = rep.engine._eos
        if self.max_new_tokens <= 1 or (eos is not None and first == eos):
            # The first token already ends the request: nothing to hand
            # off, the prefill result IS the final stream.
            self._finish(freq, tokens)
            return
        # Export NOW — the window closes when the prefill engine's next
        # step() admits into the slot.
        rows, length = rep.engine.export_kv(freq.rid)
        freq.stage = "handoff"
        self._handoffs.append(dict(
            freq=freq, rows=rows, length=length, first=first,
            src=rep.name, t_export=time.perf_counter(),
        ))
        self.recorder.record(
            "fleet.handoff_export", rid=freq.rid, src=rep.name,
            length=length,
        )

    def _sweep_handoff_deadlines(self):
        """The round-10 TTL holds in the HANDOFF stage too — for the
        WHOLE queue, not just the head: an expired parked request must
        stop pinning its exported KV-row tree (and its
        ``max_pending_handoffs`` capacity) immediately, not after every
        entry ahead of it found a decode slot."""
        if not any(
            h["freq"].deadline_s is not None for h in self._handoffs
        ):
            return
        now = time.perf_counter()
        keep: deque = deque()
        for h in self._handoffs:
            freq = h["freq"]
            if (
                freq.deadline_s is not None
                and now - freq.arrival_t > freq.deadline_s
            ):
                self.recorder.record(
                    "fleet.deadline", rid=freq.rid, stage="handoff",
                )
                self._finish(freq, RequestFailure(
                    rid=freq.rid, status="deadline",
                    error="deadline exceeded awaiting handoff",
                ))
            else:
                keep.append(h)
        self._handoffs = keep

    def _handoff_dcn_s(self, h, rep) -> float:
        """Priced DCN seconds this handoff would pay if placed on
        ``rep``: 0 without a topology profile or when the prefill
        source shares an ICI domain with the candidate; otherwise the
        exported rows' bytes through the profile's cross-domain link.
        Re-priced per flush on the LIVE profile, so a mid-run
        degradation (the dcn_degrade chaos cell) immediately steers
        placement intra-domain."""
        if self.topology is None:
            return 0.0
        src = self.replicas.get(h["src"])
        if src is None:
            return 0.0
        topo = self.topology

        def domains(r):
            return {
                int(topo.domain_of(d))
                for d in r.engine._mesh.devices.flat
            }

        if domains(src) & domains(rep):
            return 0.0
        nbytes = sum(
            getattr(x, "nbytes", 0) for x in jax.tree.leaves(h["rows"])
        )
        return float(topo.dcn_seconds(nbytes))

    def _flush_handoffs(self):
        self._sweep_handoff_deadlines()
        if self.topology is not None:
            # Chaos seam: a mid-run interconnect event (the dcn_degrade
            # matrix cell mutates the profile — cross-domain β collapse)
            # lands here, so the very NEXT placement re-prices against
            # the degraded link; a swapped profile is a recorded fleet
            # event, same as a failover.
            new = chaos_hook("fleet.topology", self.topology)
            if new is not self.topology:
                self.topology = new
                self.recorder.record(
                    "fleet.topology_change",
                    profile=getattr(new, "name", None),
                )
        while self._handoffs:
            decodes = [
                r for r in self._by_role("decode")
                if r.alive and r.name not in self._swapping
                and r.name not in self._draining
            ]
            if not decodes and any(
                r.alive for r in self._by_role("decode")
            ):
                # Every decode replica is mid-swap (K=1 decode fleets):
                # park the handoffs — they flush when the swap commits,
                # not a failover.
                return
            if not decodes:
                # No decode replica can EVER take these (all DEAD):
                # terminal under the fleet's own status, never a
                # silently parked queue a drain() would spin on.
                while self._handoffs:
                    h = self._handoffs.popleft()
                    freq = h["freq"]
                    self._finish(freq, RequestFailure(
                        rid=freq.rid, status="failover_failed",
                        error="every decode replica is dead",
                    ))
                return
            # Degradation does NOT gate a handoff: level 3 sheds NEW
            # fleet admissions (the prefill pool's own add_request), not
            # work the fleet already accepted and prefilled — and an
            # idle degraded replica could never de-escalate anyway (no
            # traffic means a frozen burn window), so waiting on it
            # would wedge the fleet. Rank ALIVE free-slot replicas by
            # the placement score only.
            h0 = self._handoffs[0]
            ranked = sorted(
                (r for r in decodes if r.engine.free_slots() > 0),
                key=lambda r: (
                    self.policy.score(
                        r, dcn_s=self._handoff_dcn_s(h0, r)),
                    r.name,
                ),
            )
            if not ranked:
                return               # every decode slot busy: try later
            h = self._handoffs.popleft()
            rep, freq = ranked[0], h["freq"]
            now = time.perf_counter()
            dst_shardings, seq_dims = self._row_layout(rep)
            rows, stats = transfer_tree(
                h["rows"], dst_shardings,
                stop=h["length"], seq_dims=seq_dims,
                page_tokens=self.kv_page_tokens,
                plan_cache=self._plan_cache,
                topology=self.topology,
                codec=self._kv_codec,
            )
            rep.engine.ingest_kv(
                rep.params, freq.prompt, h["first"], rows, rid=freq.rid,
                deadline_s=freq.deadline_s, arrival_t=freq.arrival_t,
                admit_t=now, first_token_t=now, tenant=freq.tenant,
            )
            freq.replica = rep.name
            freq.stage = "decode"
            self._c_handoffs.inc()
            self._c_kv_bytes.inc(stats["bytes"])
            self._c_kv_segments.inc(stats["segments"])
            self._c_kv_dcn_bytes.inc(stats.get("dcn_bytes", 0))
            raw = stats.get("raw_bytes", stats["bytes"])
            self._c_kv_raw_bytes.inc(raw)
            if stats["bytes"]:
                self._g_kv_ratio.set(raw / stats["bytes"])
            # The handoff leg is the ROUTER's span: it alone saw both
            # ends — export on the prefill replica through ingest on the
            # decode replica (park time in the queue included: that wait
            # is handoff latency as the request experienced it).
            self.traces.leg(
                freq.rid, "handoff", h["t_export"], time.perf_counter(),
                src=h["src"], dst=rep.name, bytes=stats["bytes"],
                raw_bytes=raw, segments=stats["segments"],
                length=h["length"],
            )
            self.recorder.record(
                "fleet.handoff", rid=freq.rid, src=h["src"],
                dst=rep.name, length=h["length"], bytes=stats["bytes"],
                raw_bytes=raw, segments=stats["segments"],
            )

    # --- zero-downtime rolling weight swap (round 12) -----------------------

    def rolling_swap(
        self, new_params, *, version: int, draft_params=None,
        max_steps: int = 10_000,
    ) -> list[dict]:
        """Update every live replica to ``new_params`` ONE AT A TIME —
        the fleet-wide half of the zero-downtime swap. The replica under
        swap is pulled out of placement (no new admissions, no handoff
        ingests) while its engine stages the resharded tree off the hot
        path and DRAINS (``engine.swap_weights`` drain mode: in-flight
        requests finish on the old version); the rest of the fleet keeps
        serving the whole time, so aggregate capacity never drops to
        zero. Only after the replica's commit does the walk move on.

        A replica whose staging aborts (the ``engine.swap_stage`` chaos
        seam, a recoverable staging failure) STAYS on its old version
        and keeps serving — the rollout continues past it and the
        timeline says so; a fleet is allowed to run mixed versions
        because every response is attributable to exactly one
        (``engine.finished_versions``). Returns the swap timeline —
        per-replica event dicts (``tenancy.write_swap_timeline``
        persists them as the case artifact)."""
        names = [
            n for n in sorted(self.replicas) if self.replicas[n].alive
        ]
        self.recorder.record(
            "fleet.swap_begin", version=version, replicas=names,
        )
        t_begin = time.perf_counter()
        timeline: list[dict] = []
        for name in names:
            rep = self.replicas[name]
            if not rep.alive:      # died earlier in this rollout
                continue
            self._swapping.add(name)
            t0 = time.perf_counter()
            steps = 0
            try:
                staged = rep.engine.swap_weights(
                    new_params, version=version,
                    draft_params=draft_params, mode="drain",
                )
                while staged and rep.engine.weights_version != version:
                    # The staged swap counts as engine work (has_work),
                    # so router.step keeps stepping this replica until
                    # its top-of-step commit fires.
                    self.step()
                    steps += 1
                    if steps > max_steps:
                        raise RuntimeError(
                            f"rolling swap wedged draining replica "
                            f"{name!r} ({steps} steps)"
                        )
            finally:
                self._swapping.discard(name)
            if staged:
                # The replica now OWNS its weights (the engine installs
                # the staged tree); keep the record in sync so failover
                # rebuilds and handoff ingests use the served version.
                rep.params = new_params
                if draft_params is not None:
                    rep.draft_params = draft_params
                self._c_swaps.inc()
            self.recorder.record(
                "fleet.swap_replica", replica=name, version=version,
                committed=staged, drain_steps=steps,
            )
            timeline.append({
                "replica": name,
                "version": version,
                "committed": bool(staged),
                "drain_steps": steps,
                "wall_s": time.perf_counter() - t0,
                "t_offset_s": t0 - t_begin,
            })
        self.recorder.record(
            "fleet.swap_end", version=version,
            committed=sum(1 for t in timeline if t["committed"]),
            replicas=len(timeline),
            wall_s=time.perf_counter() - t_begin,
        )
        return timeline

    # --- elastic scale (round 23) --------------------------------------------

    def adopt_replica(self, rep: EngineReplica) -> None:
        """Scale-out: admit a warmed replica into the fleet — a brand
        new :class:`EngineReplica`, or the REVIVAL of one this router
        retired earlier (spot re-admission after a preemption; the
        drained engine is clean by construction). The caller warms and
        probes the replica first (:class:`~.autoscaler.Autoscaler` runs
        the canary); adoption itself is bookkeeping — wiring, liveness,
        tier, gauges — and is recorded. Elastic adoption is unified-only:
        reshaping a disaggregated fleet means re-planning roles, which
        is a deployment, not a scale action."""
        existing = self.replicas.get(rep.name)
        if existing is not None and existing is not rep:
            raise ValueError(
                f"replica name {rep.name!r} is already taken by a "
                "different replica"
            )
        if existing is rep and rep.alive:
            raise ValueError(f"replica {rep.name!r} is already serving")
        if self.disaggregated or rep.role != "unified":
            raise ValueError(
                "elastic adoption supports unified fleets only "
                f"(fleet disaggregated={self.disaggregated}, "
                f"replica role={rep.role!r})"
            )
        if rep.engine._max_new != self.max_new_tokens:
            raise ValueError(
                f"adopted replica {rep.name!r} disagrees on "
                f"max_new_tokens ({rep.engine._max_new} != "
                f"{self.max_new_tokens}) — failover requeue could not "
                "recompute bit-identically"
            )
        if rep.engine._eos != self.eos_id:
            raise ValueError(
                f"adopted replica {rep.name!r} disagrees on eos_id "
                f"({rep.engine._eos} != {self.eos_id})"
            )
        rep.alive = True
        rep.engine.trace_sink = self.traces
        rep.engine.trace_replica = rep.name
        if existing is None:
            # A fresh engine's stats/ledger window starts NOW, aligned
            # with the fleet's measurement interval — warmup and canary
            # work must not book into the serving economics. A revived
            # replica keeps its window: its earlier serving already
            # belongs to this interval's books.
            rep.engine.reset_stats()
        self.replicas[rep.name] = rep
        self._draining.discard(rep.name)
        self._preempting.pop(rep.name, None)
        if self.kv_economy is not None:
            self.kv_economy.on_replica_adopt(rep)
        self._g_alive.set(
            sum(1 for r in self.replicas.values() if r.alive)
        )
        self._c_scale_outs.inc()
        self.recorder.record(
            "fleet.scale_out", replica=rep.name,
            revived=existing is rep, preemptible=rep.preemptible,
        )

    def retire_replica(
        self, name: str, *, reason: str = "scale_in",
        force: bool = False,
    ) -> dict:
        """Graceful scale-in: DRAIN-AND-MIGRATE, never a silent drop.

        In order: the retiring replica's warm KV migrates to a survivor
        (retained HBM pages write back through the counted
        ``spill_page`` plans, then its host tier moves whole —
        :meth:`~.kv_economy.KvEconomy.migrate_tier`); its queued and
        in-flight requests drain with visible ``"rerouted"`` terminals
        and requeue on survivors, where they RECOMPUTE BIT-IDENTICALLY
        (the same guarantee failover rides — sampling is keyed by
        (rid, position), never by replica); results that finished
        before the drain surface normally. The replica stays in
        ``replicas`` with ``alive=False`` — its ledger window and
        completed-request history belong to the fleet's books — and
        :meth:`adopt_replica` can revive it later.

        Retiring the LAST live replica of a role would strand work, so
        it raises unless ``force=True`` (the preemption path forces:
        the eviction takes the machine regardless)."""
        rep = self.replicas[name]
        if not rep.alive:
            raise ValueError(f"replica {name!r} is not alive")
        peers = [
            r for r in self.replicas.values()
            if r.alive and r.name != name and r.role == rep.role
        ]
        if not peers and not force:
            raise ValueError(
                f"cannot retire {name!r}: it is the last live "
                f"{rep.role!r} replica (force=True drops capacity to "
                "zero anyway)"
            )
        t0 = time.perf_counter()
        self._draining.discard(name)
        self._preempting.pop(name, None)
        migrated_pages = migrated_bytes = 0
        if self.kv_economy is not None:
            migrated_pages, migrated_bytes = (
                self.kv_economy.migrate_tier(rep)
            )
        records = rep.engine.drain_requests(
            status="rerouted", error=f"scale-in: {reason}"
        )
        # Pre-drain finished results surface before the liveness flip —
        # including finished PREFILLS, whose exported rows hand off
        # normally: a graceful exit keeps its HBM until the drain ends,
        # so nothing restarts that does not have to.
        self._collect(rep)
        rep.alive = False
        self._g_alive.set(
            sum(1 for r in self.replicas.values() if r.alive)
        )
        rerouted = [r["rid"] for r in records]
        self._requeue_records(
            rep, rerouted, error=f"scale-in: {reason}"
        )
        drain_ms = (time.perf_counter() - t0) * 1e3
        self.drain_ms.append(drain_ms)
        self._c_scale_ins.inc()
        if reason == "preempted":
            self._c_preempts.inc()
        info = dict(
            replica=name, reason=reason, rerouted=rerouted,
            migrated_pages=migrated_pages,
            migrated_bytes=migrated_bytes, drain_ms=drain_ms,
        )
        self.recorder.record("fleet.scale_in", **info)
        return info

    def preempt_replica(
        self, name: str, *, grace_steps: int | None = None,
        error: str = "preemption notice",
    ) -> None:
        """Deliver a SIGTERM-style eviction notice: the replica leaves
        the placement pool NOW (``_draining``) but keeps stepping for
        ``grace_steps`` fleet iterations so near-done work finishes in
        place; at expiry — or as soon as it runs dry — it retires
        through the graceful drain-and-migrate path. ``grace_steps<=0``
        retires immediately (the no-grace eviction)."""
        rep = self.replicas[name]
        if not rep.alive:
            raise ValueError(f"replica {name!r} is not alive")
        if name in self._preempting:
            return
        grace = (
            self.preempt_grace_steps if grace_steps is None
            else int(grace_steps)
        )
        self.recorder.record(
            "fleet.preempt_notice", replica=name, grace_steps=grace,
            error=str(error),
        )
        if grace <= 0:
            self.retire_replica(name, reason="preempted", force=True)
            return
        self._draining.add(name)
        self._preempting[name] = grace

    def _tick_preemptions(self) -> None:
        """Advance every grace window one fleet step; a window that
        expires (or whose replica ran dry early) ends in the graceful
        retire. ``force=True`` because the eviction takes the machine
        whether or not a peer exists — the requeue path then
        terminalizes homeless work honestly (``failover_failed``)."""
        for name in sorted(self._preempting):
            rep = self.replicas[name]
            if not rep.alive:
                self._preempting.pop(name)
                self._draining.discard(name)
                continue
            self._preempting[name] -= 1
            if self._preempting[name] <= 0 or not rep.engine.has_work():
                self._preempting.pop(name)
                self.retire_replica(
                    name, reason="preempted", force=True,
                )

    def _requeue_records(
        self, rep: EngineReplica, rids: Sequence[int], *, error: str,
    ) -> None:
        """Requeue drained work on survivors — same rid + original
        arrival clock, so sampling streams, deadlines, and queue-wait
        telemetry are those of the ORIGINAL request and survivors
        recompute it bit-identically. Shared by crash failover and
        graceful scale-in: one requeue path, one guarantee."""
        for rid in rids:
            freq = self._requests.get(rid)
            if freq is None:      # already finished and popped
                continue
            freq.reroutes += 1
            self._c_reroutes.inc()
            self.traces.instant(
                freq.rid, "reroute", replica=rep.name,
                error=error, reroutes=freq.reroutes,
            )
            try:
                self._route(freq, requeue=True)
            except AdmissionError as e:
                # No survivor can take it: terminal, never silent — and
                # under its OWN status: "rerouted" is the internal
                # requeue marker pop_finished callers may ignore, so a
                # request the fleet actually LOST must not wear it.
                self._finish(freq, RequestFailure(
                    rid=freq.rid, status="failover_failed", error=str(e),
                ))

    # --- failover ------------------------------------------------------------

    def kill_replica(self, name: str, error: str = "replica killed"):
        """Declare ``name`` dead and fail its work over to survivors —
        the operator/chaos entry to the same path a ``fleet.step``
        injection takes."""
        self._fail_over(self.replicas[name], RuntimeError(error))

    def _fail_over(self, rep: EngineReplica, error: BaseException):
        if not rep.alive:
            return
        rep.alive = False
        # A crash mid-grace-window outruns the graceful countdown.
        self._draining.discard(rep.name)
        self._preempting.pop(rep.name, None)
        self._g_alive.set(
            sum(1 for r in self.replicas.values() if r.alive)
        )
        if self.kv_economy is not None:
            # Its host tier dies with the process: peers recompute from
            # the prompt rather than ever serving orphaned KV.
            self.kv_economy.on_replica_death(rep.name)
        # 1. Drain the dead replica: every queued/in-flight request gets
        #    a visible "rerouted" terminal there and a requeueable record
        #    here. Results that finished BEFORE the death still surface.
        records = rep.engine.drain_requests(
            status="rerouted", error=str(error)
        )
        for rid, res in rep.pop_finished().items():
            freq = self._requests.get(rid)
            if freq is None:      # already finished and popped
                continue
            if isinstance(res, RequestFailure):
                if res.status != "rerouted":
                    # Genuinely terminal on the dead replica (deadline,
                    # poisoned, ...) — the verdict survives it.
                    self._finish(freq, res)
            elif rep.role == "prefill":
                # An uncollected finished PREFILL is not a final stream
                # — it is [prompt, first_token] whose exported KV died
                # with the replica. Restart from the prompt like the
                # drained work (recompute-exact), never surface the
                # truncated array as the caller's result.
                records.append(dict(rid=rid))
            else:
                self._finish(freq, res)
        # 2. Pending handoffs sourced from the dead replica lose their
        #    exported rows (a real death takes its HBM along) — those
        #    requests restart from the prompt like the drained ones.
        dead_handoffs = [
            h for h in self._handoffs if h["src"] == rep.name
        ]
        self._handoffs = deque(
            h for h in self._handoffs if h["src"] != rep.name
        )
        self._c_failovers.inc()
        self.recorder.record(
            "fleet.failover", replica=rep.name, error=str(error),
            rerouted=[r["rid"] for r in records]
            + [h["freq"].rid for h in dead_handoffs],
        )
        # 3. Requeue on survivors (the shared scale-in/failover path:
        #    same rid + original arrival clock → bit-identical
        #    recompute, the drain_requests guarantee).
        self._requeue_records(
            rep,
            [r["rid"] for r in records]
            + [h["freq"].rid for h in dead_handoffs],
            error=str(error),
        )

    # --- telemetry ------------------------------------------------------------

    def latency_stats(self) -> dict | None:
        """Router-side end-to-end percentiles over the current window
        (arrival at the ROUTER → final result, across handoffs and
        failovers) plus fleet totals — the bench's aggregate line."""
        comp = self._completed
        if not comp:
            return None
        e2e = np.asarray([c["e2e"] for c in comp], np.float64)
        out = {
            "requests": len(comp),
            "ok": sum(1 for c in comp if c["ok"]),
            "generated": int(sum(c["generated"] for c in comp)),
            "reroutes": int(sum(c["reroutes"] for c in comp)),
            "e2e_p50": float(np.percentile(e2e, 50)),
            "e2e_p99": float(np.percentile(e2e, 99)),
        }
        # Fleet TTFT: every replica's engine stamps per-request ttft in
        # ITS window (reset_stats aligns the windows), so the fleet
        # percentile is over the union.
        ttfts = [
            c["ttft"]
            for rep in self.replicas.values()
            for c in rep.engine._completed
            if c.get("ttft") is not None
        ]
        if ttfts:
            t = np.asarray(ttfts, np.float64)
            out["ttft_p50"] = float(np.percentile(t, 50))
            out["ttft_p99"] = float(np.percentile(t, 99))
        if self.kv_economy is not None:
            # prefix_hit_rate: realized cache-hit tokens / prompt tokens
            # over finished requests with a verdict (what fraction of
            # prefill work the economy saved); tier_miss_rate: requests
            # whose realization fell short of the routing prediction
            # (graceful re-prefill, counted — never a wrong token).
            scored = [
                c for c in comp if c["prefix_realized"] is not None
            ]
            if scored:
                realized = sum(c["prefix_realized"] for c in scored)
                prompts = sum(c["prompt_tokens"] for c in scored)
                out["prefix_hit_rate"] = (
                    realized / prompts if prompts > 0 else 0.0
                )
                out["tier_miss_rate"] = sum(
                    1 for c in scored
                    if c["prefix_realized"] < c["prefix_predicted"]
                ) / len(scored)
        return out

    def fleet_snapshot(self) -> dict:
        """Per-replica registries merged into ONE fleet view: the
        unlabeled sums (bit-compatible with the round-7 merge) plus
        ``{replica="..."}``-labeled per-replica series, and the router's
        own fleet_* counters."""
        from learning_jax_sharding_tpu.parallel.multihost import (
            merge_registry_snapshots,
        )

        labels = sorted(self.replicas)
        snaps = [
            self.replicas[n].engine.registry.snapshot() for n in labels
        ]
        return {
            "replicas": labels,
            "router": self.registry.snapshot(),
            "merged": merge_registry_snapshots(snaps, labels=labels),
        }

    def prometheus_text(self) -> str:
        """One Prometheus exposition for the whole fleet: router
        counters plus every engine metric, summed AND per-replica
        labeled."""
        from learning_jax_sharding_tpu.telemetry.registry import (
            snapshot_prometheus_text,
        )

        snap = self.fleet_snapshot()
        return snapshot_prometheus_text(
            {**snap["router"], **snap["merged"]}
        )

    def goodput_report(self) -> dict:
        """Fleet-wide goodput: every replica's ledger window (since
        ``reset_stats``) plus the fleet roll-up.

        Fleet buckets are SUMMED replica-seconds (2 replicas idling one
        wall-second cost two replica-seconds of capacity), so
        ``host_share`` = 1 − Σdevice/Σbusy is capacity-weighted, and
        ``reconcile_ok`` is the AND of every replica's own Σ buckets ==
        wall invariant — one flag tier-1 can gate the whole fleet on."""
        per_replica: dict[str, dict] = {}
        fleet: dict[str, float] = {}
        ok = True
        for name in sorted(self.replicas):
            led = self.replicas[name].engine.ledger
            rep_report = led.window_report()
            rec = led.reconcile()
            ok = ok and rec["ok"]
            per_replica[name] = {
                "report": rep_report, "reconcile": rec,
            }
            for b, s in rep_report["buckets"].items():
                fleet[b] = fleet.get(b, 0.0) + s
        device = fleet.get("device", 0.0)
        busy = sum(
            r["report"]["busy_s"] for r in per_replica.values()
        )
        gaps = {b: s for b, s in fleet.items() if b != "device"}
        top = max(gaps, key=gaps.get) if gaps else None
        wall = sum(
            r["report"]["wall_s"] for r in per_replica.values()
        )
        return {
            "replicas": per_replica,
            "fleet_buckets": fleet,
            "fleet_wall_s": wall,
            "fleet_busy_s": busy,
            "fleet_device_s": device,
            "host_share": 1.0 - device / busy if busy > 0 else None,
            "top_contributor": top,
            "top_contributor_s": gaps.get(top, 0.0) if top else 0.0,
            "telemetry_share": (
                fleet.get("telemetry", 0.0) / wall if wall > 0 else 0.0
            ),
            "reconcile_ok": ok,
        }

    def merged_chrome_trace(self) -> dict:
        """One Perfetto timeline for the whole fleet: each replica
        engine's dispatch-level ``Tracer`` ring becomes a named process
        track, and the :class:`TraceStore`'s request journeys (queue /
        prefill / handoff / decode legs, reroute + swap-pin markers)
        land on additional tracks alongside — every engine stamp and
        every trace leg came off the same ``perf_counter`` clock, so
        rebasing onto the earliest tracer epoch lines them all up."""
        tracers = {
            name: self.replicas[name].engine.tracer
            for name in sorted(self.replicas)
        }
        base = min(
            (getattr(tr, "_t0", 0.0) for tr in tracers.values()),
            default=self.traces._t0,
        )
        # TraceStore events are µs since ITS epoch; shift them onto the
        # merged (earliest-tracer) epoch and move their pids past the
        # tracer pids so the two track families never collide.
        off_us = (self.traces._t0 - base) * 1e6
        shift = len(tracers)
        extra = []
        for ev in self.traces.chrome_trace()["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = ev["pid"] + shift
            if ev.get("ph") == "M":
                ev = {**ev, "args": {
                    "name": f"requests: {ev['args']['name']}",
                }}
            if "ts" in ev:
                ev["ts"] = ev["ts"] + off_us
            extra.append(ev)
        return merge_tracers(tracers, extra_events=extra)

    def dump_merged_chrome_trace(self, path) -> None:
        import json

        with open(path, "w") as f:
            json.dump(self.merged_chrome_trace(), f)
