"""Per-tenant cost attribution: the economics half of the workload
observatory (round 20).

The fleet already measures three things exhaustively — per-request
critical paths (:class:`~.tracecontext.TraceStore`: queue / prefill /
handoff / decode legs, wasted reroute legs), per-replica wall-clock
buckets (:class:`~.ledger.GoodputLedger`: device / compile / sched /
kv_handoff / swap / recovery / telemetry / idle, reconciling to the
wall), and byte counters (handoff transfer plans, KV-economy tiers).
What it could not answer is "what did tenant X's traffic COST". This
module is the JOIN: :func:`fleet_economics` apportions every replica's
ledger bucket seconds across tenants using each tenant's own trace-leg
seconds on that replica as weights, prices the result with the
:mod:`~..analysis.costmodel` device tables, and emits per-tenant
device-seconds / tokens / bytes-moved / cost-per-token plus SLO burn
rates.

**The conservation invariant (tier-1-gated):** apportionment
distributes each replica's measured bucket total — it never invents
seconds — so Σ over tenants of attributed ``device`` seconds equals the
fleet ledger's summed ``device`` bucket to within float rounding, and
every admitted request lands in exactly one tenant's roll-up (ok,
failed, rerouted, shed — no request is double-billed, none vanishes).

**Amortization policy** (:data:`ATTRIBUTION_POLICY`, the documented
choice the README tabulates): bucket seconds with a per-tenant signal
apportion by that signal (``device`` and most buckets by non-queue leg
seconds, ``kv_handoff`` by handoff bytes landed on the replica,
``recovery`` by wasted-leg seconds); overhead buckets with no tenant
signal on an idle replica (compile warm-up, idle, telemetry on a
replica no tenant touched) book to the :data:`OVERHEAD_TENANT`
pseudo-row rather than being smeared — visible overhead beats
invisible subsidy.

The ``economics.json`` artifact splits into a ``deterministic`` subtree
(admission order, per-tenant request/token/byte tallies, pricing
policy — byte-identical across replays of the same trace) and a
``measured`` subtree (seconds, costs, burn — honest wall-clock, never
identical across runs); the replay-determinism test compares the
former and the conservation gate checks the latter.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

#: Pseudo-tenant for fleet overhead no tenant's traffic can own —
#: bucket seconds on replicas whose window saw no tenant legs at all
#: (compile warm-up on a spare, pure idle). Kept visible as its own
#: row: amortizing it into tenant bills silently would make every
#: cost-per-token depend on which OTHER tenants happened to be quiet.
OVERHEAD_TENANT = "(fleet-overhead)"

#: Roll-up label for requests admitted without a tenant label.
UNTAGGED_TENANT = "(untagged)"

#: How each ledger bucket's seconds are split across tenants — the
#: documented amortization policy (README "Workload observatory").
ATTRIBUTION_POLICY = {
    "device": "per-tenant non-queue trace-leg seconds on the replica",
    "kv_handoff": "per-tenant handoff bytes landed on the replica "
                  "(falls back to leg seconds when no handoffs)",
    "recovery": "per-tenant wasted (thrown-away) leg seconds "
                "(falls back to leg seconds when nothing was wasted)",
    "compile": "per-tenant leg seconds (warm-up amortizes over the "
               "window's actual traffic)",
    "idle": "per-tenant leg seconds (idle capacity is billed to the "
            "traffic that reserved the replica)",
    "telemetry": "per-tenant leg seconds",
    "*": "per-tenant leg seconds; replicas with zero tenant legs book "
         f"to {OVERHEAD_TENANT!r}",
}


@dataclasses.dataclass(frozen=True)
class CostRates:
    """Pricing knobs: a flat device-hour rate plus the costmodel device
    profile whose ``link_bw`` prices bytes moved as wire-seconds (a
    byte across the interconnect occupies the link like a second
    occupies the chip)."""

    usd_per_device_hour: float = 1.20
    profile: str = "TPU v5 lite"


def _tenant_of(rec: dict) -> str:
    return rec.get("tenant") or UNTAGGED_TENANT


def fleet_economics(
    router,
    *,
    replay: dict | None = None,
    rates: CostRates | None = None,
    slo: Any | None = None,
    eps: float | None = None,
    register: bool = True,
) -> dict:
    """JOIN traces × ledger × counters into the per-tenant bill.

    ``router`` is a drained :class:`~..fleet.router.FleetRouter` whose
    current stats window covers the traffic to attribute; ``replay`` is
    the :func:`~..fleet.loadgen.replay_trace` report (supplies the
    admission order and fleet-level sheds); ``slo`` a tenant-fed
    :class:`~.slo.SLOMonitor` for burn rates. ``register=True`` mirrors
    each tenant's headline numbers into the router registry as
    ``economics_*{tenant="..."}`` gauges (label values escaped), so the
    bill scrapes like every other fleet metric.

    Returns the economics document; ``measured.conservation.ok`` is the
    tier-1 gate (Σ tenant device-seconds == fleet ledger device bucket
    within ``eps``, default ``1e-6 · max(1, device_total)``).
    """
    from learning_jax_sharding_tpu.analysis.costmodel import table_profile

    rates = rates or CostRates()
    profile = table_profile(rates.profile)
    replicas = sorted(router.replicas)

    # --- gather per-replica per-tenant weights from the trace legs ----
    # Spans are clipped to each replica ledger's current window: the
    # TraceStore retains warm-up traffic's traces, but the buckets being
    # apportioned start at reset_stats() — pre-window legs must carry
    # zero weight or warm-up prompts would skew the bill.
    win_t0 = {
        n: router.replicas[n].engine.ledger.window_start
        for n in replicas
    }
    leg_s = {n: {} for n in replicas}      # non-queue leg seconds
    wasted_s = {n: {} for n in replicas}   # thrown-away leg seconds
    handoff_b = {n: {} for n in replicas}  # handoff bytes landed (dst)
    tenants: set[str] = set()
    for rid in router.traces.rids():
        rec = router.traces.record(rid)
        ten = _tenant_of(rec)
        tenants.add(ten)
        for s in rec["spans"]:
            if s["stage"] == "handoff":
                # The router's span: both ends of the transfer. Bytes
                # bill the DESTINATION replica's kv_handoff bucket —
                # ingest is where the ledger books the time.
                dst = s["attrs"].get("dst")
                if dst in handoff_b and s["t1"] > win_t0[dst]:
                    handoff_b[dst][ten] = (
                        handoff_b[dst].get(ten, 0.0)
                        + float(s["attrs"].get("bytes", 0))
                    )
                continue
            if s["stage"] == "queue":
                continue       # waiting costs no device-seconds
            rep = s["replica"]
            if rep not in leg_s:
                continue       # replica-less spans own no ledger
            dur = s["t1"] - max(s["t0"], win_t0[rep])
            if dur <= 0.0:
                continue       # warm-up leg, outside the window
            leg_s[rep][ten] = leg_s[rep].get(ten, 0.0) + dur
            if s["attrs"].get("wasted"):
                wasted_s[rep][ten] = wasted_s[rep].get(ten, 0.0) + dur

    # --- apportion each replica's ledger buckets ----------------------
    ledger_buckets = {
        n: dict(router.replicas[n].engine.ledger.window_buckets())
        for n in replicas
    }
    tenant_buckets: dict[str, dict[str, float]] = {}

    def _book(ten, bucket, secs):
        tb = tenant_buckets.setdefault(ten, {})
        tb[bucket] = tb.get(bucket, 0.0) + secs

    for name in replicas:
        for bucket, secs in ledger_buckets[name].items():
            if secs <= 0.0:
                continue
            if bucket == "kv_handoff" and handoff_b[name]:
                weights = handoff_b[name]
            elif bucket == "recovery" and wasted_s[name]:
                weights = wasted_s[name]
            else:
                weights = leg_s[name]
            total = sum(weights.values())
            if total <= 0.0:
                _book(OVERHEAD_TENANT, bucket, secs)
                continue
            for ten, w in weights.items():
                _book(ten, bucket, secs * (w / total))

    # --- conservation: nothing invented, nothing dropped --------------
    device_total = sum(
        b.get("device", 0.0) for b in ledger_buckets.values()
    )
    attributed = sum(
        tb.get("device", 0.0) for tb in tenant_buckets.values()
    )
    if eps is None:
        eps = 1e-6 * max(1.0, device_total)
    residual = abs(attributed - device_total)

    # --- per-tenant request/token roll-up (deterministic) -------------
    roll: dict[str, dict] = {}

    def _roll(ten) -> dict:
        return roll.setdefault(ten, {
            "requests": 0, "ok": 0, "failed": {}, "shed": 0,
            "reroutes": 0, "prompt_tokens": 0, "generated_tokens": 0,
            "handoff_bytes": 0.0,
        })

    for c in router._completed:
        r = _roll(c.get("tenant") or UNTAGGED_TENANT)
        r["requests"] += 1
        if c["ok"]:
            r["ok"] += 1
        else:
            st = c.get("status") or "failed"
            r["failed"][st] = r["failed"].get(st, 0) + 1
        r["reroutes"] += int(c.get("reroutes", 0))
        r["prompt_tokens"] += int(c.get("prompt_tokens", 0))
        r["generated_tokens"] += int(c.get("generated", 0))
    for shed in (replay or {}).get("shed", ()):
        _roll(shed.get("tenant") or UNTAGGED_TENANT)["shed"] += 1
    for name in replicas:
        for ten, b in handoff_b[name].items():
            _roll(ten)["handoff_bytes"] += b

    # --- price it -----------------------------------------------------
    rate_per_s = rates.usd_per_device_hour / 3600.0
    burn = slo.tenant_burn_rates() if slo is not None else {}
    measured_tenants: dict[str, dict] = {}
    for ten in sorted(set(tenant_buckets) | set(roll)):
        tb = tenant_buckets.get(ten, {})
        secs = sum(tb.values())
        bytes_moved = roll.get(ten, {}).get("handoff_bytes", 0.0)
        wire_s = bytes_moved / profile.link_bw
        cost = (secs + wire_s) * rate_per_s
        gen = roll.get(ten, {}).get("generated_tokens", 0)
        tburn = burn.get(ten, {})
        measured_tenants[ten] = {
            "bucket_seconds": {k: tb[k] for k in sorted(tb)},
            "device_seconds": tb.get("device", 0.0),
            "total_seconds": secs,
            "wasted_seconds": sum(
                wasted_s[n].get(ten, 0.0) for n in replicas
            ),
            "bytes_moved": bytes_moved,
            "wire_seconds": wire_s,
            "cost_usd": cost,
            "cost_per_token_usd": cost / gen if gen > 0 else None,
            "burn_rates": {k: tburn[k] for k in sorted(tburn)},
            "worst_burn_rate": max(tburn.values(), default=0.0),
        }

    worst_tenant, worst_burn = None, 0.0
    for ten, m in measured_tenants.items():
        if m["worst_burn_rate"] >= worst_burn and ten != OVERHEAD_TENANT:
            worst_tenant, worst_burn = ten, m["worst_burn_rate"]

    goodput = router.goodput_report()
    wall = goodput["fleet_wall_s"]
    econ = {
        "schema": "ljst.economics.v1",
        "policy": dict(ATTRIBUTION_POLICY),
        "pricing": {
            "usd_per_device_hour": rates.usd_per_device_hour,
            "profile": rates.profile,
            "link_bw": profile.link_bw,
        },
        "deterministic": {
            "admission_order": list(
                (replay or {}).get("admission_order", ())
            ),
            "offered": (replay or {}).get("offered"),
            "tenants": {t: {
                k: roll[t][k] for k in sorted(roll[t])
            } for t in sorted(roll)},
        },
        "measured": {
            "fleet": {
                "wall_s": wall,
                "device_s": goodput["fleet_device_s"],
                "goodput_ratio": (
                    goodput["fleet_device_s"] / wall if wall > 0 else 0.0
                ),
                "host_share": goodput["host_share"],
                "reconcile_ok": goodput["reconcile_ok"],
                "replay_wall_s": (replay or {}).get("wall_s"),
            },
            "tenants": measured_tenants,
            "worst_tenant": worst_tenant,
            "worst_tenant_burn_rate": worst_burn,
            "conservation": {
                "ok": bool(residual <= eps),
                "device_total_s": device_total,
                "attributed_s": attributed,
                "residual_s": residual,
                "eps": eps,
            },
        },
    }

    if register:
        from learning_jax_sharding_tpu.telemetry.registry import (
            labeled_name,
        )

        reg = router.registry
        for ten, m in measured_tenants.items():
            reg.gauge(
                labeled_name("economics_device_seconds", tenant=ten),
                "attributed device-seconds this window",
            ).set(m["device_seconds"])
            reg.gauge(
                labeled_name("economics_cost_usd", tenant=ten),
                "attributed window cost",
            ).set(m["cost_usd"])
            if m["cost_per_token_usd"] is not None:
                reg.gauge(
                    labeled_name(
                        "economics_cost_per_token_usd", tenant=ten
                    ),
                    "attributed cost per generated token",
                ).set(m["cost_per_token_usd"])
    return econ


def deterministic_view(econ: dict) -> dict:
    """The replay-determinism comparand: everything except the
    ``measured`` subtree (wall-clock seconds are honest, therefore
    never byte-identical across runs)."""
    return {k: v for k, v in econ.items() if k != "measured"}


def write_economics(path, econ: dict) -> None:
    with open(path, "w") as f:
        json.dump(econ, f, indent=2, sort_keys=True)
