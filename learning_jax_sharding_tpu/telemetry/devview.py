"""Per-device auditing: HBM watermarks, shard imbalance, per-axis volume.

Three views the aggregate metrics of PR 1 cannot give:

* :func:`device_memory_stats` / :func:`memory_report` — live per-device HBM
  watermarks (``device.memory_stats()``, GUARDED: emulated CPU devices
  return ``None`` and TPU runtimes omit keys — both degrade to empty stats,
  never a crash) compared against the static ``utils.memory.MemoryPlan``
  estimate: the predicted-vs-actual check that catches a planner drift or a
  leak before the OOM does.
* :func:`shard_imbalance` — bytes per device for a pytree of ``jax.Array``s
  read off each leaf's actual sharding (``devices_indices_map`` — exact even
  for uneven shards and single-device strays), with skew flagging: the
  "one replicated/misplaced tensor is eating a chip" bug as a report instead
  of an OOM three steps later.
* :func:`axis_collective_volume` — attribute each compiled collective's byte
  volume to the MESH AXIS whose device groups it runs over, from
  ``parallel.hlo.collective_instructions``. Bytes-moved-per-axis-per-step is
  the quantity the model-parallel communication literature optimizes
  (arXiv 2211.05322; EQuARX, arXiv 2506.17615) — now readable off every
  compiled program.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Mapping, Sequence

import jax
import numpy as np

from learning_jax_sharding_tpu.parallel.hlo import collective_instructions

#: Stat keys surfaced (when the backend reports them); everything else the
#: backend returns rides along untouched.
_CORE_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_stats(
    devices: Sequence[jax.Device] | None = None,
) -> list[dict]:
    """Per-device memory stats, guarded for backends without them.

    Returns one record per device: ``{"id", "kind", "platform", "stats"}``
    where ``stats`` is the backend's dict with JSON-able values — EMPTY when
    the backend has no ``memory_stats`` attribute, returns ``None`` (the
    emulated CPU devices here), or raises. Key presence is the backend's
    choice; consumers must ``.get``.
    """
    out = []
    for d in devices if devices is not None else jax.devices():
        raw: Mapping | None = None
        probe = getattr(d, "memory_stats", None)
        if probe is not None:
            try:
                raw = probe()
            except Exception:
                raw = None
        stats = {}
        if raw:
            for k, v in raw.items():
                if isinstance(v, (int, float, bool, str)) or v is None:
                    stats[k] = v
        out.append(
            {
                "id": int(d.id),
                "kind": str(d.device_kind),
                "platform": str(d.platform),
                "stats": stats,
            }
        )
    return out


def memory_report(
    plan: Any | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    hbm_bytes: float | None = None,
) -> dict:
    """Predicted-vs-actual HBM report.

    ``plan`` is a ``utils.memory.MemoryPlan`` (or None for live-only);
    ``hbm_bytes`` overrides the capacity used for headroom (default: the
    backend's ``bytes_limit`` when reported, else
    ``utils.memory.HBM_BYTES[device_kind]`` when known). Degrades cleanly:
    with no live stats (emulated CPU) the report is PLAN-ONLY
    (``actual_available=False``) — the devview contract tier-1 pins.
    """
    from learning_jax_sharding_tpu.utils.memory import device_hbm_bytes

    devs = device_memory_stats(devices)
    live = [
        d for d in devs
        if any(d["stats"].get(k) for k in ("peak_bytes_in_use", "bytes_in_use"))
    ]
    report: dict = {
        "devices": devs,
        "actual_available": bool(live),
        "predicted": None,
    }
    if plan is not None:
        report["predicted"] = {
            "params": plan.params,
            "grads": plan.grads,
            "optimizer_state": plan.optimizer_state,
            "saved_activations": plan.saved_activations,
            "loss_head": plan.loss_head,
            "total": plan.total,
        }
    if hbm_bytes is None:
        limits = [d["stats"].get("bytes_limit") for d in devs]
        limits = [x for x in limits if x]
        hbm_bytes = max(limits) if limits else device_hbm_bytes(
            (devices or jax.devices())[0]
        )
    report["hbm_bytes"] = hbm_bytes
    if plan is not None and hbm_bytes:
        report["predicted_fits"] = plan.fits(hbm_bytes)
    if live:
        peak = max(
            d["stats"].get("peak_bytes_in_use")
            or d["stats"].get("bytes_in_use") or 0
            for d in live
        )
        report["actual_peak_bytes"] = peak
        if plan is not None and peak:
            report["predicted_over_actual"] = plan.total / peak
    return report


def _leaf_device_bytes(leaf: jax.Array) -> dict[int, int] | None:
    """Exact bytes each device holds of ``leaf``, from its sharding's
    index map (handles uneven shards, replication, single-device strays)."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None:
        return None
    shape, itemsize = leaf.shape, leaf.dtype.itemsize
    try:
        imap = sharding.devices_indices_map(shape)
    except Exception:
        return None
    out: dict[int, int] = {}
    for dev, idx in imap.items():
        n = 1
        for sl, dim in zip(idx or (), shape):
            start, stop, _ = sl.indices(dim)
            n *= max(0, stop - start)
        out[int(dev.id)] = n * itemsize
    return out


def shard_imbalance(
    tree: Any,
    *,
    threshold: float = 1.25,
    devices: Sequence[jax.Device] | None = None,
) -> dict:
    """Audit per-device byte footprint of a pytree of ``jax.Array``s.

    Returns per-device totals, the global skew (max/mean over the device
    set — 1.0 is perfectly balanced, and a device holding NOTHING drags the
    mean down, so a forgotten shard shows up as skew too), and the flagged
    leaves whose own skew exceeds ``threshold`` (path + per-device min/max).
    ``devices`` defaults to all global devices, so arrays committed to a
    subset are charged against the full mesh.
    """
    devs = devices if devices is not None else jax.devices()
    per_device: dict[int, int] = {int(d.id): 0 for d in devs}
    flagged: list[dict] = []
    total = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        db = _leaf_device_bytes(leaf)
        if db is None:
            continue
        for did, b in db.items():
            per_device[did] = per_device.get(did, 0) + b
        vals = [db.get(d, 0) for d in per_device]
        mean = sum(vals) / len(vals) if vals else 0
        mx = max(vals) if vals else 0
        total += sum(db.values())
        if mean and mx / mean > threshold:
            flagged.append(
                {
                    "path": jax.tree_util.keystr(path),
                    "max_bytes": mx,
                    "min_bytes": min(vals),
                    "skew": mx / mean,
                }
            )
    vals = list(per_device.values())
    mean = sum(vals) / len(vals) if vals else 0.0
    skew = (max(vals) / mean) if mean else 1.0
    return {
        "per_device_bytes": per_device,
        "total_bytes": total,
        "max_bytes": max(vals) if vals else 0,
        "min_bytes": min(vals) if vals else 0,
        "mean_bytes": mean,
        "skew": skew,
        "threshold": threshold,
        "imbalanced": skew > threshold,
        "flagged": flagged,
    }


def _axis_group_sets(mesh: Any) -> dict[str, frozenset]:
    """For every non-empty subset of mesh axes: the partition-id groups a
    collective over exactly those axes would use. Ids are POSITIONS in the
    flattened mesh device order (SPMD partition ids), not device ids."""
    names = list(mesh.axis_names)
    shape = [mesh.shape[n] for n in names]
    grid = np.arange(math.prod(shape)).reshape(shape)
    out: dict[str, frozenset] = {}
    for r in range(1, len(names) + 1):
        for combo in itertools.combinations(range(len(names)), r):
            moved = np.moveaxis(grid, combo, range(-len(combo), 0))
            groups = moved.reshape(-1, math.prod(shape[i] for i in combo))
            if groups.shape[1] <= 1:
                continue   # size-1 axes form no communication groups
            label = "+".join(names[i] for i in combo)
            out[label] = frozenset(
                frozenset(int(x) for x in row) for row in groups
            )
    return out


def axis_label_of_groups(groups: Any, by_groups: dict) -> str | None:
    """THE replica-groups → mesh-axis-subset matcher, shared by this
    module's byte attribution and ``analysis.contracts``' contract keys
    (so the two can never disagree about which axis carried an op).

    Returns a key of ``by_groups`` (:func:`_axis_group_sets`) on an exact
    group-set match, ``"unattributed"`` when nothing matches or XLA
    printed no groups, and ``None`` for degenerate all-singleton groups
    (no traffic — callers decide whether to skip or bucket those).
    """
    if not groups:
        return "unattributed"
    gset = frozenset(
        frozenset(int(x) for x in g) for g in groups if len(g) > 1
    )
    if not gset:
        return None
    for cand, expected in by_groups.items():
        if gset == expected:
            return cand
    return "unattributed"


def axis_collective_volume(hlo_or_instrs: Any, mesh: Any) -> dict:
    """Attribute collective byte volume to mesh axes.

    ``hlo_or_instrs`` is optimized HLO text or the output of
    ``parallel.hlo.collective_instructions``. Returns
    ``{label: {"ops": n, "bytes": b}}`` with one label per mesh-axis subset
    that carried traffic (``"data"``, ``"model"``, ``"data+model"``, …) plus
    ``"unattributed"`` for groups matching no axis subset (or instructions
    XLA printed without groups). Bytes are each instruction's largest buffer
    — the per-device volume proxy, comparable across rounds rather than an
    exact wire model.
    """
    instrs = (
        collective_instructions(hlo_or_instrs)
        if isinstance(hlo_or_instrs, str) else hlo_or_instrs
    )
    by_groups = _axis_group_sets(mesh)
    out: dict[str, dict] = {
        label: {"ops": 0, "bytes": 0} for label in by_groups
    }
    out["unattributed"] = {"ops": 0, "bytes": 0}
    for ins in instrs:
        label = axis_label_of_groups(ins.get("replica_groups"), by_groups)
        if label is None:
            continue   # degenerate single-member groups: no traffic
        out[label]["ops"] += 1
        out[label]["bytes"] += int(ins.get("bytes", 0))
    return out
