"""Compile accounting: recompiles, compile seconds, per-executable cost.

The silent performance killer in a jit-driven stack is the compile you
did not know happened — a shape drift that recompiles the decode step
mid-serve, a config toggle that doubles trace time. This module makes
compilation first-class telemetry, three ways:

* :class:`CompileWatch` — process-wide listener on JAX's monitoring
  events (``/jax/core/compile/*``): counts jaxpr traces, MLIR lowerings,
  and backend compiles, with seconds for each, optionally mirrored into
  a :class:`~..telemetry.registry.MetricsRegistry`.
* :func:`watched` — per-function accounting: wraps a jitted callable and
  detects recompiles per CALL via the executable cache size
  (``PjitFunction._cache_size``), so "which function recompiled, and on
  which call" has an answer.
* :func:`executable_report` — per-executable ground truth from the
  compiled artifact itself: XLA ``cost_analysis()`` FLOPs/bytes,
  ``memory_analysis()`` buffer sizes, and the collective-op inventory
  via :func:`~..parallel.hlo.collective_counts` — what EQuARX
  (arXiv 2506.17615) and the model-parallel communication literature
  (arXiv 2211.05322) say dominates scaled cost, now machine-readable
  per step.

The monitoring hooks live in ``jax._src.monitoring`` in this JAX
version; their absence degrades :class:`CompileWatch` to zeros with
``monitoring_available = False`` instead of failing (no new
dependencies, no hard version pin).
"""

from __future__ import annotations

import threading
from typing import Any, Callable

import jax

from learning_jax_sharding_tpu.parallel.hlo import (
    collective_counts,
    collective_instructions,
)

try:  # the monitoring module is private API — gate, don't pin
    from jax._src import monitoring as _monitoring

    # Both halves must exist: registering without being able to
    # unregister would make stop() raise after a full bench run.
    _MON_OK = hasattr(
        _monitoring, "register_event_duration_secs_listener"
    ) and hasattr(
        _monitoring, "_unregister_event_duration_listener_by_callback"
    )
except Exception:  # pragma: no cover - import-shape drift
    _monitoring = None
    _MON_OK = False

#: Event keys observed from jax 0.4.x; unknown keys are kept under "other".
EVENT_KINDS = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "backend_compile",
}


class CompileWatch:
    """Count and time every compilation the process performs while the
    watch is active.

    Use as a context manager (or ``start()``/``stop()``). Numbers
    accumulate across nested activations of the same object; a registry
    passed at construction receives the same accounting as counters
    (``compile_events_total``/``compile_seconds_total`` per kind).
    """

    def __init__(
        self, registry: Any | None = None, *, recorder: Any | None = None
    ):
        self.monitoring_available = _MON_OK
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._seconds: dict[str, float] = {}
        self._active = 0
        self._registry = registry
        self._recorder = recorder

    def _on_duration(self, name: str, secs: float, **kw) -> None:
        kind = EVENT_KINDS.get(name)
        if kind is None:
            if not name.startswith("/jax/core/compile"):
                return
            kind = "other"
        with self._lock:
            self._counts[kind] = self._counts.get(kind, 0) + 1
            self._seconds[kind] = self._seconds.get(kind, 0.0) + secs
        if self._recorder is not None:
            self._recorder.record("compile", compile_kind=kind, seconds=secs)
        if self._registry is not None:
            self._registry.counter(
                f"compile_{kind}_total",
                "compile events observed by CompileWatch",
            ).inc()
            self._registry.counter(
                f"compile_{kind}_seconds_total",
                "seconds spent in compile events",
            ).inc(secs)

    def start(self) -> "CompileWatch":
        self._active += 1
        if self._active == 1 and _MON_OK:
            _monitoring.register_event_duration_secs_listener(
                self._on_duration
            )
        return self

    def stop(self) -> None:
        if self._active == 0:
            return
        self._active -= 1
        if self._active == 0 and _MON_OK:
            _monitoring._unregister_event_duration_listener_by_callback(
                self._on_duration
            )

    def __enter__(self) -> "CompileWatch":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def backend_compiles(self) -> int:
        return self._counts.get("backend_compile", 0)

    @property
    def backend_compile_seconds(self) -> float:
        return self._seconds.get("backend_compile", 0.0)

    def report(self) -> dict:
        """``{kind: n, kind_seconds: s, ...}`` for trace / lower /
        backend_compile, plus availability."""
        out: dict = {"monitoring_available": self.monitoring_available}
        for kind in ("trace", "lower", "backend_compile", "other"):
            out[f"{kind}s"] = self._counts.get(kind, 0)
            out[f"{kind}_seconds"] = self._seconds.get(kind, 0.0)
        return out


def cache_size(jitted: Callable) -> int | None:
    """Number of compiled executables a jitted function currently holds —
    i.e. its lifetime compile count (one per distinct shape/dtype/static
    combination). None when the runtime doesn't expose it."""
    probe = getattr(jitted, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


class WatchedFunction:
    """A jitted callable with per-call compile detection.

    ``calls`` counts invocations; ``compiles`` counts calls whose
    dispatch grew the executable cache (a fresh trace+compile);
    ``compile_calls`` lists which call indices compiled — the answer to
    "did serving hit a recompile mid-flight, and when".
    """

    def __init__(self, fn: Callable, name: str | None = None):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", repr(fn))
        self.calls = 0
        self.compiles = 0
        self.compile_calls: list[int] = []

    def __call__(self, *args, **kwargs):
        before = cache_size(self.fn)
        out = self.fn(*args, **kwargs)
        self.calls += 1
        after = cache_size(self.fn)
        if before is not None and after is not None and after > before:
            self.compiles += after - before
            self.compile_calls.append(self.calls)
        return out

    def stats(self) -> dict:
        return {
            "name": self.name,
            "calls": self.calls,
            "compiles": self.compiles,
            "compile_calls": list(self.compile_calls),
            "cache_size": cache_size(self.fn),
        }


def watched(fn: Callable, name: str | None = None) -> WatchedFunction:
    """Wrap a jitted function for per-call compile detection."""
    return WatchedFunction(fn, name)


def _cost_analysis_dict(compiled) -> dict:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):   # some backends: one dict per device
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def executable_report(fn: Callable, *args, **kwargs) -> dict:
    """Ground-truth accounting for ONE executable: lower+compile ``fn``
    on ``args`` (AOT — costs a compile; a diagnostic, not a hot-path
    call) and report

    * ``flops`` / ``bytes_accessed`` from XLA cost analysis (None when
      the backend doesn't report them);
    * ``memory``: argument/output/temp/code bytes from
      ``memory_analysis()``;
    * ``collectives``: per-op-kind instruction counts from the optimized
      HLO (``parallel.hlo.collective_counts`` — async pairs count once);
    * ``collective_instructions``: per-instruction records (op, bytes,
      replica groups) — feed ``telemetry.devview.axis_collective_volume``
      with the program's mesh to attribute bytes per mesh axis.

    ``args`` should carry their real shardings so the partitioner makes
    the same collective choices the runtime would.
    """
    jitted = fn if isinstance(fn, jax.stages.Wrapped) else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    ca = _cost_analysis_dict(compiled)
    flops = ca.get("flops")
    bytes_accessed = ca.get("bytes accessed")
    memory: dict = {}
    try:
        ms = compiled.memory_analysis()
        memory = {
            "argument_bytes": int(ms.argument_size_in_bytes),
            "output_bytes": int(ms.output_size_in_bytes),
            "temp_bytes": int(ms.temp_size_in_bytes),
            "generated_code_bytes": int(ms.generated_code_size_in_bytes),
            "alias_bytes": int(ms.alias_size_in_bytes),
        }
    except Exception:  # backends without memory stats
        memory = {}
    text = compiled.as_text()
    return {
        "flops": float(flops) if flops and flops > 0 else None,
        "bytes_accessed": (
            float(bytes_accessed)
            if bytes_accessed and bytes_accessed > 0 else None
        ),
        "memory": memory,
        "collectives": collective_counts(text),
        "collective_instructions": collective_instructions(text),
    }
