"""In-engine SLO monitoring: streaming percentiles + burn-rate targets.

The serving question the registry's cumulative histograms cannot answer
directly is "are we CURRENTLY violating our latency objective, and how
fast are we burning error budget". This module answers it in-process:

* :class:`StreamingPercentile` — a sliding-window quantile estimator (ring
  of the most recent ``window`` observations; O(1) observe, O(n log n)
  quantile on demand). Deliberately windowed, not lifetime: an SLO verdict
  is about NOW, and the pinned-exact lifetime percentiles already live in
  ``ContinuousEngine.latency_stats``.
* :class:`SLOTarget` — one objective: ``metric``'s value must be ``<=
  threshold`` for at least ``objective`` of events (e.g. "p99 TTFT under
  500 ms" is ``SLOTarget("ttft", 0.5, objective=0.99)``).
* :class:`SLOMonitor` — observes metric values (the engine feeds
  TTFT/TPOT/ITL/queue-wait/e2e per retirement when constructed with
  ``slo=monitor``, plus — round 9 — a per-dispatch ``decode_stall_share``
  0/1 indicator whenever rows were actively decoding: 1 when the
  dispatch parked them behind another slot's refill (the split engine's
  refill), 0 when they advanced (decode, or the fused ``mixed_step``) —
  so a ``decode_stall_share`` target reads as the fraction of
  decode-live dispatches that stalled decode), maintains per-target
  good/bad counts and the BURN RATE
  — the windowed bad fraction over the error budget ``1 - objective``;
  burn rate 1.0 means exactly consuming budget, >1 means the target fails
  if the window's behavior persists. Counters/gauges mirror into a
  :class:`~..telemetry.registry.MetricsRegistry` (Prometheus-exportable via
  the existing path), breaches feed the flight recorder.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Iterable

import numpy as np


class StreamingPercentile:
    """Sliding-window percentile estimator over the last ``window`` values."""

    def __init__(self, window: int = 2048):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._buf: "collections.deque[float]" = collections.deque(
            maxlen=window
        )
        self.count = 0   # lifetime observations (window holds the tail)

    def observe(self, value: float) -> None:
        self._buf.append(float(value))
        self.count += 1

    def quantile(self, q: float) -> float | None:
        if not self._buf:
            return None
        return float(np.percentile(np.asarray(self._buf), q * 100.0))

    def snapshot(self) -> dict:
        if self._buf:
            # One conversion + sort serves all three quantiles (the
            # per-call path re-sorts; snapshot is the bulk reader).
            p50, p90, p99 = (
                float(v)
                for v in np.percentile(np.asarray(self._buf), (50, 90, 99))
            )
        else:
            p50 = p90 = p99 = None
        return {
            "count": self.count,
            "window": len(self._buf),
            "p50": p50,
            "p90": p90,
            "p99": p99,
        }


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """``metric <= threshold`` for at least ``objective`` of events."""

    metric: str
    threshold: float
    objective: float = 0.99
    name: str = ""

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}"
            )
        if not self.name:
            object.__setattr__(
                self, "name", f"{self.metric}_le_{self.threshold:g}"
            )


class SLOMonitor:
    """Streams metric observations into percentile estimators and SLO
    burn-rate accounting.

    ``registry``/``recorder`` may be bound later (the engine binds its own
    registry when the monitor arrives without one); counters are created on
    first use, so late binding loses nothing.
    """

    def __init__(
        self,
        targets: Iterable[SLOTarget] = (),
        *,
        registry: Any | None = None,
        recorder: Any | None = None,
        window: int = 2048,
    ):
        self.targets = list(targets)
        self.registry = registry
        self.recorder = recorder
        self._window = window
        self._est: dict[str, StreamingPercentile] = {}
        # Per-target: lifetime events/breaches + the burn window (ring of
        # bools — True = breached).
        self._events: dict[str, int] = {t.name: 0 for t in self.targets}
        self._breaches: dict[str, int] = {t.name: 0 for t in self.targets}
        self._burn: dict[str, collections.deque] = {
            t.name: collections.deque(maxlen=window) for t in self.targets
        }
        # Running breach count per window (evictions decrement it), so
        # burn_rate is O(1) — observe() runs per ITL gap in the engine's
        # retire path. Metric handles are cached per bound registry.
        self._burn_bad: dict[str, int] = {t.name: 0 for t in self.targets}
        self._handles: dict[str, tuple] = {}
        self._handles_registry: Any | None = None
        # Per-tenant burn accounting (round 20) — created lazily on the
        # first tenant-labeled observe(), so an unlabeled monitor stays
        # BIT-compatible with the pre-tenant one: same state, same
        # registry series, same snapshot.
        self._tenants: dict[str, dict[str, dict]] = {}
        self._tenant_handles: dict[tuple, tuple] = {}

    def estimator(self, metric: str) -> StreamingPercentile:
        est = self._est.get(metric)
        if est is None:
            est = self._est[metric] = StreamingPercentile(self._window)
        return est

    def _target_handles(self, t: SLOTarget) -> tuple | None:
        if self.registry is None:
            return None
        if self._handles_registry is not self.registry:
            self._handles = {}   # re-bound: stale handles point elsewhere
            self._handles_registry = self.registry
        h = self._handles.get(t.name)
        if h is None:
            h = self._handles[t.name] = (
                self.registry.counter(
                    f"slo_{t.name}_events_total", "SLO-evaluated events"
                ),
                self.registry.counter(
                    f"slo_{t.name}_breaches_total",
                    "events over the SLO threshold",
                ),
                self.registry.gauge(
                    f"slo_{t.name}_burn_rate",
                    "windowed bad fraction over the error budget",
                ),
            )
        return h

    def _tenant_handles_for(self, t: SLOTarget, tenant: str):
        if self.registry is None:
            return None
        if self._handles_registry is not self.registry:
            self._handles = {}   # re-bound: stale handles point elsewhere
            self._tenant_handles = {}
            self._handles_registry = self.registry
        key = (tenant, t.name)
        h = self._tenant_handles.get(key)
        if h is None:
            from learning_jax_sharding_tpu.telemetry.registry import (
                labeled_name,
            )

            h = self._tenant_handles[key] = (
                self.registry.counter(
                    labeled_name(
                        f"slo_{t.name}_events_total", tenant=tenant
                    ),
                    "SLO-evaluated events",
                ),
                self.registry.counter(
                    labeled_name(
                        f"slo_{t.name}_breaches_total", tenant=tenant
                    ),
                    "events over the SLO threshold",
                ),
                self.registry.gauge(
                    labeled_name(
                        f"slo_{t.name}_burn_rate", tenant=tenant
                    ),
                    "windowed bad fraction over the error budget",
                ),
            )
        return h

    def _observe_tenant(self, t: SLOTarget, tenant: str, bad: bool):
        per = self._tenants.setdefault(tenant, {})
        s = per.get(t.name)
        if s is None:
            s = per[t.name] = {
                "events": 0, "breaches": 0, "bad": 0,
                "ring": collections.deque(maxlen=self._window),
            }
        s["events"] += 1
        ring = s["ring"]
        if len(ring) == ring.maxlen:
            s["bad"] -= ring.popleft()
        ring.append(bad)
        s["bad"] += bad
        if bad:
            s["breaches"] += 1
        h = self._tenant_handles_for(t, tenant)
        if h is not None:
            h[0].inc()
            if bad:
                h[1].inc()
            h[2].set(self.tenant_burn_rate(t.name, tenant))

    def observe(
        self, metric: str, value: float, *, tenant: str | None = None,
    ) -> None:
        """Feed one observation. ``tenant`` additionally books it into
        that tenant's OWN burn window and ``{tenant="..."}``-labeled
        registry series (label values escaped) — the unlabeled series
        keep aggregating every event exactly as before, so the
        all-tenant view stays bit-compatible."""
        if value is None:
            return
        value = float(value)
        self.estimator(metric).observe(value)
        for t in self.targets:
            if t.metric != metric:
                continue
            bad = value > t.threshold
            self._events[t.name] += 1
            ring = self._burn[t.name]
            if len(ring) == ring.maxlen:
                self._burn_bad[t.name] -= ring.popleft()
            ring.append(bad)
            self._burn_bad[t.name] += bad
            handles = self._target_handles(t)
            if handles is not None:
                handles[0].inc()
            if bad:
                self._breaches[t.name] += 1
                if handles is not None:
                    handles[1].inc()
                if self.recorder is not None:
                    self.recorder.record(
                        "slo_breach", target=t.name, metric=metric,
                        value=value, threshold=t.threshold,
                        tenant=tenant,
                    )
            if handles is not None:
                handles[2].set(self.burn_rate(t.name))
            if tenant is not None:
                self._observe_tenant(t, tenant, bad)

    def reset_window(self) -> None:
        """Open a fresh burn/percentile window: drop every ring (global,
        per-tenant) and estimator, keep the LIFETIME events/breaches
        counters. The serving-side analogue of ``ledger.begin_window``
        — warm-up and calibration traffic observed before a measurement
        (or a control loop) starts must not keep reading as burn for
        the next 2048 events."""
        self._est = {}
        for name in self._burn:
            self._burn[name] = collections.deque(maxlen=self._window)
            self._burn_bad[name] = 0
        for per in self._tenants.values():
            for s in per.values():
                s["ring"] = collections.deque(maxlen=self._window)
                s["bad"] = 0

    def burn_rate(self, name: str) -> float:
        """Windowed breach fraction over the error budget ``1-objective``
        (O(1): the window's breach count is maintained incrementally).
        0 = clean window, 1 = consuming budget exactly, >1 = violating."""
        t = self._target(name)
        ring = self._burn[name]
        if not ring:
            return 0.0
        frac = self._burn_bad[name] / len(ring)
        return frac / (1.0 - t.objective)

    def tenant_burn_rate(self, name: str, tenant: str) -> float:
        """One tenant's windowed burn rate for target ``name`` — 0.0
        for a tenant (or target) that has no labeled observations yet."""
        t = self._target(name)
        s = self._tenants.get(tenant, {}).get(name)
        if not s or not s["ring"]:
            return 0.0
        return (s["bad"] / len(s["ring"])) / (1.0 - t.objective)

    def tenant_burn_rates(self) -> dict[str, dict[str, float]]:
        """``{tenant: {target: burn_rate}}`` over every tenant that has
        labeled observations — the per-tenant SLO burn timeline's
        sample, and economics' worst-tenant pick."""
        return {
            tenant: {
                name: self.tenant_burn_rate(name, tenant)
                for name in per
            }
            for tenant, per in self._tenants.items()
        }

    def _target(self, name: str) -> SLOTarget:
        for t in self.targets:
            if t.name == name:
                return t
        raise KeyError(f"unknown SLO target {name!r}")

    def breached(self) -> list[str]:
        """Targets currently burning budget faster than they earn it."""
        return [t.name for t in self.targets if self.burn_rate(t.name) > 1.0]

    def snapshot(self) -> dict:
        """JSON-able state: per-metric percentile snapshots + per-target
        burn accounting. Also refreshes the percentile gauges in the bound
        registry (quantiles cost a window sort — paid here, not per
        observation)."""
        metrics = {m: est.snapshot() for m, est in self._est.items()}
        if self.registry is not None:
            for m, snap in metrics.items():
                for q in ("p50", "p99"):
                    if snap[q] is not None:
                        self.registry.gauge(
                            f"slo_{m}_{q}",
                            f"windowed {q} of {m}",
                        ).set(snap[q])
        targets = {}
        for t in self.targets:
            br = self.burn_rate(t.name)
            targets[t.name] = {
                "metric": t.metric,
                "threshold": t.threshold,
                "objective": t.objective,
                "events": self._events[t.name],
                "breaches": self._breaches[t.name],
                "burn_rate": br,
                "healthy": br <= 1.0,
            }
        out = {"metrics": metrics, "targets": targets}
        if self._tenants:
            # Key present ONLY when tenant-labeled observations exist —
            # an unlabeled monitor's snapshot is bit-identical to the
            # pre-tenant format.
            out["tenants"] = {
                tenant: {
                    name: {
                        "events": s["events"],
                        "breaches": s["breaches"],
                        "burn_rate": self.tenant_burn_rate(name, tenant),
                    }
                    for name, s in per.items()
                }
                for tenant, per in self._tenants.items()
            }
        return out
