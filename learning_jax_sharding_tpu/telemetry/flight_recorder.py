"""Flight recorder: a bounded ring of structured events + post-mortem dump.

PR 1 made the stack measurable; this module makes incidents *reconstructable*.
A :class:`FlightRecorder` holds the last ``max_events`` structured events —
engine admissions/evictions/preemptions, train-step records, compile events,
span closures — in a thread-safe ring that costs one dict append per event,
cheap enough to leave on in production. When something goes wrong (an
exception inside :meth:`FlightRecorder.capture`, a watchdog escalation, or an
explicit call), :meth:`FlightRecorder.dump` writes a POST-MORTEM BUNDLE:

* ``events.json``   — the ring's last-N events, oldest first;
* ``registry.json`` — a :class:`~..telemetry.registry.MetricsRegistry`
  snapshot (when one is attached/passed);
* ``trace.json``    — the attached :class:`~..telemetry.spans.Tracer`'s
  Chrome trace (Perfetto-loadable);
* ``memory.json``   — per-device memory stats via
  :func:`~.telemetry.devview.device_memory_stats` (guarded: backends without
  stats degrade to empty dicts, never a crash);
* ``error.txt``     — the exception/traceback that triggered the dump.

Producers feed the ring directly (``ContinuousEngine`` and ``fit()`` do so
automatically) or through :meth:`attach_tracer`, which forwards every span
CLOSURE (the tracer's complete events) as a ``span`` record — so the ring
carries the dispatch timeline interleaved with the lifecycle events.

Artifacts land under ``$LJST_ARTIFACT_DIR`` when set (one subdirectory per
bundle), else a fresh temp directory — never the CWD.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import pathlib
import tempfile
import threading
import time
import traceback
from typing import Any, Iterator


def _json_safe(obj: Any) -> Any:
    """Strict-JSON form of ``obj``: non-finite floats become the strings
    "NaN"/"Infinity"/"-Infinity". ``json.dump``'s default emits bare NaN
    tokens, which jq/JSON.parse/strict ingesters reject — and a NaN in
    the events is exactly the post-mortem case this module exists for."""
    if isinstance(obj, float):
        if obj != obj:
            return "NaN"
        if obj in (float("inf"), float("-inf")):
            return "Infinity" if obj > 0 else "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def artifact_dir(name: str) -> pathlib.Path:
    """Resolve the output directory for a named artifact set.

    ``$LJST_ARTIFACT_DIR`` (created on demand) when set — the operator's
    one knob for where diagnosis output lands — else a fresh temp
    directory, so cases and post-mortems never litter the CWD.
    """
    base = os.environ.get("LJST_ARTIFACT_DIR")
    if base:
        p = pathlib.Path(base) / name
        p.mkdir(parents=True, exist_ok=True)
        return p
    return pathlib.Path(tempfile.mkdtemp(prefix=f"ljst_{name}_"))


class FlightRecorder:
    """Bounded ring buffer of structured events with a post-mortem dump.

    Events are plain dicts ``{"t": unix_seconds, "kind": str, **fields}``;
    past ``max_events`` the OLDEST are evicted (with a count), because a
    post-mortem needs the window right before the incident, not the run's
    first minutes. A registry and tracer may be attached at construction so
    ``dump()`` needs no arguments at the crash site.
    """

    def __init__(
        self,
        *,
        max_events: int = 4096,
        registry: Any | None = None,
        tracer: Any | None = None,
    ):
        self._events: "collections.deque[dict]" = collections.deque(
            maxlen=max_events
        )
        self._max_events = max_events
        self._lock = threading.Lock()
        self.dropped = 0
        self.registry = registry
        self.tracer = tracer
        self.last_dump: pathlib.Path | None = None
        self._dump_seq = 0

    # --- recording ---------------------------------------------------------

    def record(self, kind: str, **fields: Any) -> None:
        """Append one event. Values must be JSON-able (the producer's
        contract — the dump path never filters)."""
        ev = {"t": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            if len(self._events) >= self._max_events:
                self.dropped += 1
            self._events.append(ev)

    def events(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def attach_tracer(self, tracer: Any) -> None:
        """Forward ``tracer``'s span closures (complete events) into the
        ring as ``span`` records — the dispatch timeline rides next to the
        lifecycle events it explains."""
        self.tracer = tracer

        def on_event(ev: dict) -> None:
            if ev.get("ph") == "X":
                self.record(
                    "span", name=ev["name"], dur_us=ev.get("dur"),
                    ts_us=ev.get("ts"),
                )

        tracer.on_event = on_event

    # --- the post-mortem bundle -------------------------------------------

    def dump(
        self,
        outdir: str | os.PathLike | None = None,
        *,
        registry: Any | None = None,
        tracer: Any | None = None,
        error: BaseException | str | None = None,
    ) -> pathlib.Path:
        """Write the post-mortem bundle; returns its directory.

        ``outdir`` defaults to a fresh ``postmortem<N>`` under
        :func:`artifact_dir` resolution. Every section is individually
        guarded — a dump taken mid-crash must never raise over the original
        failure.
        """
        if outdir is None:
            base = os.environ.get("LJST_ARTIFACT_DIR")
            if base:
                # A persistent artifact dir outlives this process: never
                # take a postmortem<N> slot an EARLIER run already wrote
                # — overwriting old forensic evidence with new is the one
                # failure a post-mortem dump must not have.
                while True:
                    self._dump_seq += 1
                    outdir = (
                        pathlib.Path(base) / f"postmortem{self._dump_seq}"
                    )
                    if not outdir.exists():
                        break
            else:
                self._dump_seq += 1
                outdir = artifact_dir(f"postmortem{self._dump_seq}")
        out = pathlib.Path(outdir)
        try:
            out.mkdir(parents=True, exist_ok=True)
            with open(out / "events.json", "w") as f:
                json.dump(
                    _json_safe(
                        {"dropped": self.dropped, "events": self.events()}
                    ),
                    f, indent=2, default=str, allow_nan=False,
                )
        except Exception:   # pragma: no cover - crash-path guard
            # An unwritable artifact dir must not mask the ORIGINAL
            # failure the dump is documenting (capture()/escalate() call
            # this mid-crash). Best effort only, like every section.
            return out
        registry = registry if registry is not None else self.registry
        if registry is not None:
            try:
                # Through the sanitizer, not registry.dump_json: a gauge
                # holding the NaN loss must not make the bundle unparseable.
                with open(out / "registry.json", "w") as f:
                    json.dump(
                        _json_safe(registry.snapshot()), f, indent=2,
                        sort_keys=True, allow_nan=False,
                    )
            except Exception:  # pragma: no cover - crash-path guard
                pass
        tracer = tracer if tracer is not None else self.tracer
        if tracer is not None:
            try:
                tracer.dump_chrome_trace(out / "trace.json")
            except Exception:  # pragma: no cover - crash-path guard
                pass
        try:
            from learning_jax_sharding_tpu.telemetry.devview import (
                device_memory_stats,
            )

            with open(out / "memory.json", "w") as f:
                json.dump(device_memory_stats(), f, indent=2)
        except Exception:  # pragma: no cover - crash-path guard
            pass
        if error is not None:
            if isinstance(error, BaseException):
                text = "".join(
                    traceback.format_exception(
                        type(error), error, error.__traceback__
                    )
                )
            else:
                text = str(error)
            (out / "error.txt").write_text(text)
        self.record("dump", path=str(out))
        self.last_dump = out
        return out

    @contextlib.contextmanager
    def capture(
        self, outdir: str | os.PathLike | None = None
    ) -> Iterator["FlightRecorder"]:
        """Dump a post-mortem bundle if the block raises, then re-raise —
        wrap a serve loop or training run to get the bundle for free."""
        try:
            yield self
        except BaseException as e:
            self.record("exception", type=type(e).__name__, message=str(e))
            self.dump(outdir, error=e)
            raise


_DEFAULT = FlightRecorder()


def default_flight_recorder() -> FlightRecorder:
    """The process-wide recorder — producers not handed one record here,
    so one ring holds the whole process's recent history."""
    return _DEFAULT
