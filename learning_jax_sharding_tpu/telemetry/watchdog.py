"""Runtime health watchdogs: NaN/spike detection, hang flagging, escalation.

Three full-speed probes that turn "the loss went bad two hours ago" into a
named step and a localized primitive:

* :class:`Watchdog` — per-step numeric health at dispatch speed: an
  on-device ``isfinite`` of the loss AND the global grad-norm is launched
  eagerly (one tiny fused op, async like everything else) and FETCHED LATE —
  results are read only once they are device-complete (``jax.Array
  .is_ready``) or ``lag`` steps old, so the probe never inserts a sync the
  training loop wasn't already paying. Finite losses also feed a loss-spike
  detector (observation vs an EMA of recent loss).
* :class:`Heartbeat` — a daemon thread that flags HUNG device syncs: wrap
  any blocking section in :meth:`Heartbeat.expect` and the thread records a
  ``hang`` event (registry counter + flight-recorder record) the moment the
  section overruns its deadline — the signal a wedged transport or deadlocked
  collective otherwise never produces, because the hung host thread can't
  report its own hang.
* :func:`localize_nan` — the escalation: re-run the offending computation
  under ``utils.profiling.checking()`` (scoped ``jax_debug_nans``), which
  recompiles with per-primitive NaN traps and raises ``FloatingPointError``
  AT the first NaN-producing primitive. The returned message names it.

``training.loop.fit(watchdog=...)`` wires all of this automatically: the
train step additionally returns the global grad-norm (on device — no extra
sync), the watchdog probes every step, and a trip re-runs the offending
step's batch under checking, records the localization, dumps the flight
recorder's post-mortem bundle, and raises :class:`NonFiniteError` naming the
step.

Steady-state cost is two eager element-wise ops on scalars plus a few dict
appends per step — measured <1% of even a TINY model's CPU train step
(PERF.md round 7; on the 66 ms bench-model step it is noise).
"""

from __future__ import annotations

import collections
import contextlib
import math
import threading
import time
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp


class NonFiniteError(RuntimeError):
    """Training produced a non-finite loss/grad-norm. Carries the step the
    watchdog flagged, the localized primitive (when escalation ran), and the
    post-mortem bundle path (when a recorder dumped one)."""

    def __init__(
        self,
        step: int,
        what: str = "loss/grad_norm",
        localized: str | None = None,
        bundle: Any | None = None,
    ):
        self.step = step
        self.what = what
        self.localized = localized
        self.bundle = bundle
        msg = f"non-finite {what} at step {step}"
        if bundle is not None:
            msg += f" (post-mortem bundle: {bundle})"
        if localized:
            first = localized.strip().splitlines()
            msg += f"; first bad primitive: {first[0] if first else ''}"
        super().__init__(msg)


def localize_nan(fn: Callable[[], Any]) -> str | None:
    """Re-run ``fn()`` under scoped NaN trapping and return the trap message
    (which names the first NaN-producing primitive), or None when the re-run
    stayed finite (non-determinism, or state moved past the bad input).

    Costs a recompile both ways (``checking()`` clears executable caches on
    entry AND exit so check-laden code never leaks into production dispatch)
    — an incident-path diagnostic, not a hot-path call.
    """
    from learning_jax_sharding_tpu.utils.profiling import checking

    try:
        with checking():
            out = fn()
            for leaf in jax.tree_util.tree_leaves(out):
                jax.block_until_ready(leaf)
    except FloatingPointError as e:
        return str(e)
    return None


class Watchdog:
    """Asynchronous numeric-health probe for a training loop.

    Call :meth:`probe` once per step with the DEVICE loss (and optionally
    the device grad-norm). The finiteness check runs on device; results are
    consumed once ready or ``lag`` steps later, whichever comes first, so
    the watchdog adds no sync of its own. :attr:`first_bad_step` is the
    earliest flagged step; :attr:`tripped` is the cheap "should I escalate"
    test. Call :meth:`flush` after the loop to drain in-flight probes.

    Loss-spike detection: a finite loss more than ``spike_factor`` × the
    EMA of previous losses (after ``spike_min_steps`` observations) records
    a ``loss_spike`` event and increments ``watchdog_loss_spikes_total`` —
    the instability signal that precedes most NaN incidents.
    """

    def __init__(
        self,
        *,
        registry: Any | None = None,
        recorder: Any | None = None,
        lag: int = 2,
        ema_alpha: float = 0.1,
        spike_factor: float = 10.0,
        spike_min_steps: int = 5,
    ):
        if lag < 0:
            raise ValueError(f"lag must be >= 0, got {lag}")
        self.lag = lag
        self.ema_alpha = ema_alpha
        self.spike_factor = spike_factor
        self.spike_min_steps = spike_min_steps
        self.first_bad_step: int | None = None
        self.bad_what: str | None = None
        self.loss_ema: float | None = None
        self.spikes: list[dict] = []
        self.steps_probed = 0
        self._seen = 0
        self._pending: "collections.deque[tuple[int, Any, Any, Any]]" = (
            collections.deque()
        )
        self._recorder = None
        self._c_probes = self._c_nonfinite = self._c_spikes = None
        self.bind(registry=registry, recorder=recorder)

    def bind(self, *, registry: Any | None = None,
             recorder: Any | None = None) -> None:
        """Late-bind export sinks an UNBOUND watchdog is missing —
        ``fit()`` calls this with its own registry/recorder, so
        ``Watchdog()`` passed bare still meters and records. Sinks set
        at construction win."""
        if self._recorder is None:
            self._recorder = recorder
        if self._c_probes is None and registry is not None:
            self._c_probes = registry.counter(
                "watchdog_probes_total", "watchdog step probes consumed")
            self._c_nonfinite = registry.counter(
                "watchdog_nonfinite_total", "steps with non-finite health")
            self._c_spikes = registry.counter(
                "watchdog_loss_spikes_total", "losses beyond spike_factor×EMA")

    @property
    def tripped(self) -> bool:
        return self.first_bad_step is not None

    def probe(self, step: int, loss: Any, grad_norm: Any = None) -> None:
        """Launch this step's health check (async) and consume any prior
        checks that are ready (or older than ``lag`` steps)."""
        # One fused check: loss + grad_norm is finite iff both are (an
        # inf-minus-inf cancellation yields NaN, still caught) — two eager
        # dispatches instead of three; dispatch latency IS the probe cost.
        finite = jnp.isfinite(
            loss if grad_norm is None else loss + grad_norm
        )
        self.steps_probed += 1
        self._pending.append((step, finite, loss, grad_norm))
        self._drain(block_over=self.lag)

    def flush(self) -> None:
        """Consume every in-flight probe (blocking reads — loop is over)."""
        self._drain(block_over=0)

    def _drain(self, *, block_over: int) -> None:
        while self._pending:
            step, finite, loss, grad_norm = self._pending[0]
            if len(self._pending) <= block_over and not _is_ready(finite):
                return
            self._pending.popleft()
            self._consume(step, finite, loss, grad_norm)

    def _consume(self, step, finite, loss, grad_norm) -> None:
        self._seen += 1
        if self._c_probes is not None:
            self._c_probes.inc()
        if bool(finite):
            val = float(loss)
            if (
                self.loss_ema is not None
                and self._seen > self.spike_min_steps
                and abs(val) > self.spike_factor * max(abs(self.loss_ema), 1e-12)
            ):
                self.spikes.append(
                    {"step": step, "loss": val, "ema": self.loss_ema}
                )
                if self._c_spikes is not None:
                    self._c_spikes.inc()
                if self._recorder is not None:
                    self._recorder.record(
                        "loss_spike", step=step, loss=val, ema=self.loss_ema
                    )
            a = self.ema_alpha
            self.loss_ema = (
                val if self.loss_ema is None
                else (1 - a) * self.loss_ema + a * val
            )
            return
        what = "loss" if not math.isfinite(float(loss)) else "grad_norm"
        if self.first_bad_step is None:
            self.first_bad_step = step
            self.bad_what = what
        if self._c_nonfinite is not None:
            self._c_nonfinite.inc()
        if self._recorder is not None:
            self._recorder.record("nonfinite", step=step, what=what)


def _is_ready(x: Any) -> bool:
    try:
        return bool(x.is_ready())
    except Exception:  # runtimes without is_ready: treat as ready (blocks)
        return True


class Heartbeat:
    """Flags sections that overrun a deadline — from a SEPARATE thread,
    because the hung thread cannot report its own hang.

    >>> hb = Heartbeat(timeout=30.0, recorder=rec)
    >>> with hb:                       # starts/stops the monitor thread
    ...     with hb.expect("decode sync"):
    ...         np.asarray(tokens)     # the blocking readback
    >>> hb.hangs                       # [] unless a section overran

    The flag is an event (``hang`` in the flight recorder, counter in the
    registry, an entry in :attr:`hangs`) — the section itself cannot be
    interrupted, but the operator (and the post-mortem bundle) now knows
    WHICH sync wedged and for how long, instead of a silent stall.
    """

    def __init__(
        self,
        timeout: float = 30.0,
        *,
        registry: Any | None = None,
        recorder: Any | None = None,
        poll: float | None = None,
    ):
        if timeout <= 0:
            raise ValueError(f"timeout must be > 0, got {timeout}")
        self.timeout = timeout
        self.hangs: list[dict] = []
        self._poll = poll if poll is not None else max(timeout / 4, 0.01)
        self._lock = threading.Lock()
        self._armed: tuple[str, float] | None = None   # (label, start)
        self._flagged = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._recorder = None
        self._c_hangs = None
        self.bind(registry=registry, recorder=recorder)

    def bind(self, *, registry: Any | None = None,
             recorder: Any | None = None) -> None:
        """Late-bind export sinks (see :meth:`Watchdog.bind`)."""
        if self._recorder is None:
            self._recorder = recorder
        if self._c_hangs is None and registry is not None:
            self._c_hangs = registry.counter(
                "watchdog_hangs_total", "sections that overran the heartbeat")

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Heartbeat":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="ljst-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self._poll + 1.0)
            self._thread = None

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @contextlib.contextmanager
    def expect(self, label: str) -> Iterator[None]:
        """Arm the monitor for the enclosed (blocking) section."""
        with self._lock:
            self._armed = (label, time.monotonic())
            self._flagged = False
        try:
            yield
        finally:
            with self._lock:
                self._armed = None
                self._flagged = False

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            with self._lock:
                armed, flagged = self._armed, self._flagged
                if armed is None or flagged:
                    continue
                label, start = armed
                overrun = time.monotonic() - start - self.timeout
                if overrun < 0:
                    continue
                self._flagged = True
            hang = {
                "label": label,
                "timeout": self.timeout,
                "overrun": overrun,
            }
            self.hangs.append(hang)
            if self._c_hangs is not None:
                self._c_hangs.inc()
            if self._recorder is not None:
                self._recorder.record("hang", **hang)
