"""Structured spans: nested wall-clock timing that lands in three places.

A :class:`Tracer` records host-side events (spans, instants, per-request
async intervals) and exports them as

* **Chrome trace-event JSON** (:meth:`Tracer.chrome_trace`) — load the
  file straight into Perfetto (https://ui.perfetto.dev) or
  ``chrome://tracing``;
* **JSONL** (:meth:`Tracer.dump_jsonl`) — one event per line for
  machine consumption, the ``BENCH_r{N}.json`` style;
* **XProf/TensorBoard**, live: every :meth:`Tracer.span` also enters a
  ``jax.profiler.TraceAnnotation``, so when a ``utils.profiling.trace``
  capture is active the framework phases appear on the profiler's host
  timeline next to the device ops they dispatched.

Honesty under async dispatch is explicit: a span around a jitted call
measures DISPATCH unless it contains a sync point (the reference's
timing flaw, `case6_attention.py:234-238`). :meth:`Tracer.sync` is that
sync point — it forces a one-element host readback of its argument
(``jax.block_until_ready`` alone is not trustworthy behind remote-device
transports, see ``utils/bench.py::_sync``) and records an instant event
marking where in the timeline the device was known to be done.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Iterator

import jax


def device_sync(out: Any) -> None:
    """Force completion of ``out`` by reading one element back to host —
    THE honest sync point. Delegates to ``utils.bench._sync`` so the
    repo has exactly one definition of what "synced" means (a fix to the
    tunneled-transport behavior documented there reaches every span)."""
    from learning_jax_sharding_tpu.utils.bench import _sync

    if not jax.tree_util.tree_leaves(out):
        return
    _sync(out)


class Tracer:
    """Collects trace events; cheap enough to leave on.

    Events are Chrome trace-event dicts (``ph`` phases used: ``X``
    complete, ``i`` instant, ``b``/``e`` async begin/end). Timestamps are
    microseconds since tracer construction; the buffer is a bounded RING
    (``max_events``): past the cap the OLDEST events are dropped (with a
    count), because the trace someone exports after an incident needs
    the most recent window, not the run's first minutes.
    """

    def __init__(
        self,
        *,
        enabled: bool = True,
        max_events: int = 200_000,
        name: str | None = None,
    ):
        import collections

        self.enabled = enabled
        self.name = name or "tracer"
        self.dropped = 0
        self.sink_errors = 0   # on_event sink raises (counted, not fatal)
        self._events: "collections.deque[dict]" = collections.deque(
            maxlen=max_events
        )
        self._lock = threading.Lock()
        self._local = threading.local()
        self._t0 = time.perf_counter()
        # Deterministic ids: the OS pid and raw thread idents change per
        # run, which made merged fleet timelines interleave replicas
        # nondeterministically in Perfetto. Events carry pid 1 and small
        # first-seen thread indexes; the real OS pid survives in the
        # process-name metadata (`chrome_trace`), and `merge_tracers`
        # re-pids per replica.
        self._pid = 1
        self._os_pid = os.getpid()
        self._tid_of: dict[int, int] = {}
        self._max_events = max_events
        # Optional event sink (``FlightRecorder.attach_tracer`` sets it):
        # called with each emitted event dict, outside the ring lock. A
        # raising sink must not take the traced code down with it.
        self.on_event = None

    # --- time/emission -----------------------------------------------------

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _emit(self, ev: dict) -> None:
        with self._lock:
            if len(self._events) >= self._max_events:
                self.dropped += 1   # the append below evicts the oldest
            self._events.append(ev)
        cb = self.on_event
        if cb is not None:
            try:
                cb(ev)
            except Exception:
                # A raising sink must not take the traced code down with
                # it — but the failure must not vanish either
                # (swallowed-exception lint): count it, so a broken
                # recorder attachment is visible in the tracer's state.
                self.sink_errors += 1

    def _tid(self) -> int:
        """Stable small tid for the calling thread: 1, 2, ... in
        first-seen order — deterministic for single-threaded loops
        (always 1), and never a raw ident that reshuffles every run."""
        ident = threading.get_ident()
        t = self._tid_of.get(ident)
        if t is None:
            with self._lock:
                t = self._tid_of.setdefault(ident, len(self._tid_of) + 1)
        return t

    def thread_ids(self) -> list[int]:
        """Assigned tids, sorted — for thread-name metadata emission."""
        with self._lock:
            return sorted(self._tid_of.values())

    def _base(self, name: str, ph: str, **extra) -> dict:
        ev = {
            "name": name,
            "ph": ph,
            "ts": self._now_us(),
            "pid": self._pid,
            "tid": self._tid(),
        }
        ev.update(extra)
        return ev

    def _stack(self) -> list[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # --- recording API -----------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[None]:
        """Nested complete event + XProf bridge. ``args`` become the
        event's ``args`` dict (JSON-able values only)."""
        if not self.enabled:
            yield
            return
        stack = self._stack()
        parent = stack[-1] if stack else None
        stack.append(name)
        start = self._now_us()
        try:
            with jax.profiler.TraceAnnotation(name):
                yield
        finally:
            stack.pop()
            end = self._now_us()
            ev = self._base(name, "X", dur=end - start)
            ev["ts"] = start
            if parent is not None:
                args = dict(args, parent=parent)
            if args:
                ev["args"] = args
            self._emit(ev)

    def complete(
        self, name: str, start_perf: float, duration_s: float, **args
    ) -> None:
        """Record a complete event retrospectively from host timestamps
        (``time.perf_counter()`` start + seconds) — for call sites that
        only know after the fact whether a dispatch actually ran."""
        if not self.enabled:
            return
        ev = self._base(name, "X", dur=duration_s * 1e6)
        ev["ts"] = (start_perf - self._t0) * 1e6
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant(self, name: str, **args) -> None:
        if not self.enabled:
            return
        ev = self._base(name, "i", s="t")
        if args:
            ev["args"] = args
        self._emit(ev)

    def async_begin(self, name: str, id: int, **args) -> None:
        """Open an async interval (e.g. one request's admit→finish
        lifetime) — Perfetto renders ``b``/``e`` pairs keyed by
        (category, id) as horizontal tracks independent of call nesting."""
        if not self.enabled:
            return
        ev = self._base(name, "b", id=int(id), cat=name)
        if args:
            ev["args"] = args
        self._emit(ev)

    def async_end(self, name: str, id: int, **args) -> None:
        if not self.enabled:
            return
        ev = self._base(name, "e", id=int(id), cat=name)
        if args:
            ev["args"] = args
        self._emit(ev)

    def sync(self, out: Any, name: str = "device_sync") -> None:
        """Honest sync point: host-readback ``out``, then mark the
        instant the device was known done (see module docstring)."""
        if not self.enabled:
            device_sync(out)
            return
        t0 = time.perf_counter()
        device_sync(out)
        self.complete(name, t0, time.perf_counter() - t0)

    # --- export ------------------------------------------------------------

    @property
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def metadata_events(self, *, pid: int | None = None) -> list[dict]:
        """Chrome ``M``-phase name rows for this tracer's process and
        threads — deterministic content, so exported traces diff cleanly
        run-to-run (the real OS pid rides in args, not in the ids)."""
        pid = self._pid if pid is None else pid
        rows = [{
            "name": "process_name", "ph": "M", "ts": 0.0, "pid": pid,
            "tid": 0,
            "args": {"name": self.name, "os_pid": self._os_pid},
        }]
        rows.extend(
            {
                "name": "thread_name", "ph": "M", "ts": 0.0, "pid": pid,
                "tid": t,
                "args": {"name": f"thread {t}"},
            }
            for t in self.thread_ids()
        )
        return rows

    def chrome_trace(self) -> dict:
        """Perfetto/chrome://tracing-loadable trace object, metadata
        (process/thread names) first."""
        return {
            "traceEvents": self.metadata_events() + self.events,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_events": self.dropped},
        }

    def dump_chrome_trace(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)

    def dump_jsonl(self, path) -> None:
        with open(path, "w") as f:
            for ev in self.events:
                f.write(json.dumps(ev) + "\n")


_DEFAULT = Tracer()


def default_tracer() -> Tracer:
    """The process-wide tracer — subsystems not handed one trace here."""
    return _DEFAULT
