"""Comm observatory: measured per-axis collective profiles + overlap
attribution.

The analysis substrate (shardflow → costmodel → layout_search → memflow)
*plans* against the collectives GSPMD inserts, but until this module it
priced them with a flat, pinned per-axis table and assumed serial
(zero-overlap) execution. Commscope is the instrument that measures what
the model asserts:

* **Calibration ladder** — :func:`run_ladder` times micro-collectives
  (psum / all-gather / reduce-scatter / ppermute) per mesh axis across a
  byte-size sweep with the latency-cancelled ``utils.bench.time_fn``
  harness, and :func:`fit_axis_profiles` fits a per-axis α–β model
  ``t = α + wire_bytes / β`` by least squares. Profiles persist as
  versioned JSON under ``analysis/profiles/`` (:class:`CommProfile`);
  ``costmodel.calibrate_axis_profiles`` folds them into
  ``price_event`` with the pinned table as fallback.

* **Attribution** — :func:`attribute_measured_seconds` distributes a
  measured comm-seconds total across source lines proportionally to each
  line's *predicted* collective seconds (from
  ``parallel/hlo.collective_instructions`` bytes through shardflow
  events), producing the per-line predicted-vs-measured report
  ``engine.explain_collectives(measured=True)`` and ``shardcheck
  --comm`` render.

* **Overlap decomposition** — :func:`decompose_overlap` splits measured
  device seconds into compute / exposed-comm / overlapped-comm such that
  the three ALWAYS sum back exactly; ``GoodputLedger.overlap_report``
  applies it per program family, preserving the ledger's reconciliation
  invariant (comm seconds book under ``device``, never ``telemetry``).

Emulated-CPU caveat: on a host-emulated mesh every "link" is a memcpy
through one shared memory system, so ladder bandwidths are memcpy
bandwidths and axes look near-identical. The instrument is still honest —
it measures what dispatches actually cost *here* — but chip numbers land
via ``bench.py`` on real hardware.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any, Iterable, Mapping, Sequence

#: Bump when the persisted JSON schema changes; :meth:`CommProfile.load`
#: refuses mismatched files rather than silently misreading them.
PROFILE_VERSION = 1

#: Default home for persisted profiles (checked-in reference profiles
#: live here; runtime dumps go under ``$LJST_ARTIFACT_DIR``).
PROFILE_DIR = (
    pathlib.Path(__file__).resolve().parents[1] / "analysis" / "profiles"
)

#: Ladder micro-collectives, matching ``parallel/collectives.py`` idioms.
LADDER_OPS = ("psum", "all_gather", "reduce_scatter", "ppermute")

#: Per-device buffer bytes swept by default: small enough to finish in
#: seconds on the emulated mesh, wide enough (256×) to separate α from β.
DEFAULT_SIZES = (1 << 15, 1 << 17, 1 << 19, 1 << 21, 1 << 23)


def wire_bytes(op: str, n: int, local_bytes: float) -> float:
    """Bytes crossing links per device for one ladder collective over an
    ``n``-device axis with a ``local_bytes`` per-device input buffer.

    Ring algorithm volumes, the same convention as
    ``costmodel._ring_factor`` (all-reduce moves the buffer twice minus
    the resident shard; gather/scatter once; permute one full hop).
    """
    if n <= 1:
        return 0.0
    if op == "psum":
        return 2.0 * local_bytes * (n - 1) / n
    if op == "all_gather":
        return float(local_bytes * (n - 1))     # receives n-1 peer shards
    if op == "reduce_scatter":
        return local_bytes * (n - 1) / n
    if op == "ppermute":
        return float(local_bytes)
    raise ValueError(f"unknown ladder op {op!r}")


# --- calibration ladder ----------------------------------------------------


def _ladder_step(mesh, op: str, axis: str, local_elems: int):
    """Build ``(jitted_fn, input)`` for one timed micro-collective: a
    ``shard_map`` whose body is exactly one collective over ``axis``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = int(mesh.shape[axis])
    m = int(local_elems)
    if op == "psum":
        body = lambda x: lax.psum(x, axis)                      # noqa: E731
        out_spec = P()
    elif op == "all_gather":
        body = lambda x: lax.all_gather(                        # noqa: E731
            x, axis, axis=0, tiled=True)
        out_spec = P()
    elif op == "reduce_scatter":
        body = lambda x: lax.psum_scatter(                      # noqa: E731
            x, axis, scatter_dimension=0, tiled=True)
        out_spec = P(axis)
    elif op == "ppermute":
        perm = [(i, (i + 1) % n) for i in range(n)]
        body = lambda x: lax.ppermute(x, axis, perm)            # noqa: E731
        out_spec = P(axis)
    else:
        raise ValueError(f"unknown ladder op {op!r}")

    fn = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(axis),), out_specs=out_spec,
        check_vma=False,
    ))
    x = jax.device_put(
        np.ones((n * m,), np.float32),
        NamedSharding(mesh, P(axis)),
    )
    del jnp
    return fn, x


def run_ladder(
    mesh,
    *,
    ops: Sequence[str] = LADDER_OPS,
    sizes_bytes: Sequence[int] = DEFAULT_SIZES,
    axes: Sequence[str] | None = None,
    min_time: float = 0.05,
    repeats: int = 2,
    warmup: int = 1,
) -> list[dict[str, Any]]:
    """Time the calibration ladder on ``mesh``; returns one record per
    (axis, op, size) cell::

        {"op", "axis", "n", "bytes", "wire_bytes", "seconds"}

    ``bytes`` is the per-device input buffer; ``seconds`` comes from the
    ``time_fn`` harness (compiles excluded), so the records feed
    :func:`fit_axis_profiles` directly. Axes of size 1 are skipped — no
    collective runs there.

    Every call is synced before the next dispatch: XLA CPU's collective
    rendezvous DEADLOCKS when participants from multiple in-flight runs
    of the same program interleave (observed on the emulated mesh —
    "waiting for all participants to arrive" across distinct run_ids),
    so the async k-calls-one-readback chain ``time_fn`` normally builds
    is not available here. The per-call sync overhead is constant per
    collective, which is exactly the α term the fit estimates.
    """
    from ..utils.bench import time_fn
    from .spans import device_sync

    out: list[dict[str, Any]] = []
    for axis in tuple(axes if axes is not None else mesh.axis_names):
        n = int(mesh.shape[axis])
        if n <= 1:
            continue
        for op in ops:
            for b in sizes_bytes:
                # float32 elems, rounded up so reduce-scatter can tile.
                m = max(n, -(-int(b) // 4 // n) * n)
                fn, x = _ladder_step(mesh, op, axis, m)

                def call(fn=fn, x=x):
                    y = fn(x)
                    device_sync(y)
                    return y

                s = time_fn(
                    call, min_time=min_time, repeats=repeats,
                    warmup=warmup,
                )
                local = 4.0 * m
                out.append({
                    "op": op, "axis": axis, "n": n, "bytes": local,
                    "wire_bytes": wire_bytes(op, n, local),
                    "seconds": float(s),
                })
    return out


# --- α–β fit ---------------------------------------------------------------


def fit_alpha_beta(
    points: Iterable[tuple[float, float]],
) -> tuple[float, float, float]:
    """Least-squares fit of ``t = α + wire_bytes / β`` over ``(wire,
    seconds)`` points; returns ``(alpha_s, beta_bytes_per_s, r2)``.

    Exact on noiseless synthetic timings (pinned in
    ``tests/test_commscope.py``): the slope is ``1/β`` and the intercept
    is ``α``, clamped to physical ranges (α ≥ 0, β > 0) only afterwards
    so clean data round-trips unperturbed.
    """
    pts = [(float(w), float(t)) for w, t in points if w > 0]
    if len(pts) < 2:
        raise ValueError("need ≥ 2 points with wire_bytes > 0 to fit α–β")
    n = float(len(pts))
    sx = sum(w for w, _ in pts)
    sy = sum(t for _, t in pts)
    sxx = sum(w * w for w, _ in pts)
    sxy = sum(w * t for w, t in pts)
    denom = n * sxx - sx * sx
    slope = (n * sxy - sx * sy) / denom if denom else 0.0
    intercept = (sy - slope * sx) / n
    alpha = max(0.0, intercept)
    beta = 1.0 / slope if slope > 1e-18 else 1e18
    mean = sy / n
    ss_tot = sum((t - mean) ** 2 for _, t in pts)
    ss_res = sum((t - (intercept + slope * w)) ** 2 for w, t in pts)
    r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else 1.0
    return alpha, beta, r2


@dataclasses.dataclass(frozen=True)
class AxisProfile:
    """Fitted α–β model for one mesh axis."""

    axis: str
    alpha_s: float              # fixed per-collective latency, seconds
    beta_bytes_per_s: float     # asymptotic link bandwidth
    n_devices: int
    points: int                 # ladder cells behind the fit
    r2: float

    def predict_s(self, wire: float) -> float:
        """Model seconds for ``wire`` bytes on this axis."""
        if wire <= 0:
            return 0.0
        return self.alpha_s + wire / max(self.beta_bytes_per_s, 1.0)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "AxisProfile":
        return cls(**{k: d[k] for k in (
            "axis", "alpha_s", "beta_bytes_per_s", "n_devices", "points",
            "r2",
        )})


def fit_axis_profiles(
    measurements: Iterable[Mapping[str, Any]],
) -> dict[str, AxisProfile]:
    """Group ladder records by axis and fit one :class:`AxisProfile`
    each. All ops pool into one fit per axis — ring wire volumes already
    normalize op shape into ``wire_bytes``, so a shared α–β line is the
    per-axis link model the cost model consumes."""
    by_axis: dict[str, list[Mapping[str, Any]]] = {}
    for m in measurements:
        if m.get("wire_bytes", 0) > 0:
            by_axis.setdefault(str(m["axis"]), []).append(m)
    out: dict[str, AxisProfile] = {}
    for axis, ms in sorted(by_axis.items()):
        alpha, beta, r2 = fit_alpha_beta(
            (m["wire_bytes"], m["seconds"]) for m in ms
        )
        out[axis] = AxisProfile(
            axis=axis, alpha_s=alpha, beta_bytes_per_s=beta,
            n_devices=max(int(m["n"]) for m in ms), points=len(ms), r2=r2,
        )
    return out


def fit_errors(
    profiles: Mapping[str, AxisProfile],
    measurements: Iterable[Mapping[str, Any]],
) -> dict[str, float]:
    """Worst per-axis |predicted − measured| / measured, in percent —
    the reconciliation number gated against ``baseline.json``'s
    ``commscope_tolerance_pct``."""
    worst: dict[str, float] = {}
    for m in measurements:
        ap = profiles.get(str(m.get("axis")))
        if ap is None or m.get("wire_bytes", 0) <= 0:
            continue
        meas = float(m["seconds"])
        err = abs(ap.predict_s(m["wire_bytes"]) - meas) / max(meas, 1e-12)
        worst[ap.axis] = max(worst.get(ap.axis, 0.0), err * 100.0)
    return worst


# --- persisted profile -----------------------------------------------------


@dataclasses.dataclass
class CommProfile:
    """A fitted, persistable set of per-axis profiles for one mesh."""

    platform: str
    mesh_axes: tuple[str, ...]
    mesh_shape: tuple[int, ...]
    axes: dict[str, AxisProfile]
    measurements: list[dict[str, Any]] = dataclasses.field(
        default_factory=list)
    created_unix: float = 0.0
    version: int = PROFILE_VERSION

    def axis_alpha_beta(self) -> tuple[tuple[str, float, float], ...]:
        """The ``(axis, α, β)`` tuple ``costmodel.Profile.axis_profiles``
        carries (hashable, ordered by axis name)."""
        return tuple(
            (a, p.alpha_s, p.beta_bytes_per_s)
            for a, p in sorted(self.axes.items())
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "version": self.version,
            "platform": self.platform,
            "mesh_axes": list(self.mesh_axes),
            "mesh_shape": list(self.mesh_shape),
            "axes": {a: p.to_dict() for a, p in sorted(self.axes.items())},
            "measurements": list(self.measurements),
            "created_unix": self.created_unix,
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "CommProfile":
        v = int(d.get("version", -1))
        if v != PROFILE_VERSION:
            raise ValueError(
                f"comm profile version {v} != supported {PROFILE_VERSION}; "
                f"re-run the calibration ladder (scripts/commscope.py)"
            )
        return cls(
            platform=str(d["platform"]),
            mesh_axes=tuple(d["mesh_axes"]),
            mesh_shape=tuple(int(s) for s in d["mesh_shape"]),
            axes={
                a: AxisProfile.from_dict(p) for a, p in d["axes"].items()
            },
            measurements=list(d.get("measurements", [])),
            created_unix=float(d.get("created_unix", 0.0)),
            version=v,
        )

    def default_path(self) -> pathlib.Path:
        shape = "x".join(str(s) for s in self.mesh_shape)
        return PROFILE_DIR / f"comm_profile_{self.platform}_{shape}.json"

    def save(self, path: pathlib.Path | str | None = None) -> pathlib.Path:
        path = pathlib.Path(path) if path is not None else self.default_path()
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.to_dict(), indent=2, sort_keys=True)
                        + "\n")
        return path

    @classmethod
    def load(cls, path: pathlib.Path | str) -> "CommProfile":
        with open(path) as f:
            return cls.from_dict(json.load(f))


def fit_profile(
    mesh,
    measurements: Sequence[Mapping[str, Any]],
    *,
    platform: str | None = None,
    keep_measurements: bool = True,
    created_unix: float = 0.0,
) -> CommProfile:
    """Fit a :class:`CommProfile` from ladder records on ``mesh``."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    return CommProfile(
        platform=str(platform),
        mesh_axes=tuple(mesh.axis_names),
        mesh_shape=tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        axes=fit_axis_profiles(measurements),
        measurements=[dict(m) for m in measurements]
        if keep_measurements else [],
        created_unix=created_unix,
    )


def calibrate_mesh(mesh, **ladder_kwargs) -> CommProfile:
    """Run the ladder and fit in one call — the whole instrument."""
    created = ladder_kwargs.pop("created_unix", 0.0)
    ms = run_ladder(mesh, **ladder_kwargs)
    return fit_profile(mesh, ms, created_unix=created)


# --- attribution -----------------------------------------------------------


def attribute_measured_seconds(
    line_predictions: Mapping[str, float],
    measured_s: float,
) -> dict[str, dict[str, float]]:
    """Distribute measured comm wall-clock across source lines
    proportionally to each line's predicted collective seconds.

    Pure algebra, pinned in tests: two collectives sharing one line pool
    into one key (callers sum their predictions before calling); if every
    prediction is zero the measured total splits evenly so no second is
    dropped; Σ measured_s over lines == ``measured_s`` exactly.
    """
    preds = {k: max(0.0, float(v)) for k, v in line_predictions.items()}
    total = sum(preds.values())
    out: dict[str, dict[str, float]] = {}
    n = len(preds)
    for line, p in preds.items():
        share = p / total if total > 0 else (1.0 / n if n else 0.0)
        out[line] = {
            "predicted_s": p,
            "measured_s": measured_s * share,
        }
    return out


def line_comm_predictions(
    report,
    profile,
    mesh_sizes: Mapping[str, int] | None = None,
) -> dict[str, float]:
    """Predicted collective seconds per source line for one shardflow
    report, priced with ``profile`` (α–β aware once calibrated)."""
    from ..analysis import costmodel

    if mesh_sizes is None:
        mesh_sizes = dict(zip(report.mesh_axes, report.mesh_shape))
    out: dict[str, float] = {}
    for ev in report.events:
        out[ev.where] = out.get(ev.where, 0.0) + costmodel.price_event(
            ev, profile, mesh_sizes)
    return out


def line_report(
    report,
    profile,
    measured_comm_s: float,
    *,
    mesh_sizes: Mapping[str, int] | None = None,
) -> list[dict[str, Any]]:
    """Per-source-line predicted-vs-measured rows for one program,
    sorted by predicted seconds descending — the table
    ``explain_collectives(measured=True)`` and ``shardcheck --comm``
    print."""
    preds = line_comm_predictions(report, profile, mesh_sizes)
    attr = attribute_measured_seconds(preds, measured_comm_s)
    ops: dict[str, list[str]] = {}
    for ev in report.events:
        for op, ax in ev.realizations[:1]:
            ops.setdefault(ev.where, []).append(
                f"{op}@{'+'.join(ev.axes) or '-'}")
    rows = [
        {
            "where": line,
            "ops": sorted(set(ops.get(line, []))),
            "predicted_s": a["predicted_s"],
            "measured_s": a["measured_s"],
        }
        for line, a in attr.items()
    ]
    rows.sort(key=lambda r: (-r["predicted_s"], r["where"]))
    return rows


def axis_comm_shares(
    report,
    profile,
    mesh_sizes: Mapping[str, int] | None = None,
) -> dict[str, float]:
    """Fraction of a program's predicted comm seconds per axis label
    (multi-axis collectives label as ``a+b``) — the split used to book
    ``comm_exposed_seconds_total{family,axis}``. Sums to 1 when any comm
    is predicted."""
    from ..analysis import costmodel

    if mesh_sizes is None:
        mesh_sizes = dict(zip(report.mesh_axes, report.mesh_shape))
    per_axis: dict[str, float] = {}
    for ev in report.events:
        label = "+".join(ev.axes) or "-"
        per_axis[label] = per_axis.get(label, 0.0) + costmodel.price_event(
            ev, profile, mesh_sizes)
    total = sum(per_axis.values())
    if total <= 0:
        return {}
    return {a: s / total for a, s in per_axis.items()}


# --- overlap decomposition -------------------------------------------------


def decompose_overlap(
    device_s: float,
    predicted_compute_s: float,
    predicted_comm_s: float,
) -> dict[str, Any]:
    """Split measured device seconds into compute / exposed-comm /
    overlapped-comm, using predicted serial compute ``C`` and predicted
    comm ``K`` as the lens on measured ``D``.

    By construction the three parts sum back to ``D`` exactly in every
    branch (model error is absorbed into the compute part, never
    invented as comm):

    * ``exposed``    = clamp(D − C, 0, K) — comm visible past compute;
    * ``overlapped`` = min(K − exposed, D − exposed) — comm hidden under
      compute, bounded by remaining device time;
    * ``compute``    = D − exposed − overlapped (≥ 0).

    ``realized_overlap_ratio`` = overlapped / K, or None when no comm
    was predicted — the signal ROADMAP item 4's hierarchy-aware pricing
    calibrates against.
    """
    d = max(0.0, float(device_s))
    c = max(0.0, float(predicted_compute_s))
    k = max(0.0, float(predicted_comm_s))
    exposed = min(max(0.0, d - c), k)
    overlapped = max(0.0, min(k - exposed, d - exposed))
    compute = d - exposed - overlapped
    return {
        "compute_s": compute,
        "exposed_comm_s": exposed,
        "overlapped_comm_s": overlapped,
        "realized_overlap_ratio": (overlapped / k) if k > 0 else None,
    }


# --- registry export -------------------------------------------------------


def export_profile_gauges(registry, profile: CommProfile) -> None:
    """Publish fitted per-axis bandwidth into the Prometheus/fleet-merge
    path as ``comm_axis_bandwidth_bytes_per_s{axis="..."}`` gauges."""
    for axis, ap in sorted(profile.axes.items()):
        registry.gauge(
            f'comm_axis_bandwidth_bytes_per_s{{axis="{axis}"}}',
            "measured ring bandwidth from the commscope α–β fit",
        ).set(ap.beta_bytes_per_s)
        registry.gauge(
            f'comm_axis_alpha_seconds{{axis="{axis}"}}',
            "measured per-collective latency from the commscope α–β fit",
        ).set(ap.alpha_s)


def export_exposed_gauges(
    registry,
    family: str,
    exposed_s: float,
    axis_shares: Mapping[str, float],
    *,
    metric: str = "comm_exposed_seconds_total",
) -> None:
    """Publish a family's window exposed-comm seconds, split across axes
    by predicted comm share, as ``{family,axis}``-labeled gauges."""
    shares = dict(axis_shares) or {"-": 1.0}
    for axis, share in sorted(shares.items()):
        registry.gauge(
            f'{metric}{{family="{family}",axis="{axis}"}}',
            "window exposed (non-overlapped) collective seconds",
        ).set(exposed_s * share)
