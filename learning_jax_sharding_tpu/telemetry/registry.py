"""Metrics registry: counters, gauges, fixed-bucket histograms.

One process-wide place for every numeric the stack emits — the serving
engine's admission/page-pool/acceptance counters, the training loop's
step timings, compile accounting — exported two ways:

* :meth:`MetricsRegistry.snapshot` — a JSON-able dict (the
  ``BENCH_r{N}.json`` / run-report style);
* :meth:`MetricsRegistry.prometheus_text` — Prometheus text exposition
  (version 0.0.4), so a scrape endpoint is one ``http.server`` handler
  away.

Deliberately dependency-free and small: three metric kinds, get-or-create
by name, thread-safe. Percentile-grade latency numbers stay sample-based
where exactness is pinned (``ContinuousEngine.latency_stats``); the
histograms here carry the same observations in fixed buckets for export,
where bucket resolution is the accepted trade.
"""

from __future__ import annotations

import bisect
import json
import math
import threading
from typing import Sequence

#: Default histogram upper bounds (seconds-flavoured, Prometheus-style).
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, 30.0,
)


class Counter:
    """Monotonically increasing value. ``inc`` with a negative amount
    raises — a counter that goes down is a gauge."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc {amount})"
            )
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value, plus a high-water mark (max value seen since
    the last :meth:`reset_high_water`) — the page-pool/queue-depth shape
    of measurement, where the peak inside a window matters as much as the
    current value."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._high_water = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value
            if value > self._high_water:
                self._high_water = value

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount
            if self._value > self._high_water:
                self._high_water = self._value

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    @property
    def high_water(self) -> float:
        return self._high_water

    def reset_high_water(self) -> None:
        with self._lock:
            self._high_water = self._value


class Histogram:
    """Fixed-bucket histogram: cumulative bucket counts (Prometheus
    ``le`` semantics), sum, and count. Buckets are chosen at creation and
    never resize — snapshots are O(buckets), observation is O(log
    buckets)."""

    kind = "histogram"

    def __init__(
        self, name: str, help: str = "",
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.name = name
        self.help = help
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)   # [+Inf] overflow last
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> list[tuple[float, int]]:
        """[(upper_bound, cumulative_count)] ending with (+inf, count)."""
        out, running = [], 0
        for ub, c in zip(self.buckets, self._counts):
            running += c
            out.append((ub, running))
        out.append((math.inf, self._count))
        return out


class MetricsRegistry:
    """Get-or-create metric store. Re-requesting a name returns the same
    object; requesting it as a different kind (or a histogram with
    different buckets) raises — silent double-registration is how two
    subsystems end up fighting over one counter."""

    def __init__(self):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kwargs)
                self._metrics[name] = m
                return m
        if not isinstance(m, cls):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested as {cls.kind}"
            )
        if kwargs.get("buckets") is not None and tuple(
            sorted(float(b) for b in kwargs["buckets"])
        ) != m.buckets:
            raise ValueError(
                f"histogram {name!r} already registered with different "
                f"buckets"
            )
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "",
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str):
        return self._metrics.get(name)

    def metrics(self) -> list:
        return list(self._metrics.values())

    # --- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able dump: counters/gauges as numbers, gauges' high-water
        alongside, histograms as {buckets, counts, sum, count}."""
        out: dict = {}
        for m in self.metrics():
            if m.kind == "counter":
                out[m.name] = m.value
            elif m.kind == "gauge":
                out[m.name] = m.value
                out[m.name + "__high_water"] = m.high_water
            else:
                out[m.name] = {
                    "buckets": list(m.buckets),
                    "counts": [c for _, c in m.cumulative()],
                    "sum": m.sum,
                    "count": m.count,
                }
        return out

    def dump_json(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=2, sort_keys=True)

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4.

        Metric names may carry an inline label set
        (``'ledger_seconds_total{bucket="device"}'`` — the goodput
        ledger and per-stage trace histograms register one metric per
        label value): every such series is grouped under its FAMILY name
        (labels stripped) with ONE ``# TYPE``/``# HELP`` header, as the
        exposition format requires — a per-series header with braces in
        the metric name would be malformed.
        """
        import re

        fmt = _fmt_value

        def parsed(name):
            # re.S: a raw (pre-escaping) newline inside a label value
            # must not crash the exporter — it degrades to an odd line,
            # escape_label_value at construction is the real fix.
            m = re.match(r"([^{]+?)(\{.*\})?$", name, re.S)
            return m.group(1), m.group(2) or ""

        fams: dict[str, list] = {}
        order: list[str] = []
        for m in self.metrics():
            fam, labels = parsed(m.name)
            if fam not in fams:
                fams[fam] = []
                order.append(fam)
            fams[fam].append((m, labels))
        lines = []
        for fam in order:
            members = fams[fam]
            help_text = next((m.help for m, _ in members if m.help), "")
            if help_text:
                lines.append(f"# HELP {fam} {help_text}")
            lines.append(f"# TYPE {fam} {members[0][0].kind}")
            for m, labels in members:
                if m.kind in ("counter", "gauge"):
                    lines.append(f"{fam}{labels} {fmt(m.value)}")
                else:
                    inner = labels[1:-1] + "," if labels else ""
                    for ub, c in m.cumulative():
                        lines.append(
                            f'{fam}_bucket{{{inner}le="{fmt(ub)}"}} {c}'
                        )
                    lines.append(f"{fam}_sum{labels} {fmt(m.sum)}")
                    lines.append(f"{fam}_count{labels} {m.count}")
        return "\n".join(lines) + "\n"

    def dump_prometheus(self, path) -> None:
        with open(path, "w") as f:
            f.write(self.prometheus_text())


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def escape_label_value(value) -> str:
    """Prometheus text-exposition label-value escaping: backslash,
    double-quote, and newline must be escaped or the exposition line is
    corrupt (a tenant named ``evil"} 1`` would otherwise terminate the
    label set early and smuggle a fake sample). Escape at CONSTRUCTION
    time — label sets live inside metric NAMES here, and a retro-escape
    at render time could not tell an escaped backslash from a raw one."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def labeled_name(family: str, **labels) -> str:
    """Compose a metric name carrying an inline Prometheus label set —
    ``labeled_name("slo_e2e_burn_rate", tenant='a"b')`` →
    ``'slo_e2e_burn_rate{tenant="a\\"b"}'`` — with every value escaped
    via :func:`escape_label_value`. The one sanctioned way to build
    labeled series from UNTRUSTED strings (tenant ids, adapter names);
    the fixed internal labels (ledger buckets, trace stages) predate it
    and are trusted literals."""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items())
    )
    return f"{family}{{{inner}}}" if inner else family


def snapshot_prometheus_text(snapshot: dict) -> str:
    """Prometheus text exposition (0.0.4) for a SNAPSHOT dict — the
    registry-independent renderer (round 11).

    Accepts what :meth:`MetricsRegistry.snapshot` and
    ``parallel.multihost.merge_registry_snapshots`` produce, including
    LABELED keys (``'name{replica="x"}'``) from a labeled fleet merge —
    those render as real Prometheus labels, so one scrape carries the
    fleet sums and the per-replica series side by side. A snapshot
    cannot tell counters from gauges, so scalars render untyped;
    histogram dicts render as ``_bucket``/``_sum``/``_count`` series
    (the snapshot's counts are already cumulative, +Inf last); gauge
    ``__high_water`` companions render as ``<name>_high_water``.
    """
    import re

    def parsed(key):
        m = re.match(r"([^{]+?)(\{.*\})?$", key, re.S)
        name, labels = m.group(1), m.group(2) or ""
        if name.endswith("__high_water"):
            name = name[: -len("__high_water")] + "_high_water"
        return name, labels

    lines = []
    # Group by the RENDERED family name (labels stripped, high-water
    # normalized), not the raw key: the exposition format requires every
    # series of one metric in ONE contiguous group, and a raw-key sort
    # would split a family around its labeled variants ('{' sorts after
    # '_') and interleave 'name_high_water' between them.
    for key in sorted(snapshot, key=lambda k: parsed(k)):
        v = snapshot[key]
        name, labels = parsed(key)
        if isinstance(v, dict):
            inner = labels[1:-1] + "," if labels else ""
            for ub, c in zip(
                list(v["buckets"]) + [math.inf], v["counts"]
            ):
                lines.append(
                    f'{name}_bucket{{{inner}le="{_fmt_value(ub)}"}} {c}'
                )
            lines.append(f"{name}_sum{labels} {_fmt_value(v['sum'])}")
            lines.append(f"{name}_count{labels} {v['count']}")
        else:
            lines.append(f"{name}{labels} {_fmt_value(v)}")
    return "\n".join(lines) + "\n"


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry — subsystems that are not handed one
    explicitly meter here."""
    return _DEFAULT
