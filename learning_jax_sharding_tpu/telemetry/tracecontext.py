"""Request-scoped trace context that survives every fleet hop.

A request served by the round-11 fleet touches up to four processes'
worth of machinery — router admission, a prefill replica, a cross-mesh
KV handoff, a decode replica — and may additionally be REROUTED after a
replica death (round 11) or recomputed under a weight-swap preemption
(round 12). Until now each engine timed its own slice and the joins were
lost. This module is the join: a trace id is MINTED ONCE at
``FleetRouter.add_request`` (or lazily by a solo engine) and every
subsequent hop appends spans to the same record, so each retired request
yields

* a **critical-path decomposition** — queue → prefill → handoff →
  decode, with ``stall`` as the remainder the named stages cannot cover
  (requeue gaps, swap drains, rerouted recompute) and ``wasted`` as the
  work thrown away by failovers;
* per-stage histograms in the owning registry
  (``trace_stage_seconds{stage="queue"}`` …), rendered/merged by the
  labeled-registry plumbing like every other fleet metric;
* one merged **Perfetto timeline**: each replica is a ``pid`` (its own
  named process track), each request a ``tid`` row, swap pins and
  reroutes instant markers on the affected trace.

Timestamps are raw ``perf_counter`` values — the one clock the
engine's request stamps (``arrival_t``/``admit_t``/…) already use — so
producers hand their existing stamps straight to :meth:`TraceStore.leg`
and cross-replica spans line up without a rebase. :func:`merge_tracers`
applies the same trick to whole engine ``Tracer`` rings (each keeps
``ts`` relative to its own construction; merging rebases onto the
earliest) for the full-detail per-replica dispatch tracks.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Iterable, Optional

#: The named critical-path stages, in journey order. ``stall`` is the
#: derived remainder; anything else a producer invents rides along.
STAGES = ("queue", "prefill", "handoff", "decode")


class TraceStore:
    """The fleet-wide (or engine-local) trace join point.

    One store per routing domain: the ``FleetRouter`` owns one and
    attaches it to every replica engine (``engine.trace_sink``); a solo
    engine given a store mints ids itself on first sight of a request.
    Keyed by ``rid`` — rids are unique within a domain and survive
    reroutes/requeues by design (the failover contract), which is
    exactly what makes the trace id stable across hops.

    ``auto_complete`` (default True, for solo engines): the engine
    finalizes a trace when it retires the request. The router sets it
    False and calls :meth:`complete` itself at ``_finish`` — in a
    disaggregated fleet the prefill replica also "retires" its one-token
    pass, which must append legs, not close the trace.
    """

    def __init__(
        self,
        *,
        registry: Any | None = None,
        auto_complete: bool = True,
        max_done: int = 4096,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self._clock = clock
        self._registry = registry
        self.auto_complete = auto_complete
        self._next = 0
        self._t0 = clock()
        self._recs: dict[Any, dict] = {}
        self._max_done = max_done
        self._done_order: list[Any] = []
        # Histogram handles cached at first completion: the registry's
        # get-or-create re-validates bucket edges per call, which at one
        # call per stage per retire is real money on the telemetry
        # budget perf_goodput.py pins.
        self._hists: dict[str, Any] = {}

    def _hist(self, key: str, name: str, help: str):
        h = self._hists.get(key)
        if h is None:
            h = self._registry.histogram(name, help)
            self._hists[key] = h
        return h

    # --- minting -----------------------------------------------------------

    def mint(
        self, rid: Any, *, arrival_t: float | None = None,
        tenant: str | None = None,
    ) -> str:
        """Mint (or return the existing) trace id for ``rid``.
        ``tenant`` labels the whole journey (cost attribution, tenant
        lanes in the Chrome export); like ``arrival_t`` it backfills an
        implicit mint — the router's canonical stamp wins either way."""
        rec = self._recs.get(rid)
        if rec is None:
            self._next += 1
            rec = {
                "trace_id": f"trace-{self._next:05d}",
                "rid": rid,
                "arrival_t": arrival_t,
                "tenant": tenant,
                "spans": [],
                "events": [],
                "done": False,
                "status": None,
                "finish_t": None,
            }
            self._recs[rid] = rec
        if arrival_t is not None and rec["arrival_t"] is None:
            rec["arrival_t"] = arrival_t
        if tenant is not None and rec.get("tenant") is None:
            rec["tenant"] = tenant
        return rec["trace_id"]

    def trace_of(self, rid: Any) -> str | None:
        rec = self._recs.get(rid)
        return rec["trace_id"] if rec else None

    def rids(self) -> list:
        return list(self._recs)

    # --- recording ---------------------------------------------------------

    def leg(
        self,
        rid: Any,
        stage: str,
        t0: float,
        t1: float,
        *,
        replica: str | None = None,
        **attrs: Any,
    ) -> None:
        """Append one span of the request's journey. ``t0``/``t1`` are
        raw ``perf_counter`` stamps; zero-length and clock-skewed legs
        are clipped to non-negative. Unknown rids mint implicitly (the
        solo-engine path)."""
        self.mint(rid)
        self._recs[rid]["spans"].append({
            "stage": stage,
            "t0": t0,
            "t1": max(t0, t1),
            "replica": replica,
            "attrs": attrs,
        })

    def instant(
        self,
        rid: Any,
        name: str,
        *,
        t: float | None = None,
        replica: str | None = None,
        **attrs: Any,
    ) -> None:
        """A point event on the trace (swap version pin, reroute,
        deadline sweep...)."""
        self.mint(rid)
        self._recs[rid]["events"].append({
            "name": name,
            "t": self._clock() if t is None else t,
            "replica": replica,
            "attrs": attrs,
        })

    def complete(
        self,
        rid: Any,
        *,
        status: str = "ok",
        finish_t: float | None = None,
    ) -> dict | None:
        """Close the trace: stamp status/finish, fold the critical path
        into the registry histograms. Idempotent — the first close wins
        (a late duplicate retire must not double-observe)."""
        rec = self._recs.get(rid)
        if rec is None or rec["done"]:
            return rec
        rec["done"] = True
        rec["status"] = status
        rec["finish_t"] = self._clock() if finish_t is None else finish_t
        self._done_order.append(rid)
        cp = self.critical_path(rid)
        if self._registry is not None and cp is not None:
            for stage in (*STAGES, "stall"):
                self._hist(
                    stage,
                    f'trace_stage_seconds{{stage="{stage}"}}',
                    "per-request critical-path seconds by stage",
                ).observe(cp["stages"].get(stage, 0.0))
            if cp["ttft_s"] is not None:
                self._hist(
                    "ttft", "trace_ttft_seconds",
                    "trace-derived time to first token",
                ).observe(cp["ttft_s"])
            self._hist(
                "e2e", "trace_e2e_seconds",
                "trace-derived end-to-end latency",
            ).observe(cp["e2e_s"])
        # Bound memory like every other ring in the stack: the OLDEST
        # finished traces age out, live ones never do.
        while len(self._done_order) > self._max_done:
            old = self._done_order.pop(0)
            self._recs.pop(old, None)
        return rec

    # --- analysis ----------------------------------------------------------

    def critical_path(self, rid: Any) -> dict | None:
        """The per-request decomposition. Stage seconds count only legs
        that WEREN'T thrown away (``wasted=True`` legs — a dead
        replica's partial compute — sum separately); ``stall`` is the
        e2e remainder no named stage covers: requeue gaps, swap drains,
        and that same wasted work as the user experienced it."""
        rec = self._recs.get(rid)
        if rec is None:
            return None
        spans = sorted(rec["spans"], key=lambda s: s["t0"])
        t_first = min((s["t0"] for s in spans), default=None)
        arrival = rec["arrival_t"] if rec["arrival_t"] is not None else t_first
        finish = rec["finish_t"]
        if finish is None:
            finish = max((s["t1"] for s in spans), default=arrival)
        stages: dict[str, float] = {}
        wasted = 0.0
        ttft = None
        for s in spans:
            dur = s["t1"] - s["t0"]
            if s["attrs"].get("wasted"):
                wasted += dur
                continue
            stages[s["stage"]] = stages.get(s["stage"], 0.0) + dur
            if s["stage"] == "prefill" and s["attrs"].get("first_token_t"):
                t = s["attrs"]["first_token_t"] - arrival
                ttft = t if ttft is None else min(ttft, t)
        e2e = max(0.0, (finish - arrival)) if arrival is not None else 0.0
        named = sum(stages.get(st, 0.0) for st in STAGES)
        stages["stall"] = max(0.0, e2e - named)
        return {
            "trace_id": rec["trace_id"],
            "rid": rid,
            "tenant": rec.get("tenant"),
            "status": rec["status"],
            "e2e_s": e2e,
            "ttft_s": ttft,
            "stages": stages,
            "wasted_s": wasted,
            "legs": len(spans),
            "reroutes": sum(
                1 for e in rec["events"] if e["name"] == "reroute"
            ),
            "swap_pins": [
                e["attrs"].get("version") for e in rec["events"]
                if e["name"] == "swap_pin"
            ],
        }

    def completed(self) -> list[dict]:
        """Critical paths of every completed trace, completion order."""
        out = []
        for rid in self._done_order:
            cp = self.critical_path(rid)
            if cp is not None:
                out.append(cp)
        return out

    def record(self, rid: Any) -> dict | None:
        """The raw trace record (spans + instants) — test/debug access."""
        return self._recs.get(rid)

    # --- export ------------------------------------------------------------

    def chrome_trace(self) -> dict:
        """One Perfetto timeline over every replica the store saw:
        replicas become named process tracks (``pid`` + process_name
        metadata), requests become ``tid`` rows within them, instants
        render as markers. Traces carrying a ``tenant`` label (round
        20) additionally mirror onto per-tenant process lanes AFTER the
        replica pids — "what did tenant X's traffic do, across every
        replica it touched" as one track; a tenant-less store emits
        exactly the pre-tenant document. Load at
        https://ui.perfetto.dev."""
        replicas: list[str] = []
        for rec in self._recs.values():
            for s in rec["spans"]:
                r = s["replica"] or "fleet"
                if r not in replicas:
                    replicas.append(r)
            for e in rec["events"]:
                r = e["replica"] or "fleet"
                if r not in replicas:
                    replicas.append(r)
        replicas.sort()
        pid_of = {r: i + 1 for i, r in enumerate(replicas)}
        tenants = sorted({
            rec["tenant"] for rec in self._recs.values()
            if rec.get("tenant")
        })
        tenant_pid = {
            t: len(replicas) + 1 + i for i, t in enumerate(tenants)
        }
        events: list[dict] = [
            {
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": f"replica {r}" if r != "fleet" else "fleet"},
            }
            for r, pid in pid_of.items()
        ] + [
            {
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": f"tenant {t}"},
            }
            for t, pid in tenant_pid.items()
        ]
        base = self._t0
        for rec in self._recs.values():
            tid = rec["rid"] if isinstance(rec["rid"], int) else (
                abs(hash(rec["rid"])) % 10_000
            )
            lane = tenant_pid.get(rec.get("tenant"))
            for s in rec["spans"]:
                ev = {
                    "name": s["stage"],
                    "ph": "X",
                    "ts": (s["t0"] - base) * 1e6,
                    "dur": (s["t1"] - s["t0"]) * 1e6,
                    "pid": pid_of[s["replica"] or "fleet"],
                    "tid": tid,
                    "args": {
                        "trace_id": rec["trace_id"], **s["attrs"],
                    },
                }
                events.append(ev)
                if lane is not None:
                    events.append({
                        **ev, "pid": lane,
                        "args": {
                            **ev["args"],
                            "replica": s["replica"] or "fleet",
                        },
                    })
            for e in rec["events"]:
                ev = {
                    "name": e["name"],
                    "ph": "i",
                    "s": "t",
                    "ts": (e["t"] - base) * 1e6,
                    "pid": pid_of[e["replica"] or "fleet"],
                    "tid": tid,
                    "args": {
                        "trace_id": rec["trace_id"], **e["attrs"],
                    },
                }
                events.append(ev)
                if lane is not None:
                    events.append({
                        **ev, "pid": lane,
                        "args": {
                            **ev["args"],
                            "replica": e["replica"] or "fleet",
                        },
                    })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"traces": len(self._recs)},
        }

    def dump_chrome_trace(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)


def merge_tracers(
    tracers: dict[str, Any], *, extra_events: Iterable[dict] = (),
) -> dict:
    """Merge per-replica engine ``Tracer`` rings into one Perfetto trace.

    Each ``Tracer``'s event ``ts`` is microseconds since ITS OWN
    construction; merging rebases every ring onto the earliest tracer's
    epoch and assigns one ``pid`` (with a process_name metadata row) per
    replica, so the fleet's dispatch-level detail lands on the same
    timeline the :class:`TraceStore` request tracks use. ``extra_events``
    (e.g. ``TraceStore.chrome_trace()["traceEvents"]`` rebased by the
    caller, or anything already on the merged epoch) append verbatim.
    """
    t0s = {
        name: getattr(tr, "_t0", 0.0) for name, tr in tracers.items()
    }
    base = min(t0s.values(), default=0.0)
    events: list[dict] = []
    for i, (name, tr) in enumerate(sorted(tracers.items())):
        pid = i + 1
        # Deterministic track identity: pid from the sorted replica-name
        # order, process_sort_index matching it, and the tracer's own
        # metadata rows (process/thread names; tids are the tracer's
        # small first-seen indexes, not raw thread idents) — so the
        # merged fleet timeline sorts identically across runs in
        # Perfetto instead of interleaving by OS-assigned ids.
        meta = getattr(tr, "metadata_events", None)
        if meta is not None:
            rows = meta(pid=pid)
            for row in rows:
                if row["name"] == "process_name":
                    row["args"] = dict(row["args"], name=f"replica {name}")
            events.extend(rows)
        else:
            events.append({
                "name": "process_name", "ph": "M", "ts": 0.0, "pid": pid,
                "tid": 0, "args": {"name": f"replica {name}"},
            })
        events.append({
            "name": "process_sort_index", "ph": "M", "ts": 0.0, "pid": pid,
            "tid": 0, "args": {"sort_index": i},
        })
        off_us = (t0s[name] - base) * 1e6
        for ev in tr.events:
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = ev["ts"] + off_us
            events.append(ev)
    events.extend(extra_events)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"replicas": len(tracers), "epoch_perf_t0": base},
    }
