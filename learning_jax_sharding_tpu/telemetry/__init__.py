"""Unified telemetry (observability layer): spans, metrics, compile watch.

Three pillars, one import point:

* :mod:`~.telemetry.spans` — nested structured spans with explicit
  device-sync points, bridged into ``jax.profiler.TraceAnnotation``
  (XProf) and exported as Chrome trace-event JSON (Perfetto) + JSONL;
* :mod:`~.telemetry.registry` — counters / gauges / fixed-bucket
  histograms with JSON snapshot and Prometheus text exposition;
* :mod:`~.telemetry.compile_watch` — recompilation + compile-time
  accounting, per-executable FLOPs/bytes, and the per-step collective
  inventory.

Consumers: ``models.serving.ContinuousEngine`` (per-request span
timeline, queue/page-pool gauges, acceptance counters — its
``last_stats``/``last_latency`` are re-derived from the registry),
``training.loop.fit`` + ``utils.metrics.MetricsLogger`` (same registry),
``bench.py`` (compile-vs-steady-state phase breakdown), and
``cases/case18_observability.py`` (the end-to-end driver that dumps all
three artifact kinds).
"""

from learning_jax_sharding_tpu.telemetry.compile_watch import (  # noqa: F401
    CompileWatch,
    WatchedFunction,
    cache_size,
    executable_report,
    watched,
)
from learning_jax_sharding_tpu.telemetry.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from learning_jax_sharding_tpu.telemetry.spans import (  # noqa: F401
    Tracer,
    default_tracer,
    device_sync,
)
