"""Unified telemetry (observability layer): measurement + diagnosis.

Stage 1 (PR 1) — measurement, three pillars:

* :mod:`~.telemetry.spans` — nested structured spans with explicit
  device-sync points, bridged into ``jax.profiler.TraceAnnotation``
  (XProf) and exported as Chrome trace-event JSON (Perfetto) + JSONL;
* :mod:`~.telemetry.registry` — counters / gauges / fixed-bucket
  histograms with JSON snapshot and Prometheus text exposition;
* :mod:`~.telemetry.compile_watch` — recompilation + compile-time
  accounting, per-executable FLOPs/bytes, and the per-step collective
  inventory.

Stage 2 (PR 2) — diagnosis, four more:

* :mod:`~.telemetry.flight_recorder` — bounded ring of structured events
  (admissions, evictions, train steps, compiles, span closures) with a
  post-mortem ``dump()`` bundle on exception or demand;
* :mod:`~.telemetry.watchdog` — full-speed health probes: async on-device
  ``isfinite`` of loss/grad-norm, loss-spike EMA, a hang-flagging
  heartbeat thread, and NaN escalation via ``utils.profiling.checking``;
* :mod:`~.telemetry.devview` — per-device HBM watermarks vs the static
  ``MemoryPlan``, shard-imbalance audit, and per-mesh-axis collective
  byte attribution;
* :mod:`~.telemetry.slo` — streaming TTFT/TPOT/ITL/queue-wait percentile
  estimators and SLO targets with burn-rate counters, exported through
  the registry/Prometheus path;
* :mod:`~.telemetry.commscope` — the comm observatory: a calibration
  ladder of timed micro-collectives fitting per-axis α–β link profiles
  (persisted under ``analysis/profiles/``), per-source-line
  predicted-vs-measured collective attribution, and the compute /
  exposed-comm / overlapped-comm decomposition behind
  ``GoodputLedger.overlap_report``;
* :mod:`~.telemetry.economics` — round 20's workload observatory JOIN:
  per-tenant cost attribution over TraceStore critical paths ×
  GoodputLedger buckets × byte counters, with the tier-1-gated
  conservation invariant (Σ tenant device-seconds == fleet device
  bucket) and per-tenant SLO burn rates.

Consumers: ``models.serving.ContinuousEngine`` (per-request span
timeline, queue/page-pool gauges, SLO feed, flight-recorder lifecycle
events), ``training.loop.fit`` + ``utils.metrics.MetricsLogger`` (same
registry, watchdog probes), ``bench.py`` (compile-vs-steady-state phase
breakdown + the diagnosis block), and ``cases/case18_observability.py``
/ ``cases/case19_diagnosis.py`` (the end-to-end drivers).
"""

from learning_jax_sharding_tpu.telemetry.commscope import (  # noqa: F401
    AxisProfile,
    CommProfile,
    attribute_measured_seconds,
    calibrate_mesh,
    decompose_overlap,
    fit_alpha_beta,
    fit_axis_profiles,
    run_ladder,
)
from learning_jax_sharding_tpu.telemetry.compile_watch import (  # noqa: F401
    CompileWatch,
    WatchedFunction,
    cache_size,
    executable_report,
    watched,
)
from learning_jax_sharding_tpu.telemetry.devview import (  # noqa: F401
    axis_collective_volume,
    device_memory_stats,
    memory_report,
    shard_imbalance,
)
from learning_jax_sharding_tpu.telemetry.economics import (  # noqa: F401
    ATTRIBUTION_POLICY,
    OVERHEAD_TENANT,
    UNTAGGED_TENANT,
    CostRates,
    deterministic_view,
    fleet_economics,
    write_economics,
)
from learning_jax_sharding_tpu.telemetry.flight_recorder import (  # noqa: F401
    FlightRecorder,
    artifact_dir,
    default_flight_recorder,
)
from learning_jax_sharding_tpu.telemetry.ledger import (  # noqa: F401
    BUCKETS,
    GoodputLedger,
)
from learning_jax_sharding_tpu.telemetry.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    escape_label_value,
    labeled_name,
)
from learning_jax_sharding_tpu.telemetry.slo import (  # noqa: F401
    SLOMonitor,
    SLOTarget,
    StreamingPercentile,
)
from learning_jax_sharding_tpu.telemetry.spans import (  # noqa: F401
    Tracer,
    default_tracer,
    device_sync,
)
from learning_jax_sharding_tpu.telemetry.tracecontext import (  # noqa: F401
    STAGES,
    TraceStore,
    merge_tracers,
)
from learning_jax_sharding_tpu.telemetry.watchdog import (  # noqa: F401
    Heartbeat,
    NonFiniteError,
    Watchdog,
    localize_nan,
)
